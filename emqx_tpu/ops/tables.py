"""Host-side builder for the flattened match tables mirrored into HBM.

Plays the role of the reference's route/trie mutation path
(`apps/emqx/src/emqx_router.erl:106-123`, `emqx_trie.erl:115-120`) but
produces fixed-shape arrays:

* an open-addressed hash table (``key_a``/``key_b``/``val``) over filter
  pattern hashes, probe window ``PROBE`` slots, load factor <= 1/2;
* a dense descriptor block for the distinct wildcard shapes present
  (``incl``/``k_a``/``k_b``/``min_len``/``max_len``/``wild_root``/``valid``).

All mutations are applied to the numpy mirror *and* recorded as deltas so the
engine can scatter them into the device copy without re-uploading the table
(the churn requirement: BASELINE.json config #5, 5%/sec subscribe/unsubscribe).
Capacity growth doubles the table and invalidates the device mirror (rare,
amortized) — the analog of the reference's transactional trie rebuild.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .hashing import HashSpace, Shape

PROBE = 8  # fixed probe window; every key lives within PROBE slots of home
MAX_LOG2CAP = 30  # growth guard: past this, growth can't be the fix
_U32 = 0xFFFFFFFF
_MIX1 = 0x85EBCA77
_MIX2 = 0x9E3779B1


def bucket_of(ha: int, hb: int, log2cap: int) -> int:
    """Home slot for a key — must match the device computation bit-for-bit."""
    m = (ha + hb * _MIX1) & _U32
    return ((m * _MIX2) & _U32) >> (32 - log2cap)


class GrowNeeded(Exception):
    """Raised when an insert cannot be placed; caller must grow()."""


@dataclass
class Delta:
    """Pending device-mirror updates since the last drain."""

    slots: List[int] = field(default_factory=list)
    key_a: List[int] = field(default_factory=list)
    key_b: List[int] = field(default_factory=list)
    val: List[int] = field(default_factory=list)
    desc_dirty: bool = False  # descriptor block changed (tiny; re-upload whole)
    rebuilt: bool = False  # table arrays replaced wholesale

    def empty(self) -> bool:
        return not self.slots and not self.desc_dirty and not self.rebuilt

    def compressed(self) -> "Delta":
        """Last-write-wins per slot.

        A delete + reinsert of the same slot between device syncs must not
        reach the scatter as duplicate indices (jax .at[].set application
        order is undefined for duplicates).
        """
        if len(set(self.slots)) == len(self.slots):
            return self
        last: Dict[int, int] = {s: i for i, s in enumerate(self.slots)}
        keep = sorted(last.values())
        return Delta(
            slots=[self.slots[i] for i in keep],
            key_a=[self.key_a[i] for i in keep],
            key_b=[self.key_b[i] for i in keep],
            val=[self.val[i] for i in keep],
            desc_dirty=self.desc_dirty,
            rebuilt=self.rebuilt,
        )

    def split(self, n: int) -> Tuple["Delta", "Delta"]:
        """(head, tail): the first n slot-writes and the remainder.

        The desc/rebuild flags ride the HEAD (they are tiny or handled
        wholesale by sync); callers apply head before tail so the
        slot-write order — and compressed()'s last-write-wins — holds."""
        head = Delta(
            slots=self.slots[:n], key_a=self.key_a[:n],
            key_b=self.key_b[:n], val=self.val[:n],
            desc_dirty=self.desc_dirty, rebuilt=self.rebuilt,
        )
        tail = Delta(
            slots=self.slots[n:], key_a=self.key_a[n:],
            key_b=self.key_b[n:], val=self.val[n:],
        )
        return head, tail

    def merge(self, newer: "Delta") -> "Delta":
        """This delta's writes followed by `newer`'s (order preserved)."""
        return Delta(
            slots=self.slots + newer.slots,
            key_a=self.key_a + newer.key_a,
            key_b=self.key_b + newer.key_b,
            val=self.val + newer.val,
            desc_dirty=self.desc_dirty or newer.desc_dirty,
            rebuilt=self.rebuilt or newer.rebuilt,
        )


class MatchTables:
    """Numpy mirror of the device tables + incremental mutation log."""

    def __init__(
        self,
        space: Optional[HashSpace] = None,
        log2cap: int = 10,
        desc_cap: int = 32,
    ):
        # ---- concurrency contract (cross-thread lint annotations): the
        # tables have ONE mutator at a time — runtime churn is serialized
        # on the event loop (or the churn plane's serial fid phase), boot
        # restore runs on a to_thread worker before traffic (executor
        # join publishes the arrays).  Collect threads only READ, and a
        # mid-grow reference swap hands them the intact OLD array —
        # the benign-dirty-read model PR 6 documents.
        self.space = space or HashSpace()
        self.log2cap = log2cap  # analysis: owner=loop
        self.desc_cap = desc_cap  # analysis: owner=loop
        L = self.space.max_levels

        cap = 1 << log2cap
        self.key_a = np.zeros(cap, dtype=np.uint32)  # analysis: owner=loop
        self.key_b = np.zeros(cap, dtype=np.uint32)  # analysis: owner=loop
        self.val = np.full(cap, -1, dtype=np.int32)  # analysis: owner=loop

        self.incl = np.zeros((desc_cap, L), dtype=np.uint32)
        self.k_a = np.zeros(desc_cap, dtype=np.uint32)
        self.k_b = np.zeros(desc_cap, dtype=np.uint32)
        self.min_len = np.zeros(desc_cap, dtype=np.int32)
        self.max_len = np.zeros(desc_cap, dtype=np.int32)
        self.wild_root = np.zeros(desc_cap, dtype=bool)
        self.valid = np.zeros(desc_cap, dtype=bool)

        self.n_entries = 0  # analysis: owner=loop
        # shape -> (descriptor index, refcount)
        self._shapes: Dict[Shape, Tuple[int, int]] = {}
        self._free_desc: List[int] = list(range(desc_cap - 1, -1, -1))  # analysis: owner=loop
        self._desc_shape: List[Optional[Shape]] = [None] * desc_cap
        # per-fid entry bookkeeping as ARRAYS (a python dict of tuples
        # costs ~1 us/insert and ~150 B/entry at 10M routes — the former
        # round-3 insert bottleneck): key lanes + descriptor index, -1 =
        # absent, grown by doubling over the max fid seen
        self._ent_cap = 1024  # analysis: owner=loop
        self.ent_ha = np.zeros(self._ent_cap, dtype=np.uint32)
        self.ent_hb = np.zeros(self._ent_cap, dtype=np.uint32)
        self.ent_desc = np.full(self._ent_cap, -1, dtype=np.int32)
        self.delta = Delta()  # analysis: owner=loop

    # ------------------------------------------------------------- shapes

    def _shape_incl_row(self, shape: Shape) -> np.ndarray:
        L = self.space.max_levels
        row = np.zeros(L, dtype=np.uint32)
        for l in range(min(shape.plen, L)):
            if not (shape.plus_mask >> l & 1):
                row[l] = 1
        return row

    def _acquire_shape(self, shape: Shape) -> int:
        ent = self._shapes.get(shape)
        if ent is not None:
            idx, rc = ent
            self._shapes[shape] = (idx, rc + 1)
            return idx
        if not self._free_desc:
            raise GrowNeeded("descriptor block full")
        idx = self._free_desc.pop()
        self._desc_shape[idx] = shape
        ka, kb = self.space.shape_const(shape)
        self.incl[idx] = self._shape_incl_row(shape)
        self.k_a[idx] = ka
        self.k_b[idx] = kb
        self.min_len[idx] = shape.min_len()
        self.max_len[idx] = shape.max_len(self.space.max_levels)
        self.wild_root[idx] = shape.wild_root
        self.valid[idx] = True
        self._shapes[shape] = (idx, 1)
        self.delta.desc_dirty = True
        return idx

    def _release_shape(self, shape: Shape) -> None:
        idx, rc = self._shapes[shape]
        if rc > 1:
            self._shapes[shape] = (idx, rc - 1)
            return
        del self._shapes[shape]
        self.valid[idx] = False
        self._desc_shape[idx] = None
        self._free_desc.append(idx)
        self.delta.desc_dirty = True

    def _ensure_ent_cap(self, max_fid: int) -> None:
        if max_fid < self._ent_cap:
            return
        cap = self._ent_cap
        while cap <= max_fid:
            cap *= 2
        for name in ("ent_ha", "ent_hb", "ent_desc"):
            arr = getattr(self, name)
            new = np.full(cap, -1, dtype=arr.dtype) if name == "ent_desc" \
                else np.zeros(cap, dtype=arr.dtype)
            new[: self._ent_cap] = arr
            setattr(self, name, new)
        self._ent_cap = cap

    @property
    def n_shapes(self) -> int:
        return len(self._shapes)

    # ------------------------------------------------------------ entries

    def _place(self, ha: int, hb: int, fid: int) -> int:
        cap = 1 << self.log2cap
        home = bucket_of(ha, hb, self.log2cap)
        for off in range(PROBE):
            slot = (home + off) & (cap - 1)
            if self.val[slot] == -1:
                self.key_a[slot] = ha
                self.key_b[slot] = hb
                self.val[slot] = fid
                self.delta.slots.append(slot)
                self.delta.key_a.append(ha)
                self.delta.key_b.append(hb)
                self.delta.val.append(fid)
                return slot
        raise GrowNeeded("probe window exhausted")

    def _window_is_duplicates(self, ha: int, hb: int) -> bool:
        """True when the probe window is full of THIS key: growth rehashes
        them to the same home, so growing can never help — fail fast."""
        cap = 1 << self.log2cap
        home = bucket_of(ha, hb, self.log2cap)
        for off in range(PROBE):
            slot = (home + off) & (cap - 1)
            if not (self.val[slot] != -1 and self.key_a[slot] == ha
                    and self.key_b[slot] == hb):
                return False
        return True

    def insert(self, filter_words: Sequence[str], fid: int) -> None:
        """Insert filter with id `fid`. Grows tables automatically."""
        ha, hb, shape = self.space.filter_key(filter_words)
        while True:
            try:
                self._acquire_shape(shape)
                break
            except GrowNeeded:
                self._grow_desc()
        while True:
            try:
                self._place(ha, hb, fid)
                break
            except GrowNeeded:
                if self._window_is_duplicates(ha, hb):
                    raise RuntimeError(
                        "duplicate filter key inserted >%d times — callers "
                        "must refcount per unique filter (models/engine.py)"
                        % PROBE)
                self._grow_table()
        self._ensure_ent_cap(fid)
        self.ent_ha[fid] = ha
        self.ent_hb[fid] = hb
        self.ent_desc[fid] = self._shapes[shape][0]
        self.n_entries += 1
        if self.n_entries * 2 > (1 << self.log2cap):
            self._grow_table()

    def _register_batch(self, fids, ha, hb, plen, plus_mask, has_hash) -> None:
        """Shape + per-fid bookkeeping for a key batch, vectorized.

        Shapes are deduplicated on a single combined int64 key (axis-wise
        np.unique sorts rows ~10x slower); per-fid lanes/descriptors land
        in the entry arrays with two fancy-index stores."""
        combo = (
            plen.astype(np.int64)
            | (plus_mask.astype(np.int64) << 7)
            | (has_hash.astype(np.int64) << 43)
        )
        uniq, inv, counts = np.unique(
            combo, return_inverse=True, return_counts=True
        )
        desc_of = np.empty(len(uniq), dtype=np.int32)
        for j, key in enumerate(uniq.tolist()):
            shape = Shape(
                plen=int(key & 0x7F),
                plus_mask=int((key >> 7) & 0xFFFFFFFFF),
                has_hash=bool(key >> 43),
            )
            cnt = int(counts[j])
            ent = self._shapes.get(shape)
            if ent is not None:
                idx, rc = ent
                self._shapes[shape] = (idx, rc + cnt)
            else:
                while True:
                    try:
                        self._acquire_shape(shape)
                        break
                    except GrowNeeded:
                        self._grow_desc()
                idx, _one = self._shapes[shape]
                self._shapes[shape] = (idx, cnt)
            desc_of[j] = idx
        fid_arr = np.asarray(fids, dtype=np.int64)
        self._ensure_ent_cap(int(fid_arr.max()))
        self.ent_ha[fid_arr] = ha
        self.ent_hb[fid_arr] = hb
        self.ent_desc[fid_arr] = desc_of[inv]

    def bulk_insert(self, filters: Sequence[str], fids: Sequence[int]) -> None:
        """Insert many filters at once (route-table bootstrap / resync).

        Uses the native batch key computation + placement
        (native/matchhash.cc etpu_filter_keys/etpu_bulk_place) and a single
        device-mirror rebuild, instead of n Python-loop inserts — the bulk
        analog of the reference's transactional trie load.  Falls back to
        per-filter insert() when the native lib is absent or the batch is
        small enough that delta-tracking is cheaper than a rebuild.
        """
        from . import native

        n = len(filters)
        out = None
        if n >= 512:
            out = native.filter_keys(list(filters), self.space.max_levels,
                                     self.space)
        if out is None:
            for f, fid in zip(filters, fids):
                self.insert(f.split("/"), fid)
            return
        ha, hb, plen, plus_mask, has_hash = out
        self.bulk_insert_keys(fids, ha, hb, plen, plus_mask, has_hash)

    def bulk_insert_keys(self, fids, ha, hb, plen, plus_mask, has_hash) -> None:
        """bulk_insert for callers that already hold the native key batch
        (engine.add_filters computes keys once for dedup + deep routing +
        registry fill — recomputing them here would double the cost)."""
        self._register_batch(fids, ha, hb, plen, plus_mask, has_hash)
        self.n_entries += len(fids)
        while self.n_entries * 2 > (1 << self.log2cap):
            self.log2cap += 1
        self._rebuild(pending=(ha, hb, np.asarray(fids, dtype=np.int32)))

    def churn_insert(self, filters: Sequence[str], fids: Sequence[int],
                     words: Optional[Sequence[Sequence[str]]] = None) -> None:
        """Incremental batched insert for churn ticks.

        Unlike bulk_insert (which rebuilds the whole table — right for
        bootstrap, wrong for a 5%/s churn tick against 10M resident
        entries), this places the batch into the live arrays with the
        native open-addressing pass and appends the touched slots to the
        delta, so sync_device stays one small scatter.  Falls back to
        per-filter insert() without the native lib.
        """
        from . import native

        n = len(filters)
        if n == 0:
            return
        out = native.filter_keys(list(filters), self.space.max_levels,
                                 self.space)
        if out is None:
            ws = words or [f.split("/") for f in filters]
            for w, fid in zip(ws, fids):
                self.insert(w, fid)
            return
        ha, hb, plen, plus_mask, has_hash = out
        self.churn_insert_keys(fids, ha, hb, plen, plus_mask, has_hash)

    def churn_insert_keys(self, fids, ha, hb, plen, plus_mask, has_hash) -> None:
        """churn_insert for callers holding the native key batch."""
        from . import native

        n = len(fids)
        self._register_batch(fids, ha, hb, plen, plus_mask, has_hash)
        self.n_entries += n

        if self.n_entries * 2 > (1 << self.log2cap):
            # load factor crossed: one rebuild places everything
            # (entries above already include this batch)
            while self.n_entries * 2 > (1 << self.log2cap):
                self.log2cap += 1
            self._rebuild(pending=(ha, hb, np.asarray(fids, dtype=np.int32)))
            return

        fid_arr = np.asarray(fids, dtype=np.int32)
        placed = native.bulk_place_slots(
            self.key_a, self.key_b, self.val, self.log2cap, PROBE,
            ha, hb, fid_arr,
        )
        if placed is None:
            n_ok, slots = 0, np.zeros(0, dtype=np.int32)
        else:
            n_ok, slots = placed
        # .tolist() over genexprs: one C conversion pass per column
        self.delta.slots.extend(slots[:n_ok].tolist())
        self.delta.key_a.extend(ha[:n_ok].tolist())
        self.delta.key_b.extend(hb[:n_ok].tolist())
        self.delta.val.extend(fid_arr[:n_ok].tolist())
        if n_ok < n:
            # a probe window filled: grow + native rebuild covers the
            # remainder — NOT _grow_table, whose per-entry Python
            # re-place loop would stall for tens of seconds at 10M
            # resident entries.  The not-yet-placed tail rides the
            # rebuild's pending batch (the table itself is the entry
            # store, and [n_ok:] never made it in).
            self.log2cap += 1
            if self.log2cap > MAX_LOG2CAP:
                raise RuntimeError("match-table growth runaway")
            self._rebuild(pending=(ha[n_ok:], hb[n_ok:], fid_arr[n_ok:]))

    def delete_batch(self, fids: Sequence[int]) -> None:
        """Vectorized tombstoning for churn ticks: one numpy pass finds
        every entry's slot across its probe window instead of n Python
        probes; shape refcounts release grouped by shape."""
        n = len(fids)
        if n == 0:
            return
        if n < 32:  # below this the numpy overhead loses
            for fid in fids:
                self.delete(fid)
            return
        cap = 1 << self.log2cap
        farr = np.asarray(fids, dtype=np.int64)
        if (farr >= self._ent_cap).any():
            raise KeyError("filter id missing from table in delete_batch")
        ha = self.ent_ha[farr]
        hb = self.ent_hb[farr]
        descs = self.ent_desc[farr]
        if (descs < 0).any():  # pragma: no cover - bookkeeping
            raise KeyError("filter id missing from table in delete_batch")
        shape_counts: Dict[Shape, int] = {}
        for j, cnt in zip(*np.unique(descs, return_counts=True)):
            shape_counts[self._desc_shape[int(j)]] = int(cnt)
        self.ent_desc[farr] = -1
        farr = farr.astype(np.int32)
        mixed = (ha + hb * np.uint32(_MIX1)) * np.uint32(_MIX2)
        home = (mixed >> np.uint32(32 - self.log2cap)).astype(np.int64)
        windows = (home[:, None] + np.arange(PROBE)[None, :]) & (cap - 1)
        hit = (
            (self.val[windows] == farr[:, None])
            & (self.key_a[windows] == ha[:, None])
            & (self.key_b[windows] == hb[:, None])
        )
        if not hit.any(axis=1).all():  # pragma: no cover - bookkeeping
            raise KeyError("filter id missing from table in delete_batch")
        slots = windows[np.arange(n), hit.argmax(axis=1)]
        self.key_a[slots] = 0
        self.key_b[slots] = 0
        self.val[slots] = -1
        self.delta.slots.extend(slots.tolist())
        self.delta.key_a.extend([0] * n)
        self.delta.key_b.extend([0] * n)
        self.delta.val.extend([-1] * n)
        for shape, cnt in shape_counts.items():
            idx, rc = self._shapes[shape]
            if rc > cnt:
                self._shapes[shape] = (idx, rc - cnt)
            else:
                del self._shapes[shape]
                self.valid[idx] = False
                self._desc_shape[idx] = None
                self._free_desc.append(idx)
                self.delta.desc_dirty = True
        self.n_entries -= n

    def apply_planned(
        self,
        new_fids, new_ha, new_hb, new_plen, new_mask, new_hash, new_slots,
        dead_fids, dead_plen, dead_mask, dead_hash, dead_slots,
    ) -> None:
        """Adopt one churn tick the native plane already applied to the
        table ARRAYS (churn.cc etpu_churn_apply: dead slots cleared, new
        entries CAS-placed), keeping the Python-side bookkeeping — shape
        refcounts, per-fid entry arrays, n_entries, and the device-
        mirror Delta — consistent with it.  Dead writes precede new
        writes in the delta (the plane clears before it places, and
        compressed()'s last-write-wins depends on that order).  Unplaced
        news (slot -1: a probe window filled mid-tick) ride a grow +
        native rebuild, exactly like churn_insert_keys' overflow path.

        All inputs are numpy arrays covering NON-DEEP entries only (deep
        filters never touch the table; the engine routes them to the
        host trie)."""
        n_dead = len(dead_fids)
        n_new = len(new_fids)
        if n_dead:
            dl = np.asarray(dead_slots)
            live = dl >= 0
            slots = dl[live].tolist()
            self.delta.slots.extend(slots)
            self.delta.key_a.extend([0] * len(slots))
            self.delta.key_b.extend([0] * len(slots))
            self.delta.val.extend([-1] * len(slots))
            combo = (
                np.asarray(dead_plen, dtype=np.int64)
                | (np.asarray(dead_mask, dtype=np.int64) << 7)
                | (np.asarray(dead_hash, dtype=np.int64) << 43)
            )
            for key, cnt in zip(*np.unique(combo, return_counts=True)):
                key = int(key)
                shape = Shape(
                    plen=key & 0x7F,
                    plus_mask=(key >> 7) & 0xFFFFFFFFF,
                    has_hash=bool(key >> 43),
                )
                idx, rc = self._shapes[shape]
                if rc > int(cnt):
                    self._shapes[shape] = (idx, rc - int(cnt))
                else:
                    del self._shapes[shape]
                    self.valid[idx] = False
                    self._desc_shape[idx] = None
                    self._free_desc.append(idx)
                    self.delta.desc_dirty = True
            farr = np.asarray(dead_fids, dtype=np.int64)
            keep = farr < self._ent_cap
            self.ent_desc[farr[keep]] = -1
            self.n_entries -= n_dead
        if n_new:
            self._register_batch(
                new_fids, new_ha, new_hb, new_plen, new_mask, new_hash
            )
            self.n_entries += n_new
            sl = np.asarray(new_slots)
            placed = sl >= 0
            self.delta.slots.extend(sl[placed].tolist())
            self.delta.key_a.extend(np.asarray(new_ha)[placed].tolist())
            self.delta.key_b.extend(np.asarray(new_hb)[placed].tolist())
            self.delta.val.extend(np.asarray(new_fids)[placed].tolist())
        else:
            placed = None
        grew = False
        while self.n_entries * 2 > (1 << self.log2cap):
            self.log2cap += 1
            grew = True
        unplaced = placed is not None and not placed.all()
        if not grew and unplaced:
            self.log2cap += 1  # a probe window filled: growth is the fix
        if self.log2cap > MAX_LOG2CAP:
            raise RuntimeError("match-table growth runaway")
        if grew or unplaced:
            pend = None
            if unplaced:
                miss = ~placed
                pend = (
                    np.asarray(new_ha)[miss].astype(np.uint32, copy=False),
                    np.asarray(new_hb)[miss].astype(np.uint32, copy=False),
                    np.asarray(new_fids, dtype=np.int32)[miss],
                )
            self._rebuild(pending=pend)

    def _rebuild(self, pending=None) -> None:
        """Re-place every entry into fresh arrays at the current capacity,
        growing until placement succeeds; native path when available.

        The live table arrays ARE the entry store (val >= 0 slots carry
        every placed key); `pending` is an optional (ha, hb, fids) batch
        registered in the entry arrays but not yet placed."""
        from . import native

        live = self.val >= 0
        ha = self.key_a[live]
        hb = self.key_b[live]
        fids = self.val[live]
        if pending is not None:
            pha, phb, pfids = pending
            ha = np.concatenate([ha, pha.astype(np.uint32, copy=False)])
            hb = np.concatenate([hb, phb.astype(np.uint32, copy=False)])
            fids = np.concatenate([fids, pfids])
        n = len(fids)

        worst_dup = -1  # computed lazily, once per rebuild (keys are fixed)

        def _check_duplicate_keys() -> None:
            # >PROBE entries sharing one (ha,hb) key rehash to one home at
            # every capacity, so growing can never place them — fail fast
            # instead of doubling to MAX_LOG2CAP (~12 GiB of arrays)
            nonlocal worst_dup
            if worst_dup < 0:
                keys = ((ha.astype(np.uint64) << np.uint64(32))
                        | hb.astype(np.uint64))
                _, counts = np.unique(keys, return_counts=True)
                worst_dup = int(counts.max()) if counts.size else 0
            if worst_dup > PROBE:
                raise RuntimeError(
                    "duplicate filter key appears %d times (> probe window "
                    "%d) — callers must refcount per unique filter "
                    "(models/engine.py)" % (worst_dup, PROBE))

        while True:
            cap = 1 << self.log2cap
            self.key_a = np.zeros(cap, dtype=np.uint32)
            self.key_b = np.zeros(cap, dtype=np.uint32)
            self.val = np.full(cap, -1, dtype=np.int32)
            r = native.bulk_place(self.key_a, self.key_b, self.val,
                                  self.log2cap, PROBE, ha, hb, fids)
            if r is None:  # no native lib: python placement loop
                try:
                    for i in range(n):
                        home = bucket_of(int(ha[i]), int(hb[i]), self.log2cap)
                        for off in range(PROBE):
                            slot = (home + off) & (cap - 1)
                            if self.val[slot] == -1:
                                self.key_a[slot] = ha[i]
                                self.key_b[slot] = hb[i]
                                self.val[slot] = fids[i]
                                break
                        else:
                            raise GrowNeeded
                    break
                except GrowNeeded:
                    _check_duplicate_keys()
                    self.log2cap += 1
                    if self.log2cap > MAX_LOG2CAP:
                        raise RuntimeError("match-table growth runaway")
                    continue
            if r == n:
                break
            _check_duplicate_keys()
            self.log2cap += 1
            if self.log2cap > MAX_LOG2CAP:
                raise RuntimeError("match-table growth runaway")
        self.delta = Delta(rebuilt=True, desc_dirty=True)

    def delete(self, fid: int) -> None:
        if fid >= self._ent_cap or self.ent_desc[fid] < 0:
            raise KeyError(f"filter id {fid} not found in table")
        ha = int(self.ent_ha[fid])
        hb = int(self.ent_hb[fid])
        shape = self._desc_shape[int(self.ent_desc[fid])]
        self.ent_desc[fid] = -1
        cap = 1 << self.log2cap
        home = bucket_of(ha, hb, self.log2cap)
        for off in range(PROBE):
            slot = (home + off) & (cap - 1)
            if (
                self.val[slot] == fid
                and self.key_a[slot] == ha
                and self.key_b[slot] == hb
            ):
                # Fixed-window probing always scans all PROBE slots, so a
                # cleared slot needs no tombstone.
                self.key_a[slot] = 0
                self.key_b[slot] = 0
                self.val[slot] = -1
                self.delta.slots.append(slot)
                self.delta.key_a.append(0)
                self.delta.key_b.append(0)
                self.delta.val.append(-1)
                break
        else:  # pragma: no cover - entry bookkeeping guarantees presence
            raise KeyError(f"filter id {fid} not found in table")
        self._release_shape(shape)
        self.n_entries -= 1

    # ------------------------------------------------------------- growth

    def _grow_table(self) -> None:
        self.log2cap += 1
        if self.log2cap > MAX_LOG2CAP:
            raise RuntimeError(
                "match-table growth runaway: >%d duplicate keys in one probe "
                "window (duplicate filter inserts? callers must refcount "
                "per unique filter like models/engine.py)" % PROBE)
        self._rebuild()

    def _grow_desc(self) -> None:
        old = self.desc_cap
        self.desc_cap *= 2
        L = self.space.max_levels
        for name, fill in (
            ("incl", 0),
            ("k_a", 0),
            ("k_b", 0),
            ("min_len", 0),
            ("max_len", 0),
            ("wild_root", False),
            ("valid", False),
        ):
            arr = getattr(self, name)
            shape = (self.desc_cap, L) if arr.ndim == 2 else (self.desc_cap,)
            new = np.full(shape, fill, dtype=arr.dtype)
            new[:old] = arr
            setattr(self, name, new)
        self._free_desc = [
            i for i in range(self.desc_cap - 1, old - 1, -1)
        ] + self._free_desc
        self._desc_shape.extend([None] * (self.desc_cap - old))
        self.delta.desc_dirty = True
        self.delta.rebuilt = True  # shapes changed size; device must re-init

    def ensure_caps(self, log2cap: int, desc_cap: int) -> None:
        """Grow to at least the given capacities (for uniform shard shapes)."""
        while self.desc_cap < desc_cap:
            self._grow_desc()
        if self.log2cap < log2cap:
            self.log2cap = log2cap - 1  # _grow_table bumps by one first
            self._grow_table()

    # -------------------------------------------------------------- sync

    def drain_delta(self) -> Delta:
        d = self.delta.compressed()
        self.delta = Delta()
        return d

    # ------------------------------------------------------- checkpoint

    _STATE_ARRAYS = (
        "key_a", "key_b", "val", "incl", "k_a", "k_b", "min_len",
        "max_len", "wild_root", "valid", "ent_ha", "ent_hb", "ent_desc",
    )

    def export_state(self) -> Tuple[Dict[str, np.ndarray], dict]:
        """Snapshot the full host truth as (named arrays, JSON meta) for
        `checkpoint/store.py`.  Arrays are COPIED at capture time: the
        serializer may run on a writer thread while churn keeps mutating
        the live arrays in place."""
        arrays = {name: getattr(self, name).copy()
                  for name in self._STATE_ARRAYS}
        n = len(self._shapes)
        shp_plen = np.zeros(n, dtype=np.int32)
        shp_mask = np.zeros(n, dtype=np.uint64)
        shp_hash = np.zeros(n, dtype=bool)
        shp_idx = np.zeros(n, dtype=np.int32)
        shp_rc = np.zeros(n, dtype=np.int64)
        for j, (shape, (idx, rc)) in enumerate(self._shapes.items()):
            shp_plen[j] = shape.plen
            shp_mask[j] = shape.plus_mask
            shp_hash[j] = shape.has_hash
            shp_idx[j] = idx
            shp_rc[j] = rc
        arrays.update(
            shp_plen=shp_plen, shp_mask=shp_mask, shp_hash=shp_hash,
            shp_idx=shp_idx, shp_rc=shp_rc,
        )
        meta = {
            "log2cap": self.log2cap,
            "desc_cap": self.desc_cap,
            "n_entries": self.n_entries,
            "max_levels": self.space.max_levels,
        }
        return arrays, meta

    @classmethod
    def from_state(cls, space, arrays: Dict[str, np.ndarray],
                   meta: dict) -> "MatchTables":
        """Rebuild a MatchTables wholesale from a snapshot — array
        adoption plus shape-registry reconstruction, no re-hashing and
        no placement.  The delta is marked rebuilt so the next
        `sync_device` ships one bulk upload."""
        from .hashing import Shape

        if int(meta["max_levels"]) != space.max_levels:
            raise ValueError(
                "snapshot max_levels %s != engine %d — table keys are "
                "not portable across level caps"
                % (meta["max_levels"], space.max_levels)
            )
        t = cls.__new__(cls)
        t.space = space
        t.log2cap = int(meta["log2cap"])
        t.desc_cap = int(meta["desc_cap"])
        t.n_entries = int(meta["n_entries"])
        for name in cls._STATE_ARRAYS:
            setattr(t, name, arrays[name])
        if len(t.key_a) != (1 << t.log2cap):
            raise ValueError("snapshot table size != 2**log2cap")
        if t.incl.shape != (t.desc_cap, space.max_levels):
            raise ValueError("snapshot descriptor block shape mismatch")
        t._ent_cap = len(t.ent_ha)
        t._shapes = {}
        t._desc_shape = [None] * t.desc_cap
        for plen, mask, hsh, idx, rc in zip(
            arrays["shp_plen"].tolist(), arrays["shp_mask"].tolist(),
            arrays["shp_hash"].tolist(), arrays["shp_idx"].tolist(),
            arrays["shp_rc"].tolist(),
        ):
            shape = Shape(plen=int(plen), plus_mask=int(mask),
                          has_hash=bool(hsh))
            t._shapes[shape] = (int(idx), int(rc))
            t._desc_shape[int(idx)] = shape
        t._free_desc = [
            i for i in range(t.desc_cap - 1, -1, -1)
            if t._desc_shape[i] is None
        ]
        t.delta = Delta(rebuilt=True, desc_dirty=True)
        return t

    def device_arrays(self) -> Dict[str, np.ndarray]:
        """The full array set to mirror into HBM."""
        return {
            "key_a": self.key_a,
            "key_b": self.key_b,
            "val": self.val,
            "incl": self.incl,
            "k_a": self.k_a,
            "k_b": self.k_b,
            "min_len": self.min_len,
            "max_len": self.max_len,
            "wild_root": self.wild_root,
            "valid": self.valid,
        }
