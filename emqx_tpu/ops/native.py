"""ctypes loader for the native host hot paths (native/matchhash.cc).

The reference keeps its data-plane hot loops in C NIFs (jiffy JSON,
quicer QUIC, bcrypt — SURVEY.md §2.3); here the equivalents are the
topic-batch hashing that feeds the TPU match kernel and the MQTT frame
boundary scan.  The library is built on demand with g++ (no pip deps);
every caller falls back to pure Python when it is unavailable, so the
framework stays importable on machines without a toolchain.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

log = logging.getLogger("emqx_tpu.native")


def _isa_tag() -> str:
    """Host ISA fingerprint for the build cache: the lib is compiled
    -march=native, so a .so built on one machine must not be loaded on a
    host lacking those instructions (SIGILL is not catchable) — the CPU
    flag set is part of the cache key."""
    import hashlib
    import platform

    tag = platform.machine()
    try:
        with open("/proc/cpuinfo", "r", encoding="utf-8") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    tag += hashlib.sha1(
                        " ".join(sorted(line.split(":", 1)[1].split()))
                        .encode()
                    ).hexdigest()[:10]
                    break
    except OSError:  # pragma: no cover - non-linux
        pass
    return tag


_LIB_PATH = os.path.join(
    os.path.dirname(__file__), f"libemqxtpu-{_isa_tag()}.so"
)
_SRC_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SRCS = [
    os.path.join(_SRC_DIR, "matchhash.cc"),
    os.path.join(_SRC_DIR, "registry.cc"),
    os.path.join(_SRC_DIR, "churn.cc"),
    os.path.join(_SRC_DIR, "prep.cc"),
    os.path.join(_SRC_DIR, "bcrypt.cc"),
    os.path.join(_SRC_DIR, "drain.cc"),
]
_PYMOD_SRC = os.path.join(_SRC_DIR, "pymod.cc")
_HDRS = [os.path.join(_SRC_DIR, "pool.h"), os.path.join(_SRC_DIR, "match_core.h")]

_lib: Optional[ctypes.CDLL] = None
_ext = None  # CPython extension view of the same .so (may stay None)
_tried = False
_lock = threading.Lock()

_u8p = ctypes.POINTER(ctypes.c_uint8)
_u32p = ctypes.POINTER(ctypes.c_uint32)
_i32p = ctypes.POINTER(ctypes.c_int32)
_i64p = ctypes.POINTER(ctypes.c_int64)


def _build() -> bool:
    srcs = [os.path.abspath(s) for s in _SRCS if os.path.exists(s)]
    if not srcs:
        return False
    base = ["g++", "-O3", "-Wall", "-fPIC", "-std=c++17", "-shared",
            "-pthread", "-o", _LIB_PATH]
    # The CPython extension face (pymod.cc) rides in the same .so when
    # Python headers exist; variants without it keep the ctypes paths
    # alive on header-less machines.
    pymod: List[List[str]] = []
    if os.path.exists(_PYMOD_SRC):
        import sysconfig

        inc = sysconfig.get_paths().get("include")
        if inc and os.path.exists(os.path.join(inc, "Python.h")):
            pymod.append([f"-I{inc}", os.path.abspath(_PYMOD_SRC)])
    pymod.append([])
    # -march=native first: the hash contractions in the host match are
    # u32 multiply-add loops that vectorize well past the SSE2 baseline;
    # retried portable if the toolchain rejects it
    for ext in pymod:
        for extra in (["-march=native"], []):
            try:
                subprocess.run(  # analysis: allow-blocking(one-shot toolchain build at import, before the loop exists)
                    base + extra + ext + srcs,
                    check=True, capture_output=True, timeout=120,
                )
                return True
            except (OSError, subprocess.SubprocessError) as e:
                err = e
    log.info("native build unavailable: %s", err)
    return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.etpu_fnv1a64.restype = ctypes.c_uint64
    lib.etpu_fnv1a64.argtypes = [_u8p, ctypes.c_uint64]
    lib.etpu_prep_topics.restype = None
    lib.etpu_prep_topics.argtypes = [
        _u8p, _i64p, ctypes.c_int32, ctypes.c_int32,
        _u32p, _u32p, _u32p, _u32p,
        _u32p, _u32p, _i32p, _u8p,
    ]
    lib.etpu_scan_frames.restype = ctypes.c_int32
    lib.etpu_scan_frames.argtypes = [
        _u8p, ctypes.c_int64, ctypes.c_int64,
        _u8p, _i64p, _i64p, ctypes.c_int32, _i64p, _i32p,
    ]
    lib.etpu_filter_keys.restype = None
    lib.etpu_filter_keys.argtypes = [
        _u8p, _i64p, ctypes.c_int32, ctypes.c_int32,
        _u32p, _u32p, _u32p, _u32p,
        _u32p, _u32p, _u32p, _u32p,
        _u32p, _u32p, _i32p, _u32p, _u8p,
    ]
    lib.etpu_bulk_place.restype = ctypes.c_int32
    lib.etpu_bulk_place.argtypes = [
        _u32p, _u32p, _i32p, ctypes.c_int32, ctypes.c_int32,
        _u32p, _u32p, _i32p, ctypes.c_int32,
    ]
    lib.etpu_bulk_place_slots.restype = ctypes.c_int32
    lib.etpu_bulk_place_slots.argtypes = [
        _u32p, _u32p, _i32p, ctypes.c_int32, ctypes.c_int32,
        _u32p, _u32p, _i32p, ctypes.c_int32, _i32p,
    ]
    lib.etpu_verify_pairs.restype = None
    lib.etpu_verify_pairs.argtypes = [
        _u8p, _i64p, _u8p, _i64p, _i32p, ctypes.c_int32, _u8p,
    ]
    lib.etpu_reg_new.restype = ctypes.c_void_p
    lib.etpu_reg_new.argtypes = []
    lib.etpu_reg_free.restype = None
    lib.etpu_reg_free.argtypes = [ctypes.c_void_p]
    lib.etpu_reg_count.restype = ctypes.c_int64
    lib.etpu_reg_count.argtypes = [ctypes.c_void_p]
    lib.etpu_reg_set_bulk.restype = None
    lib.etpu_reg_set_bulk.argtypes = [
        ctypes.c_void_p, _i32p, ctypes.c_int32, _u8p, _i64p,
    ]
    lib.etpu_reg_del_bulk.restype = None
    lib.etpu_reg_del_bulk.argtypes = [ctypes.c_void_p, _i32p, ctypes.c_int32]
    lib.etpu_match_host_verified.restype = ctypes.c_int64
    lib.etpu_match_host_verified.argtypes = [
        ctypes.c_void_p,
        _u8p, _i64p, ctypes.c_int32,
        ctypes.c_int32,
        _u32p, _u32p, _u32p, _u32p,
        _u32p, _u32p, _i32p, ctypes.c_int32, ctypes.c_int32,
        _u32p, _u32p, _u32p, _i32p, _i32p, _u8p, _u8p,
        ctypes.c_int32, ctypes.c_int32,
        _i32p, _i32p, ctypes.c_int32,
        _i32p, ctypes.c_int32, _i32p,
    ]
    lib.etpu_verify_pairs_reg.restype = None
    lib.etpu_verify_pairs_reg.argtypes = [
        ctypes.c_void_p, _u8p, _i64p, _i32p, _i32p, ctypes.c_int32, _u8p,
    ]
    lib.etpu_pool_width.restype = ctypes.c_int32
    lib.etpu_pool_width.argtypes = []
    lib.etpu_churn_new.restype = ctypes.c_void_p
    lib.etpu_churn_new.argtypes = [
        ctypes.c_int32, ctypes.c_int32,
        _u32p, _u32p, _u32p, _u32p, _u32p, _u32p, _u32p, _u32p,
    ]
    lib.etpu_churn_free.restype = None
    lib.etpu_churn_free.argtypes = [ctypes.c_void_p]
    lib.etpu_churn_count.restype = ctypes.c_int64
    lib.etpu_churn_count.argtypes = [ctypes.c_void_p]
    lib.etpu_churn_next_fid.restype = ctypes.c_int32
    lib.etpu_churn_next_fid.argtypes = [ctypes.c_void_p]
    lib.etpu_churn_free_count.restype = ctypes.c_int64
    lib.etpu_churn_free_count.argtypes = [ctypes.c_void_p]
    lib.etpu_churn_shards.restype = ctypes.c_int32
    lib.etpu_churn_shards.argtypes = [ctypes.c_void_p]
    lib.etpu_churn_lookup.restype = ctypes.c_int32
    lib.etpu_churn_lookup.argtypes = [ctypes.c_void_p, _u8p, ctypes.c_int64]
    lib.etpu_churn_ref.restype = ctypes.c_int64
    lib.etpu_churn_ref.argtypes = [ctypes.c_void_p, _u8p, ctypes.c_int64]
    lib.etpu_churn_apply.restype = ctypes.c_int32
    lib.etpu_churn_apply.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p,
        _u8p, _i64p, ctypes.c_int32,
        _u8p, _i64p, ctypes.c_int32,
        _u32p, _u32p, _i32p,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        _i32p,
        _i32p, _u32p, _u32p, _i32p, _u32p, _u8p, _i32p, _u8p, _i32p, _i32p,
        _i32p, _u32p, _u32p, _i32p, _u32p, _u8p, _i32p, _u8p, _i32p, _i32p,
    ]
    lib.etpu_churn_export_sizes.restype = None
    lib.etpu_churn_export_sizes.argtypes = [
        ctypes.c_void_p, _i64p, _i64p, _i64p,
    ]
    lib.etpu_churn_export.restype = None
    lib.etpu_churn_export.argtypes = [
        ctypes.c_void_p, _u8p, _i64p, _i32p, _i64p, _u8p, _i32p,
    ]
    lib.etpu_churn_ingest.restype = None
    lib.etpu_churn_ingest.argtypes = [
        ctypes.c_void_p, _u8p, _i64p, _i32p, _i64p, ctypes.c_int32,
        _i32p, ctypes.c_int32, ctypes.c_int32,
    ]
    lib.etpu_prep_new.restype = ctypes.c_void_p
    lib.etpu_prep_new.argtypes = [
        ctypes.c_int32, ctypes.c_int64, _u32p, _u32p, _u32p, _u32p,
    ]
    lib.etpu_prep_free.restype = None
    lib.etpu_prep_free.argtypes = [ctypes.c_void_p]
    lib.etpu_prep_set_cap.restype = None
    lib.etpu_prep_set_cap.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.etpu_prep_stats.restype = None
    lib.etpu_prep_stats.argtypes = [ctypes.c_void_p, _i64p]
    lib.etpu_prep_lookup.restype = ctypes.c_int32
    lib.etpu_prep_lookup.argtypes = [ctypes.c_void_p, _u8p, ctypes.c_int64]
    lib.etpu_prep_hash.restype = ctypes.c_int32
    lib.etpu_prep_hash.argtypes = [
        ctypes.c_void_p, _u8p, _i64p, ctypes.c_int32, _i64p,
    ]
    lib.etpu_prep_pack.restype = None
    lib.etpu_prep_pack.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        _u32p, _i64p,
    ]
    lib.etpu_prep_rows.restype = None
    lib.etpu_prep_rows.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, _u32p, _u32p, _i32p, _u8p,
    ]
    lib.etpu_drain_wait.restype = ctypes.c_int32
    lib.etpu_drain_wait.argtypes = [
        _i32p, ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.etpu_bcrypt_init.restype = None
    lib.etpu_bcrypt_init.argtypes = [_u32p]
    lib.etpu_bcrypt_hash.restype = ctypes.c_int32
    lib.etpu_bcrypt_hash.argtypes = [
        _u8p, ctypes.c_int32, _u8p, ctypes.c_int32, _u8p,
    ]
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first use; None if absent."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        try:
            if not os.path.exists(_LIB_PATH) or any(
                os.path.exists(s)
                and os.path.getmtime(s) > os.path.getmtime(_LIB_PATH)
                for s in _SRCS + _HDRS + [_PYMOD_SRC]
            ):
                _build()
            if os.path.exists(_LIB_PATH):
                _lib = _bind(ctypes.CDLL(_LIB_PATH))
                log.info("native hot paths loaded (%s)", _LIB_PATH)
                _load_ext()
        except (OSError, AttributeError) as e:
            # AttributeError: a stale .so missing newer symbols that
            # could not be rebuilt — degrade to pure Python, don't crash
            _lib = None
            log.info("native load failed: %s", e)
        _tried = True
    return _lib


def _load_ext() -> None:
    """Import the CPython extension face of the already-loaded .so (same
    image in memory: dlopen refcounts the handle, so ctypes and the
    module share globals/registries)."""
    global _ext
    try:
        import importlib.util

        spec = importlib.util.spec_from_file_location("_etpu_ext", _LIB_PATH)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _ext = mod
        log.info("native extension face loaded")
    except Exception as e:  # built without Python.h: ctypes paths only
        _ext = None
        log.info("native extension face unavailable: %s", e)


def get_ext():
    """The CPython extension module view of the native lib, or None."""
    if not _tried:
        get_lib()
    return _ext


def available() -> bool:
    return get_lib() is not None


# -------------------------------------------------------------- wrappers

def fnv1a64(data: bytes) -> int:
    lib = get_lib()
    if lib is None:
        h = 0xCBF29CE484222325
        for byte in data:
            h = ((h ^ byte) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
        return h
    buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data) if data else (ctypes.c_uint8 * 1)()
    return lib.etpu_fnv1a64(buf, len(data))


def prep_topics(
    topics: List[str], max_levels: int,
    Ca: np.ndarray, Cb: np.ndarray, Ra: np.ndarray, Rb: np.ndarray,
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
    """Native topic-batch prep: (terms_a, terms_b, lengths, dollar) or None."""
    out = prep_topics_packed(topics, max_levels, Ca, Cb, Ra, Rb)
    return None if out is None else out[:4]


def prep_topics_packed(
    topics: List[str], max_levels: int,
    Ca: np.ndarray, Cb: np.ndarray, Ra: np.ndarray, Rb: np.ndarray,
):
    """Like prep_topics, but also returns the packed utf-8 topic buffer
    (buf, offsets) so later stages (exact-verify) reuse it instead of
    re-encoding the batch: (ta, tb, ln, dl, buf, offsets) or None."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(topics)
    buf, offsets = _pack_strs(topics)

    ta = np.zeros((n, max_levels), dtype=np.uint32)
    tb = np.zeros((n, max_levels), dtype=np.uint32)
    ln = np.zeros(n, dtype=np.int32)
    dl = np.zeros(n, dtype=np.uint8)
    c = np.ascontiguousarray
    lib.etpu_prep_topics(
        buf.ctypes.data_as(_u8p), c(offsets).ctypes.data_as(_i64p),
        n, max_levels,
        c(Ca).ctypes.data_as(_u32p), c(Cb).ctypes.data_as(_u32p),
        c(Ra).ctypes.data_as(_u32p), c(Rb).ctypes.data_as(_u32p),
        ta.ctypes.data_as(_u32p), tb.ctypes.data_as(_u32p),
        ln.ctypes.data_as(_i32p), dl.ctypes.data_as(_u8p),
    )
    return ta, tb, ln, dl.astype(bool), buf, offsets


class FrameScan:
    __slots__ = ("count", "headers", "body_offs", "body_lens", "consumed", "err")

    def __init__(self, count, headers, body_offs, body_lens, consumed, err):
        self.count = count
        self.headers = headers
        self.body_offs = body_offs
        self.body_lens = body_lens
        self.consumed = consumed
        self.err = err  # 0 ok, 1 malformed varint, 2 oversize


def scan_frames(buf: bytes, max_size: int, max_frames: int = 256) -> Optional[FrameScan]:
    """Native MQTT frame-boundary scan; None when the lib is absent."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(buf)
    arr = np.frombuffer(buf, dtype=np.uint8) if n else np.zeros(1, dtype=np.uint8)
    arr = np.ascontiguousarray(arr)
    headers = np.zeros(max_frames, dtype=np.uint8)
    offs = np.zeros(max_frames, dtype=np.int64)
    lens = np.zeros(max_frames, dtype=np.int64)
    consumed = ctypes.c_int64(0)
    err = ctypes.c_int32(0)
    count = lib.etpu_scan_frames(
        arr.ctypes.data_as(_u8p), n, max_size,
        headers.ctypes.data_as(_u8p), offs.ctypes.data_as(_i64p),
        lens.ctypes.data_as(_i64p), max_frames,
        ctypes.byref(consumed), ctypes.byref(err),
    )
    return FrameScan(count, headers, offs, lens, consumed.value, err.value)


def _pack_strs(strs):
    """Pack strings into (buf, offsets): one join+encode + three
    vectorized passes instead of a per-string encode loop (the loop was
    half the cost of a small bulk insert).  MQTT forbids U+0000 in
    topics/filters, so NUL is a safe separator; an embedded NUL is
    detected by separator count and falls back to the per-string path."""
    n = len(strs)
    if n >= 64:
        try:
            data = "\x00".join(strs).encode("utf-8")
        except TypeError:  # non-str entries: caller bug, slow path raises
            return _pack_blobs([s.encode("utf-8") for s in strs])
        buf = np.frombuffer(data, dtype=np.uint8)
        mask = buf == 0
        sep = np.flatnonzero(mask)
        if len(sep) == n - 1:
            offs = np.empty(n + 1, dtype=np.int64)
            offs[0] = 0
            offs[1:n] = sep - np.arange(n - 1)
            offs[n] = len(data) - (n - 1)
            packed = buf[~mask]
            if not len(packed):
                packed = np.zeros(1, dtype=np.uint8)
            return np.ascontiguousarray(packed), offs
    return _pack_blobs([s.encode("utf-8") for s in strs])


def pack_strs(strs):
    """Pack strings into (buf, offsets) for the packed-batch entry points."""
    return _pack_strs(strs)


def filter_keys(filters, max_levels: int, space):
    """Native batch filter_key: (ha, hb, plen, plus_mask, has_hash) arrays,
    or None when the lib is absent."""
    out = filter_keys_packed(filters, max_levels, space)
    return None if out is None else out[:5]


def filter_keys_packed(filters, max_levels: int, space):
    """filter_keys that also returns the packed utf-8 buffer
    (..., buf, offsets) so callers can feed the registry without
    re-encoding the batch."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(filters)
    buf, offsets = _pack_strs(filters)
    ha = np.zeros(n, dtype=np.uint32)
    hb = np.zeros(n, dtype=np.uint32)
    plen = np.zeros(n, dtype=np.int32)
    plus_mask = np.zeros(n, dtype=np.uint32)
    has_hash = np.zeros(n, dtype=np.uint8)
    c = np.ascontiguousarray
    hra = c(space.HR[0]); hrb = c(space.HR[1])
    lib.etpu_filter_keys(
        buf.ctypes.data_as(_u8p), c(offsets).ctypes.data_as(_i64p),
        n, max_levels,
        c(space.C[0]).ctypes.data_as(_u32p), c(space.C[1]).ctypes.data_as(_u32p),
        c(space.R[0]).ctypes.data_as(_u32p), c(space.R[1]).ctypes.data_as(_u32p),
        c(space.PLUS).ctypes.data_as(_u32p), c(space.HM).ctypes.data_as(_u32p),
        hra.ctypes.data_as(_u32p), hrb.ctypes.data_as(_u32p),
        ha.ctypes.data_as(_u32p), hb.ctypes.data_as(_u32p),
        plen.ctypes.data_as(_i32p), plus_mask.ctypes.data_as(_u32p),
        has_hash.ctypes.data_as(_u8p),
    )
    return ha, hb, plen, plus_mask, has_hash.astype(bool), buf, offsets


def _pack_blobs(blobs):
    n = len(blobs)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(
        np.fromiter(map(len, blobs), dtype=np.int64, count=n),
        out=offsets[1:],
    )
    data = b"".join(blobs)
    buf = np.frombuffer(data, dtype=np.uint8) if data else np.zeros(1, dtype=np.uint8)
    return np.ascontiguousarray(buf), offsets


def verify_pairs(topic_blobs, tidx: np.ndarray, filt_blobs):
    """Exact per-pair topic-vs-filter match (device-hit verification).

    topic_blobs: utf-8 topic strings (indexed by tidx); filt_blobs: one
    utf-8 filter string per pair.  Returns a bool array per pair, or
    None when the lib is absent (caller falls back to Python)."""
    if get_lib() is None:
        return None
    tbuf, toffs = _pack_blobs(topic_blobs)
    return verify_pairs_packed(tbuf, toffs, tidx, filt_blobs)


def verify_pairs_packed(tbuf: np.ndarray, toffs: np.ndarray,
                        tidx: np.ndarray, filt_blobs):
    """verify_pairs against an already-packed topic buffer (the packed
    batch from prep_topics_packed) — skips re-encoding the topics."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(filt_blobs)
    fbuf, foffs = _pack_blobs(filt_blobs)
    tidx = np.ascontiguousarray(tidx.astype(np.int32, copy=False))
    ok = np.zeros(n, dtype=np.uint8)
    lib.etpu_verify_pairs(
        tbuf.ctypes.data_as(_u8p), toffs.ctypes.data_as(_i64p),
        fbuf.ctypes.data_as(_u8p), foffs.ctypes.data_as(_i64p),
        tidx.ctypes.data_as(_i32p), n, ok.ctypes.data_as(_u8p),
    )
    return ok.astype(bool)


class FilterRegistry:
    """Handle on a C++-owned fid -> filter-string registry.

    The registry backs inline exact-verification in the fused host match
    (`etpu_match_host_verified`) and registry-backed device-hit verify
    (`etpu_verify_pairs_reg`), replacing per-call Python blob assembly.
    Freed via weakref.finalize (safe at interpreter shutdown)."""

    __slots__ = ("ptr", "_finalizer", "__weakref__")

    def __init__(self):
        import weakref

        lib = get_lib()
        if lib is None:
            raise RuntimeError("native lib unavailable")
        self.ptr = lib.etpu_reg_new()
        self._finalizer = weakref.finalize(self, lib.etpu_reg_free, self.ptr)

    def set_bulk(self, fids, blobs) -> None:
        if len(fids) == 0:
            return
        buf, offs = _pack_blobs(blobs)
        self.set_bulk_packed(fids, buf, offs)

    def set_bulk_packed(self, fids, buf: np.ndarray, offs: np.ndarray) -> None:
        """set_bulk from an already-packed blob buffer (e.g. the packed
        batch filter_keys_packed produced) — no re-encode, no re-join."""
        lib = get_lib()
        n = len(fids)
        if n == 0:
            return
        farr = np.ascontiguousarray(np.asarray(fids, dtype=np.int32))
        lib.etpu_reg_set_bulk(
            self.ptr, farr.ctypes.data_as(_i32p), n,
            np.ascontiguousarray(buf).ctypes.data_as(_u8p),
            np.ascontiguousarray(offs).ctypes.data_as(_i64p),
        )

    def del_bulk(self, fids) -> None:
        lib = get_lib()
        n = len(fids)
        if n == 0:
            return
        farr = np.ascontiguousarray(np.asarray(fids, dtype=np.int32))
        lib.etpu_reg_del_bulk(self.ptr, farr.ctypes.data_as(_i32p), n)

    def count(self) -> int:
        return int(get_lib().etpu_reg_count(self.ptr))


def make_registry() -> Optional[FilterRegistry]:
    """A new native filter registry, or None when the lib is absent."""
    if get_lib() is None:
        return None
    return FilterRegistry()


class ChurnApply:
    """Outputs of one ChurnPlane.apply tick (numpy views, no copies).

    ``fids``: the fid per add, input order.  ``new_*``: truly-new
    filters in first-occurrence order — key lanes, shape fields, the
    table slot the plane claimed (-1: unplaced or place=False or deep),
    deep flag, and the index into the adds batch (for string recovery).
    ``dead_*``: fully-removed filters in first-decrement order."""

    __slots__ = (
        "fids", "new_fid", "new_ha", "new_hb", "new_plen", "new_mask",
        "new_hash", "new_slot", "new_deep", "new_aidx",
        "dead_fid", "dead_ha", "dead_hb", "dead_plen", "dead_mask",
        "dead_hash", "dead_slot", "dead_deep", "dead_ridx",
    )


class ChurnPlane:
    """Handle on the C++ sharded churn-bookkeeping plane (churn.cc).

    Owns the filter -> (fid, refcount, table key) truth, partitioned by
    matchhash(filter) % n_shards and mutated by the native worker pool
    with the GIL released.  One `apply` call per churn tick replaces the
    per-filter Python dict work; the outputs feed
    `MatchTables.apply_planned` (shape/entry/delta bookkeeping) and the
    deep-filter trie.  Freed via weakref.finalize."""

    __slots__ = ("ptr", "max_levels", "_finalizer", "__weakref__")

    def __init__(self, space, n_shards: int = 16):
        import weakref

        lib = get_lib()
        if lib is None:
            raise RuntimeError("native lib unavailable")
        c = np.ascontiguousarray
        hra = c(space.HR[0]); hrb = c(space.HR[1])
        self.max_levels = space.max_levels
        self.ptr = lib.etpu_churn_new(
            n_shards, space.max_levels,
            c(space.C[0]).ctypes.data_as(_u32p),
            c(space.C[1]).ctypes.data_as(_u32p),
            c(space.R[0]).ctypes.data_as(_u32p),
            c(space.R[1]).ctypes.data_as(_u32p),
            c(space.PLUS).ctypes.data_as(_u32p),
            c(space.HM).ctypes.data_as(_u32p),
            hra.ctypes.data_as(_u32p), hrb.ctypes.data_as(_u32p),
        )
        self._finalizer = weakref.finalize(self, lib.etpu_churn_free, self.ptr)

    # ------------------------------------------------------------ queries

    def count(self) -> int:
        return int(get_lib().etpu_churn_count(self.ptr))

    def lookup(self, filt: str) -> Optional[int]:
        ext = get_ext()
        if ext is not None:
            return ext.churn_lookup(self.ptr, filt)
        b = filt.encode("utf-8")
        buf = (ctypes.c_uint8 * max(len(b), 1)).from_buffer_copy(b or b"\0")
        fid = get_lib().etpu_churn_lookup(self.ptr, buf, len(b))
        return None if fid < 0 else fid

    def refcount(self, filt: str) -> int:
        b = filt.encode("utf-8")
        buf = (ctypes.c_uint8 * max(len(b), 1)).from_buffer_copy(b or b"\0")
        return int(get_lib().etpu_churn_ref(self.ptr, buf, len(b)))

    def next_fid(self) -> int:
        return int(get_lib().etpu_churn_next_fid(self.ptr))

    def free_count(self) -> int:
        return int(get_lib().etpu_churn_free_count(self.ptr))

    def n_shards(self) -> int:
        return int(get_lib().etpu_churn_shards(self.ptr))

    # -------------------------------------------------------------- apply

    def apply(self, adds, removes, tables=None, reg=None,
              place: bool = True) -> ChurnApply:
        """One churn tick (removes then adds; see churn.cc).

        With ``tables`` (a MatchTables) and ``place=True`` the plane
        CAS-places new entries into the live table arrays and clears
        dead slots; the caller still owns shape/entry/delta bookkeeping
        (`MatchTables.apply_planned`).  ``reg`` maintains the native
        string registry inline (set new / del dead, non-deep only)."""
        lib = get_lib()
        na, nr = len(adds), len(removes)
        abuf, aoffs = _pack_strs(adds)
        rbuf, roffs = _pack_strs(removes)
        r = ChurnApply()
        out_fid = np.empty(max(na, 1), dtype=np.int32)
        new_fid = np.empty(max(na, 1), dtype=np.int32)
        new_ha = np.empty(max(na, 1), dtype=np.uint32)
        new_hb = np.empty(max(na, 1), dtype=np.uint32)
        new_plen = np.empty(max(na, 1), dtype=np.int32)
        new_mask = np.empty(max(na, 1), dtype=np.uint32)
        new_hash = np.empty(max(na, 1), dtype=np.uint8)
        new_slot = np.empty(max(na, 1), dtype=np.int32)
        new_deep = np.empty(max(na, 1), dtype=np.uint8)
        new_aidx = np.empty(max(na, 1), dtype=np.int32)
        dead_fid = np.empty(max(nr, 1), dtype=np.int32)
        dead_ha = np.empty(max(nr, 1), dtype=np.uint32)
        dead_hb = np.empty(max(nr, 1), dtype=np.uint32)
        dead_plen = np.empty(max(nr, 1), dtype=np.int32)
        dead_mask = np.empty(max(nr, 1), dtype=np.uint32)
        dead_hash = np.empty(max(nr, 1), dtype=np.uint8)
        dead_slot = np.empty(max(nr, 1), dtype=np.int32)
        dead_deep = np.empty(max(nr, 1), dtype=np.uint8)
        dead_ridx = np.empty(max(nr, 1), dtype=np.int32)
        n_new = ctypes.c_int32(0)
        n_dead = ctypes.c_int32(0)
        if tables is not None and place:
            ka = tables.key_a.ctypes.data_as(_u32p)
            kb = tables.key_b.ctypes.data_as(_u32p)
            vv = tables.val.ctypes.data_as(_i32p)
            log2cap = tables.log2cap
            from .tables import PROBE as probe
        else:
            ka = kb = ctypes.cast(None, _u32p)
            vv = ctypes.cast(None, _i32p)
            log2cap, probe, place = 0, 0, False
        d = lambda a, t: a.ctypes.data_as(t)
        lib.etpu_churn_apply(
            self.ptr, reg.ptr if reg is not None else None,
            d(abuf, _u8p), d(aoffs, _i64p), na,
            d(rbuf, _u8p), d(roffs, _i64p), nr,
            ka, kb, vv, log2cap, probe, 1 if place else 0,
            d(out_fid, _i32p),
            d(new_fid, _i32p), d(new_ha, _u32p), d(new_hb, _u32p),
            d(new_plen, _i32p), d(new_mask, _u32p), d(new_hash, _u8p),
            d(new_slot, _i32p), d(new_deep, _u8p), d(new_aidx, _i32p),
            ctypes.byref(n_new),
            d(dead_fid, _i32p), d(dead_ha, _u32p), d(dead_hb, _u32p),
            d(dead_plen, _i32p), d(dead_mask, _u32p), d(dead_hash, _u8p),
            d(dead_slot, _i32p), d(dead_deep, _u8p), d(dead_ridx, _i32p),
            ctypes.byref(n_dead),
        )
        k, m = n_new.value, n_dead.value
        r.fids = out_fid[:na]
        r.new_fid = new_fid[:k]
        r.new_ha = new_ha[:k]
        r.new_hb = new_hb[:k]
        r.new_plen = new_plen[:k]
        r.new_mask = new_mask[:k]
        r.new_hash = new_hash[:k].astype(bool)
        r.new_slot = new_slot[:k]
        r.new_deep = new_deep[:k].astype(bool)
        r.new_aidx = new_aidx[:k]
        r.dead_fid = dead_fid[:m]
        r.dead_ha = dead_ha[:m]
        r.dead_hb = dead_hb[:m]
        r.dead_plen = dead_plen[:m]
        r.dead_mask = dead_mask[:m]
        r.dead_hash = dead_hash[:m].astype(bool)
        r.dead_slot = dead_slot[:m]
        r.dead_deep = dead_deep[:m].astype(bool)
        r.dead_ridx = dead_ridx[:m]
        return r

    # ---------------------------------------------------- export / ingest

    def export(self):
        """(buf, offs, fids, rcs, deep, free_fids, next_fid): the full
        bookkeeping truth as arrays (checkpoint capture, ref_snapshot)."""
        lib = get_lib()
        ne = ctypes.c_int64(0)
        sb = ctypes.c_int64(0)
        nf = ctypes.c_int64(0)
        lib.etpu_churn_export_sizes(
            self.ptr, ctypes.byref(ne), ctypes.byref(sb), ctypes.byref(nf)
        )
        n, bytes_, n_free = ne.value, sb.value, nf.value
        buf = np.empty(max(bytes_, 1), dtype=np.uint8)
        offs = np.zeros(n + 1, dtype=np.int64)
        fids = np.empty(max(n, 1), dtype=np.int32)
        rcs = np.empty(max(n, 1), dtype=np.int64)
        deep = np.zeros(max(n, 1), dtype=np.uint8)
        free = np.empty(max(n_free, 1), dtype=np.int32)
        lib.etpu_churn_export(
            self.ptr, buf.ctypes.data_as(_u8p), offs.ctypes.data_as(_i64p),
            fids.ctypes.data_as(_i32p), rcs.ctypes.data_as(_i64p),
            deep.ctypes.data_as(_u8p), free.ctypes.data_as(_i32p),
        )
        return (buf[:bytes_], offs, fids[:n], rcs[:n],
                deep[:n].astype(bool), free[:n_free], self.next_fid())

    def ingest(self, buf, offs, fids, rcs, free_fids, next_fid) -> None:
        """Bulk-load (checkpoint restore): keys recomputed natively, in
        parallel per shard; deep flags rederived from plen."""
        lib = get_lib()
        n = len(fids)
        c = np.ascontiguousarray
        buf = c(np.asarray(buf, dtype=np.uint8))
        if not len(buf):
            buf = np.zeros(1, dtype=np.uint8)
        offs = c(np.asarray(offs, dtype=np.int64))
        fids = c(np.asarray(fids, dtype=np.int32))
        rcs = c(np.asarray(rcs, dtype=np.int64))
        free = c(np.asarray(free_fids, dtype=np.int32))
        if not len(free):
            free = np.zeros(1, dtype=np.int32)
        lib.etpu_churn_ingest(
            self.ptr, buf.ctypes.data_as(_u8p), offs.ctypes.data_as(_i64p),
            fids.ctypes.data_as(_i32p), rcs.ctypes.data_as(_i64p), n,
            free.ctypes.data_as(_i32p), len(free_fids), next_fid,
        )

    def fid_map(self):
        """filter -> fid dict (tests/introspection; O(n) materialize)."""
        buf, offs, fids, _rcs, _deep, _free, _nx = self.export()
        data = buf.tobytes()
        ol = offs.tolist()
        return {
            data[ol[i]:ol[i + 1]].decode("utf-8"): int(f)
            for i, f in enumerate(fids.tolist())
        }


class NativePrepPlane:
    """Handle on the C++ fused prep plane (native/prep.cc).

    Owns the two-generation topic memo + hashed row store; one
    `hash_batch` + `pack_into` pair per tick replaces the per-topic
    Python memo walk and the staging-buffer fill — both calls run with
    the GIL released, parallel over the worker pool.  NOT internally
    synchronized: callers (ops/prep.py TopicPrep) serialize access
    behind one lock, like ChurnPlane's single-apply discipline.
    Freed via weakref.finalize."""

    __slots__ = ("ptr", "max_levels", "_finalizer", "__weakref__")

    def __init__(self, space, cap: int):
        import weakref

        lib = get_lib()
        if lib is None:
            raise RuntimeError("native lib unavailable")
        c = np.ascontiguousarray
        self.max_levels = space.max_levels
        self.ptr = lib.etpu_prep_new(
            space.max_levels, cap,
            c(space.C[0]).ctypes.data_as(_u32p),
            c(space.C[1]).ctypes.data_as(_u32p),
            c(space.R[0]).ctypes.data_as(_u32p),
            c(space.R[1]).ctypes.data_as(_u32p),
        )
        self._finalizer = weakref.finalize(self, lib.etpu_prep_free, self.ptr)

    def set_cap(self, cap: int) -> None:
        get_lib().etpu_prep_set_cap(self.ptr, int(cap))

    def stats(self):
        """(hits, misses, live entries, old entries, stored rows)."""
        out = np.zeros(8, dtype=np.int64)
        get_lib().etpu_prep_stats(self.ptr, out.ctypes.data_as(_i64p))
        return tuple(int(x) for x in out[:5])

    def lookup_gen(self, topic: str) -> int:
        """Generation holding the topic: 0 live, 1 old-only, -1 absent."""
        b = topic.encode("utf-8")
        buf = (ctypes.c_uint8 * max(len(b), 1)).from_buffer_copy(b or b"\0")
        return int(get_lib().etpu_prep_lookup(self.ptr, buf, len(b)))

    def hash_batch(self, tbuf: np.ndarray, toffs: np.ndarray, n: int):
        """Memo+split+hash the packed batch; returns
        (max_len, ns, batch_hits, batch_misses)."""
        out3 = (ctypes.c_int64 * 3)()
        maxlen = get_lib().etpu_prep_hash(
            self.ptr,
            np.ascontiguousarray(tbuf).ctypes.data_as(_u8p),
            np.ascontiguousarray(toffs).ctypes.data_as(_i64p),
            n, ctypes.cast(out3, _i64p),
        )
        return int(maxlen), int(out3[0]), int(out3[1]), int(out3[2])

    def pack_into(self, n: int, B: int, L: int, buf: np.ndarray) -> int:
        """Gather the last hashed batch into buf [B, 2L+2]; returns ns."""
        ns = ctypes.c_int64(0)
        get_lib().etpu_prep_pack(
            self.ptr, n, B, L, buf.ctypes.data_as(_u32p), ctypes.byref(ns)
        )
        return int(ns.value)

    def rows(self, n: int):
        """Full-width (ta, tb, ln, dl) arrays of the last hashed batch."""
        L = self.max_levels
        ta = np.empty((n, L), dtype=np.uint32)
        tb = np.empty((n, L), dtype=np.uint32)
        ln = np.empty(n, dtype=np.int32)
        dl = np.empty(n, dtype=np.uint8)
        get_lib().etpu_prep_rows(
            self.ptr, n, ta.ctypes.data_as(_u32p), tb.ctypes.data_as(_u32p),
            ln.ctypes.data_as(_i32p), dl.ctypes.data_as(_u8p),
        )
        return ta, tb, ln, dl


def make_prep_plane(space, cap: int) -> Optional[NativePrepPlane]:
    """A new native fused prep plane, or None when the lib is absent."""
    if get_lib() is None:
        return None
    return NativePrepPlane(space, cap)


def make_churn_plane(space, n_shards: int = 16) -> Optional[ChurnPlane]:
    """A new native churn plane, or None when the lib is absent."""
    if get_lib() is None:
        return None
    return ChurnPlane(space, n_shards)


def pool_width() -> int:
    """Worker-pool parallelism (workers + caller thread), 1 w/o the lib.

    Honors ETPU_POOL_THREADS (pool.h): the churn worker-sweep bench pins
    it per subprocess."""
    lib = get_lib()
    if lib is None:
        return 1
    return int(lib.etpu_pool_width())


def match_host_verified(
    reg: FilterRegistry,
    tbuf: np.ndarray, toffs: np.ndarray, B: int,
    space,
    key_a: np.ndarray, key_b: np.ndarray, val: np.ndarray,
    log2cap: int, probe: int,
    incl: np.ndarray, k_a: np.ndarray, k_b: np.ndarray,
    min_len: np.ndarray, max_len: np.ndarray,
    wild_root: np.ndarray, valid: np.ndarray,
    vcap: int, coll_cap: int = 256,
):
    """Fused split+hash+probe+verify over a packed topic batch.

    Returns (fids [total] i32 row-major by topic, counts [B] i32,
    collisions [(topic_idx, fid), ...]) or None when the lib is absent."""
    lib = get_lib()
    if lib is None:
        return None
    c = np.ascontiguousarray
    L = incl.shape[1]
    M = valid.shape[0]
    vcap = max(vcap, 1)
    out_fid = np.empty(B * vcap, dtype=np.int32)
    out_cnt = np.zeros(max(B, 1), dtype=np.int32)
    out_coll = np.zeros(2 * coll_cap, dtype=np.int32)
    n_coll = ctypes.c_int32(0)
    wr = c(wild_root.astype(np.uint8, copy=False))
    vd = c(valid.astype(np.uint8, copy=False))
    lib.etpu_match_host_verified(
        reg.ptr,
        c(tbuf).ctypes.data_as(_u8p), c(toffs).ctypes.data_as(_i64p), B,
        space.max_levels,
        c(space.C[0]).ctypes.data_as(_u32p), c(space.C[1]).ctypes.data_as(_u32p),
        c(space.R[0]).ctypes.data_as(_u32p), c(space.R[1]).ctypes.data_as(_u32p),
        key_a.ctypes.data_as(_u32p), key_b.ctypes.data_as(_u32p),
        val.ctypes.data_as(_i32p), log2cap, probe,
        c(incl).ctypes.data_as(_u32p),
        c(k_a).ctypes.data_as(_u32p), c(k_b).ctypes.data_as(_u32p),
        c(min_len).ctypes.data_as(_i32p), c(max_len).ctypes.data_as(_i32p),
        wr.ctypes.data_as(_u8p), vd.ctypes.data_as(_u8p), M, L,
        out_fid.ctypes.data_as(_i32p), out_cnt.ctypes.data_as(_i32p), vcap,
        out_coll.ctypes.data_as(_i32p), coll_cap, ctypes.byref(n_coll),
    )
    cnt = out_cnt[:B]
    mat = out_fid.reshape(B, vcap) if B else out_fid.reshape(0, vcap)
    jj_mask = np.arange(vcap)[None, :] < cnt[:, None]
    fids = mat[jj_mask]
    nc = min(n_coll.value, coll_cap)
    colls = [(int(out_coll[2 * k]), int(out_coll[2 * k + 1]))
             for k in range(nc)]
    return fids, cnt, colls


def match_host_lists(
    reg: FilterRegistry, topics: list, space,
    key_a: np.ndarray, key_b: np.ndarray, val: np.ndarray,
    log2cap: int, probe: int,
    incl: np.ndarray, k_a: np.ndarray, k_b: np.ndarray,
    min_len: np.ndarray, max_len: np.ndarray,
    wild_root: np.ndarray, valid: np.ndarray, vcap: int,
):
    """Fused host match via the CPython extension: Python topic list in,
    per-topic fid LISTS out — no numpy masking, no per-call packing glue.

    Returns (rows, collisions) or None when the extension is absent (the
    caller falls back to match_host_verified).  All array arguments must
    be C-contiguous (they are the live table arrays, created contiguous);
    references are held here for the duration of the call.
    """
    ext = get_ext()
    if ext is None or not isinstance(topics, list):
        return None
    L = int(incl.shape[1])
    M = int(valid.shape[0])
    # keep direct references to every array whose address crosses the
    # boundary (no inline temporaries: the address must outlive the call)
    ca, cb = space.C[0], space.C[1]
    ra, rb = space.R[0], space.R[1]
    assert incl.flags.c_contiguous and key_a.flags.c_contiguous
    return ext.match_lists(
        reg.ptr, topics, space.max_levels,
        ca.ctypes.data, cb.ctypes.data, ra.ctypes.data, rb.ctypes.data,
        key_a.ctypes.data, key_b.ctypes.data, val.ctypes.data,
        log2cap, probe,
        incl.ctypes.data, k_a.ctypes.data, k_b.ctypes.data,
        min_len.ctypes.data, max_len.ctypes.data,
        wild_root.ctypes.data, valid.ctypes.data, M, L, max(vcap, 1),
    )


def verify_pairs_reg(reg: FilterRegistry, tbuf: np.ndarray, toffs: np.ndarray,
                     tidx: np.ndarray, fids: np.ndarray):
    """Registry-backed exact verification of device hash hits; bool per
    pair, or None when the lib is absent."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(fids)
    tidx = np.ascontiguousarray(tidx.astype(np.int32, copy=False))
    farr = np.ascontiguousarray(fids.astype(np.int32, copy=False))
    ok = np.zeros(max(n, 1), dtype=np.uint8)
    lib.etpu_verify_pairs_reg(
        reg.ptr, np.ascontiguousarray(tbuf).ctypes.data_as(_u8p),
        np.ascontiguousarray(toffs).ctypes.data_as(_i64p),
        tidx.ctypes.data_as(_i32p), farr.ctypes.data_as(_i32p), n,
        ok.ctypes.data_as(_u8p),
    )
    return ok[:n].astype(bool)


def bulk_place(key_a: np.ndarray, key_b: np.ndarray, val: np.ndarray,
               log2cap: int, probe: int,
               ha: np.ndarray, hb: np.ndarray, fids: np.ndarray):
    """In-place open-addressed placement; returns index of first failure or
    len(ha).  None when the lib is absent."""
    lib = get_lib()
    if lib is None:
        return None
    assert key_a.flags.c_contiguous and val.flags.c_contiguous
    c = np.ascontiguousarray
    ha = c(ha.astype(np.uint32, copy=False))
    hb = c(hb.astype(np.uint32, copy=False))
    fids = c(fids.astype(np.int32, copy=False))
    return lib.etpu_bulk_place(
        key_a.ctypes.data_as(_u32p), key_b.ctypes.data_as(_u32p),
        val.ctypes.data_as(_i32p), log2cap, probe,
        ha.ctypes.data_as(_u32p), hb.ctypes.data_as(_u32p),
        fids.ctypes.data_as(_i32p), len(ha),
    )


def bulk_place_slots(key_a: np.ndarray, key_b: np.ndarray, val: np.ndarray,
                     log2cap: int, probe: int,
                     ha: np.ndarray, hb: np.ndarray, fids: np.ndarray):
    """Incremental churn placement: returns (n_placed, slots[n]) where
    slots carries each key's chosen table index (for the device-mirror
    delta scatter), or None when the lib is absent."""
    lib = get_lib()
    if lib is None:
        return None
    assert key_a.flags.c_contiguous and val.flags.c_contiguous
    c = np.ascontiguousarray
    ha = c(ha.astype(np.uint32, copy=False))
    hb = c(hb.astype(np.uint32, copy=False))
    fids = c(fids.astype(np.int32, copy=False))
    out_slots = np.zeros(len(ha), dtype=np.int32)
    n = lib.etpu_bulk_place_slots(
        key_a.ctypes.data_as(_u32p), key_b.ctypes.data_as(_u32p),
        val.ctypes.data_as(_i32p), log2cap, probe,
        ha.ctypes.data_as(_u32p), hb.ctypes.data_as(_u32p),
        fids.ctypes.data_as(_i32p), len(ha),
        out_slots.ctypes.data_as(_i32p),
    )
    return n, out_slots


def drain_wait(fds: List[int], timeout_ms: int):
    """Block (GIL released by ctypes) until any doorbell fd is readable,
    read-clearing every ready eventfd.  Returns (ready_count, ready_mask)
    — count 0 on timeout, -1 on error — or None when the lib is absent
    (the drain thread falls back to select.poll)."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "etpu_drain_wait"):
        return None
    n = len(fds)
    arr = (ctypes.c_int32 * max(n, 1))(*fds)
    mask = ctypes.c_uint64(0)
    rc = lib.etpu_drain_wait(
        ctypes.cast(arr, _i32p), n, timeout_ms, ctypes.byref(mask)
    )
    return int(rc), int(mask.value)
