"""STOMP 1.2 gateway — `apps/emqx_gateway/src/stomp` analog.

Frame codec: command line, header lines (with STOMP 1.2 escaping),
blank line, body terminated by NUL; content-length bodies may contain
NULs.  Channel: CONNECT/STOMP -> CONNECTED (with login check through
the broker authn chain), SEND -> publish, SUBSCRIBE/UNSUBSCRIBE with
client subscription ids, MESSAGE delivery with subscription header,
RECEIPT for any frame carrying `receipt`, DISCONNECT, ERROR on
violations.  Destinations are MQTT topics verbatim (the reference maps
STOMP destinations straight onto topics).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Optional, Tuple

from ..broker.access_control import ClientInfo
from ..broker.broker import Broker
from .core import GatewayContext

log = logging.getLogger("emqx_tpu.gateway.stomp")

_ESCAPES = {"\\n": "\n", "\\c": ":", "\\r": "\r", "\\\\": "\\"}


def _unescape(s: str) -> str:
    out, i = [], 0
    while i < len(s):
        pair = s[i : i + 2]
        if pair in _ESCAPES:
            out.append(_ESCAPES[pair])
            i += 2
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


def _escape(s: str) -> str:
    return (
        s.replace("\\", "\\\\").replace("\r", "\\r").replace("\n", "\\n").replace(":", "\\c")
    )


class StompFrame:
    def __init__(self, command: str, headers: Optional[Dict[str, str]] = None,
                 body: bytes = b""):
        self.command = command
        self.headers = headers or {}
        self.body = body

    def serialize(self) -> bytes:
        lines = [self.command]
        headers = dict(self.headers)
        if self.body and "content-length" not in headers:
            headers["content-length"] = str(len(self.body))
        for k, v in headers.items():
            lines.append(f"{_escape(k)}:{_escape(str(v))}")
        return ("\n".join(lines) + "\n\n").encode() + self.body + b"\x00"

    def __repr__(self):
        return f"StompFrame({self.command}, {self.headers}, {self.body!r})"


class StompParser:
    """Incremental parser with content-length support."""

    def __init__(self, max_frame: int = 1_048_576):
        self.buf = b""
        self.max_frame = max_frame

    def feed(self, data: bytes) -> List[StompFrame]:
        self.buf += data
        if len(self.buf) > self.max_frame:
            raise ValueError("frame too large")
        out = []
        while True:
            frame = self._try_parse()
            if frame is None:
                return out
            if frame != "heartbeat":
                out.append(frame)

    def _try_parse(self):
        # heart-beats are bare EOLs between frames
        while self.buf[:1] in (b"\n", b"\r"):
            self.buf = self.buf[1:]
            return "heartbeat"
        if not self.buf:
            return None
        head_end = self.buf.find(b"\n\n")
        sep = 2
        if head_end < 0:
            head_end = self.buf.find(b"\r\n\r\n")
            sep = 4
            if head_end < 0:
                return None
        head = self.buf[:head_end].decode("utf-8", "replace")
        lines = [l.rstrip("\r") for l in head.split("\n")]
        command = lines[0].strip()
        headers: Dict[str, str] = {}
        for line in lines[1:]:
            if not line:
                continue
            k, _, v = line.partition(":")
            k = _unescape(k)
            if k not in headers:  # first occurrence wins (spec)
                headers[k] = _unescape(v)
        body_start = head_end + sep
        clen = headers.get("content-length")
        if clen is not None:
            n = int(clen)
            if len(self.buf) < body_start + n + 1:
                return None
            body = self.buf[body_start : body_start + n]
            if self.buf[body_start + n : body_start + n + 1] != b"\x00":
                raise ValueError("missing NUL after content-length body")
            self.buf = self.buf[body_start + n + 1 :]
        else:
            nul = self.buf.find(b"\x00", body_start)
            if nul < 0:
                return None
            body = self.buf[body_start:nul]
            self.buf = self.buf[nul + 1 :]
        return StompFrame(command, headers, body)


class StompChannel:
    def __init__(self, ctx: GatewayContext, writer: asyncio.StreamWriter,
                 peername: str):
        self.ctx = ctx
        self.writer = writer
        self.peername = peername
        self.clientid = ""
        self.session = None
        self.clientinfo: Optional[ClientInfo] = None
        self.connected = False
        self.closing = False
        # subscription id -> (destination, ack mode)
        self.subs: Dict[str, Tuple[str, str]] = {}
        self._msg_seq = 0

    # ------------------------------------------------------------ outbound

    def send(self, frame: StompFrame) -> None:
        try:
            self.writer.write(frame.serialize())
        except Exception:
            pass

    def error(self, message: str, receipt: Optional[str] = None) -> None:
        headers = {"message": message}
        if receipt:
            headers["receipt-id"] = receipt
        self.send(StompFrame("ERROR", headers, message.encode()))
        self.closing = True

    def deliver(self, delivers) -> None:
        """Broker deliveries -> MESSAGE frames (ChannelLike protocol)."""
        for filt, msg in delivers:
            for sub_id, (dest, _ack) in self.subs.items():
                if dest == filt:
                    self._msg_seq += 1
                    self.send(StompFrame(
                        "MESSAGE",
                        {
                            "subscription": sub_id,
                            "message-id": f"{self.clientid}-{self._msg_seq}",
                            "destination": msg.topic,
                            "content-type": "text/plain",
                        },
                        msg.payload,
                    ))
                    break

    def kick(self, rc: int = 0) -> None:
        self.error("kicked")
        try:
            self.writer.close()
        except Exception:
            pass

    # ------------------------------------------------------------- inbound

    def handle(self, frame: StompFrame) -> None:
        receipt = frame.headers.get("receipt")
        cmd = frame.command
        if not self.connected and cmd in ("CONNECT", "STOMP"):
            self._connect(frame)
            return
        if not self.connected:
            self.error("not connected")
            return
        if cmd == "SEND":
            self._send_cmd(frame)
        elif cmd == "SUBSCRIBE":
            self._subscribe(frame)
        elif cmd == "UNSUBSCRIBE":
            self._unsubscribe(frame)
        elif cmd == "DISCONNECT":
            self.closing = True
        elif cmd in ("ACK", "NACK", "BEGIN", "COMMIT", "ABORT"):
            pass  # transactions/acks accepted as no-ops (client mode auto)
        else:
            self.error(f"unknown command {cmd!r}", receipt)
            return
        if receipt and not self.closing:
            self.send(StompFrame("RECEIPT", {"receipt-id": receipt}))
        elif receipt and cmd == "DISCONNECT":
            self.send(StompFrame("RECEIPT", {"receipt-id": receipt}))

    def _connect(self, frame: StompFrame) -> None:
        login = frame.headers.get("login")
        passcode = frame.headers.get("passcode")
        self.clientid = frame.headers.get("client-id") or f"stomp-{id(self):x}"
        ci = ClientInfo(
            clientid=self.clientid,
            username=login,
            password=passcode.encode() if passcode else None,
            peerhost=self.peername,
            protocol="stomp",
        )
        self.clientinfo = ci
        if not self.ctx.authenticate(ci):
            self.error("authentication failed")
            return
        self.ctx.open_session(True, ci, self)
        self.connected = True
        self.send(StompFrame("CONNECTED", {
            "version": "1.2",
            "server": "emqx_tpu-stomp",
            "heart-beat": "0,0",
            "session": self.clientid,
        }))

    def _send_cmd(self, frame: StompFrame) -> None:
        dest = frame.headers.get("destination")
        if not dest:
            self.error("SEND needs destination")
            return
        if not self.ctx.authorize(self.clientinfo, "publish", dest):
            self.error(f"publish to {dest} denied")
            return
        self.ctx.publish(self.clientinfo, dest, frame.body)

    def _subscribe(self, frame: StompFrame) -> None:
        dest = frame.headers.get("destination")
        sub_id = frame.headers.get("id")
        if not dest or sub_id is None:
            self.error("SUBSCRIBE needs destination and id")
            return
        if not self.ctx.authorize(self.clientinfo, "subscribe", dest):
            self.error(f"subscribe to {dest} denied")
            return
        self.subs[sub_id] = (dest, frame.headers.get("ack", "auto"))
        self.ctx.subscribe(self, dest)

    def _unsubscribe(self, frame: StompFrame) -> None:
        sub_id = frame.headers.get("id")
        ent = self.subs.pop(sub_id, None)
        if ent is not None:
            self.ctx.unsubscribe(self, ent[0])


class StompGateway:
    def __init__(self, broker: Broker, host: str = "127.0.0.1", port: int = 0,
                 mountpoint: str = ""):
        self.ctx = GatewayContext(broker, "stomp", mountpoint)
        self.host = host
        self.port = port
        self._server: Optional[asyncio.base_events.Server] = None
        self._conns: set = set()

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._on_client, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("stomp gateway on %s:%s", self.host, self.port)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            for t in list(self._conns):
                t.cancel()
            if self._conns:
                await asyncio.gather(*self._conns, return_exceptions=True)
            await self._server.wait_closed()
            self._server = None

    async def _on_client(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        self._conns.add(task)
        peer = writer.get_extra_info("peername")
        ch = StompChannel(self.ctx, writer, peer[0] if peer else "?")
        parser = StompParser()
        try:
            while not ch.closing:
                data = await reader.read(65536)
                if not data:
                    break
                try:
                    frames = parser.feed(data)
                except ValueError as e:
                    ch.error(str(e))
                    break
                for f in frames:
                    ch.handle(f)
                    if ch.closing:
                        break
                await writer.drain()
        except asyncio.CancelledError:
            raise  # gateway stop cancels clients; finally closes the session
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            self._conns.discard(task)
            if ch.connected:
                self.ctx.close_session(ch)
            try:
                writer.close()
            except Exception:
                pass
