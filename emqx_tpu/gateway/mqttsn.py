"""MQTT-SN 1.2 gateway over UDP — `apps/emqx_gateway/src/mqttsn` analog.

Wire format per the MQTT-SN 1.2 spec: 1-byte (or 3-byte escaped)
length, message type, variable part.  Supported message set mirrors
the reference gateway's core path: SEARCHGW/GWINFO, CONNECT/CONNACK,
REGISTER/REGACK (both directions), PUBLISH/PUBACK (QoS 0/1),
SUBSCRIBE/SUBACK, UNSUBSCRIBE/UNSUBACK, PINGREQ/PINGRESP, DISCONNECT.
Topic-id registry per client; topic-id type 0 = registered, 1 =
predefined, 2 = two-char short names.  Subscriptions/publishes flow
through `GatewayContext`, so MQTT-SN sensors interoperate with MQTT
and STOMP clients on the same broker.
"""

from __future__ import annotations

import asyncio
import logging
import struct
from typing import Dict, Optional, Tuple

from ..broker.access_control import ClientInfo
from ..broker.broker import Broker
from .core import GatewayContext

log = logging.getLogger("emqx_tpu.gateway.mqttsn")

# message types
SEARCHGW = 0x01
GWINFO = 0x02
CONNECT = 0x04
CONNACK = 0x05
REGISTER = 0x0A
REGACK = 0x0B
PUBLISH = 0x0C
PUBACK = 0x0D
SUBSCRIBE = 0x12
SUBACK = 0x13
UNSUBSCRIBE = 0x14
UNSUBACK = 0x15
PINGREQ = 0x16
PINGRESP = 0x17
DISCONNECT = 0x18

RC_ACCEPTED = 0x00
RC_INVALID_TOPIC = 0x02
RC_NOT_SUPPORTED = 0x03

FLAG_DUP = 0x80
FLAG_QOS_MASK = 0x60
FLAG_RETAIN = 0x10
FLAG_CLEAN = 0x04
FLAG_TOPIC_TYPE = 0x03

TOPIC_NORMAL = 0  # registered topic id
TOPIC_PREDEF = 1
TOPIC_SHORT = 2


def mk(msg_type: int, body: bytes) -> bytes:
    n = len(body) + 2
    if n < 256:
        return bytes([n, msg_type]) + body
    return b"\x01" + struct.pack("!H", n + 2) + bytes([msg_type]) + body


def parse(datagram: bytes) -> Tuple[int, bytes]:
    if not datagram:
        raise ValueError("empty datagram")
    if datagram[0] == 0x01:
        (n,) = struct.unpack_from("!H", datagram, 1)
        if len(datagram) < n or n < 4:
            raise ValueError("bad length")
        return datagram[3], datagram[4:n]
    n = datagram[0]
    if len(datagram) < n or n < 2:
        raise ValueError("bad length")
    return datagram[1], datagram[2:n]


def qos_of(flags: int) -> int:
    q = (flags & FLAG_QOS_MASK) >> 5
    return 0 if q == 3 else q  # 0b11 = QoS -1 (publish-only) -> treat as 0


class SnClient:
    def __init__(self, addr, clientid: str):
        self.addr = addr
        self.clientid = clientid
        self.session = None
        self.clientinfo: Optional[ClientInfo] = None
        self.connected = False
        # topic registry, both directions
        self.topic_by_id: Dict[int, str] = {}
        self.id_by_topic: Dict[str, int] = {}
        self._next_topic_id = 1
        self._next_msg_id = 1
        self.gateway: Optional["MqttSnGateway"] = None

    def reg_topic(self, topic: str) -> int:
        tid = self.id_by_topic.get(topic)
        if tid is None:
            tid = self._next_topic_id
            self._next_topic_id += 1
            self.id_by_topic[topic] = tid
            self.topic_by_id[tid] = topic
        return tid

    def next_msg_id(self) -> int:
        mid = self._next_msg_id
        self._next_msg_id = mid % 0xFFFF + 1
        return mid

    # ChannelLike: broker -> datagrams
    def deliver(self, delivers) -> None:
        if self.gateway is None:
            return
        for _filt, msg in delivers:
            self.gateway.deliver_publish(self, msg)

    def kick(self, rc: int = 0) -> None:
        if self.gateway is not None:
            self.gateway.send(self.addr, mk(DISCONNECT, b""))
            self.gateway.drop_client(self)


class MqttSnGateway(asyncio.DatagramProtocol):
    def __init__(self, broker: Broker, host: str = "127.0.0.1", port: int = 0,
                 gateway_id: int = 1, predefined: Optional[Dict[int, str]] = None):
        self.ctx = GatewayContext(broker, "mqttsn")
        self.host = host
        self.port = port
        self.gateway_id = gateway_id
        self.predefined = predefined or {}
        self.clients: Dict[tuple, SnClient] = {}
        self.transport: Optional[asyncio.DatagramTransport] = None

    # ----------------------------------------------------------- lifecycle

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self.transport, _ = await loop.create_datagram_endpoint(
            lambda: self, local_addr=(self.host, self.port)
        )
        self.port = self.transport.get_extra_info("sockname")[1]
        log.info("mqtt-sn gateway on %s:%s", self.host, self.port)

    async def stop(self) -> None:
        for client in list(self.clients.values()):
            if client.connected:
                self.ctx.close_session(client)
        self.clients.clear()
        if self.transport is not None:
            self.transport.close()
            self.transport = None

    def send(self, addr, datagram: bytes) -> None:
        if self.transport is not None:
            self.transport.sendto(datagram, addr)

    def drop_client(self, client: SnClient) -> None:
        self.clients.pop(client.addr, None)

    # ------------------------------------------------------------ datagrams

    def datagram_received(self, data: bytes, addr) -> None:
        try:
            msg_type, body = parse(data)
        except ValueError:
            return
        handler = {
            SEARCHGW: self._searchgw,
            CONNECT: self._connect,
            REGISTER: self._register,
            PUBLISH: self._publish,
            SUBSCRIBE: self._subscribe,
            UNSUBSCRIBE: self._unsubscribe,
            PINGREQ: self._pingreq,
            DISCONNECT: self._disconnect,
            REGACK: lambda a, b: None,
            PUBACK: lambda a, b: None,
        }.get(msg_type)
        if handler is not None:
            try:
                handler(addr, body)
            except Exception:
                log.exception("mqtt-sn handler failed (type=%#x)", msg_type)

    def _searchgw(self, addr, body: bytes) -> None:
        self.send(addr, mk(GWINFO, bytes([self.gateway_id])))

    def _connect(self, addr, body: bytes) -> None:
        if len(body) < 4:
            return
        flags, _proto, _duration = body[0], body[1], struct.unpack_from("!H", body, 2)[0]
        clientid = body[4:].decode("utf-8", "replace") or f"sn-{addr[0]}-{addr[1]}"
        client = SnClient(addr, clientid)
        client.gateway = self
        ci = ClientInfo(clientid=clientid, peerhost=addr[0], protocol="mqtt-sn")
        client.clientinfo = ci
        if not self.ctx.authenticate(ci):
            self.send(addr, mk(CONNACK, bytes([RC_NOT_SUPPORTED])))
            return
        self.ctx.open_session(bool(flags & FLAG_CLEAN), ci, client)
        client.connected = True
        self.clients[addr] = client
        self.send(addr, mk(CONNACK, bytes([RC_ACCEPTED])))

    def _register(self, addr, body: bytes) -> None:
        client = self.clients.get(addr)
        if client is None or len(body) < 4:
            return
        _tid, msg_id = struct.unpack_from("!HH", body)
        topic = body[4:].decode("utf-8", "replace")
        tid = client.reg_topic(topic)
        self.send(addr, mk(REGACK, struct.pack("!HHB", tid, msg_id, RC_ACCEPTED)))

    def _resolve_topic(self, client: SnClient, flags: int, tid_bytes: bytes) -> Optional[str]:
        ttype = flags & FLAG_TOPIC_TYPE
        if ttype == TOPIC_SHORT:
            return tid_bytes.decode("utf-8", "replace").rstrip("\x00")
        (tid,) = struct.unpack("!H", tid_bytes)
        if ttype == TOPIC_PREDEF:
            return self.predefined.get(tid)
        return client.topic_by_id.get(tid)

    def _publish(self, addr, body: bytes) -> None:
        client = self.clients.get(addr)
        if len(body) < 5:
            return
        flags = body[0]
        msg_id = struct.unpack_from("!H", body, 3)[0]
        if client is None:
            return  # QoS -1 anonymous publish unsupported without predefined
        topic = self._resolve_topic(client, flags, body[1:3])
        qos = qos_of(flags)
        if topic is None:
            self.send(addr, mk(PUBACK, body[1:3] + struct.pack("!HB", msg_id, RC_INVALID_TOPIC)))
            return
        if not self.ctx.authorize(client.clientinfo, "publish", topic):
            self.send(addr, mk(PUBACK, body[1:3] + struct.pack("!HB", msg_id, RC_NOT_SUPPORTED)))
            return
        self.ctx.publish(client.clientinfo, topic, body[5:], qos=qos,
                         retain=bool(flags & FLAG_RETAIN))
        if qos >= 1:
            self.send(addr, mk(PUBACK, body[1:3] + struct.pack("!HB", msg_id, RC_ACCEPTED)))

    def _subscribe(self, addr, body: bytes) -> None:
        client = self.clients.get(addr)
        if client is None or len(body) < 3:
            return
        flags = body[0]
        (msg_id,) = struct.unpack_from("!H", body, 1)
        ttype = flags & FLAG_TOPIC_TYPE
        tid = 0
        if ttype == TOPIC_NORMAL:
            topic = body[3:].decode("utf-8", "replace")
            if "+" not in topic and "#" not in topic:
                tid = client.reg_topic(topic)
        else:
            topic = self._resolve_topic(client, flags, body[3:5])
        qos = qos_of(flags)
        if topic is None or not self.ctx.authorize(client.clientinfo, "subscribe", topic):
            self.send(addr, mk(SUBACK, struct.pack("!BHHB", 0, 0, msg_id, RC_INVALID_TOPIC)))
            return
        self.ctx.subscribe(client, topic, qos=qos)
        self.send(addr, mk(
            SUBACK, struct.pack("!BHHB", (qos << 5), tid, msg_id, RC_ACCEPTED)
        ))

    def _unsubscribe(self, addr, body: bytes) -> None:
        client = self.clients.get(addr)
        if client is None or len(body) < 3:
            return
        flags = body[0]
        (msg_id,) = struct.unpack_from("!H", body, 1)
        if flags & FLAG_TOPIC_TYPE == TOPIC_NORMAL:
            topic = body[3:].decode("utf-8", "replace")
        else:
            topic = self._resolve_topic(client, flags, body[3:5])
        if topic is not None:
            self.ctx.unsubscribe(client, topic)
        self.send(addr, mk(UNSUBACK, struct.pack("!H", msg_id)))

    def _pingreq(self, addr, body: bytes) -> None:
        self.send(addr, mk(PINGRESP, b""))

    def _disconnect(self, addr, body: bytes) -> None:
        client = self.clients.pop(addr, None)
        if client is not None and client.connected:
            self.ctx.close_session(client)
        self.send(addr, mk(DISCONNECT, b""))

    # ------------------------------------------------------------ outbound

    def deliver_publish(self, client: SnClient, msg) -> None:
        """Broker delivery -> REGISTER (if unknown topic id) + PUBLISH."""
        topic = msg.topic
        if len(topic) == 2 and "+" not in topic and "#" not in topic:
            flags = TOPIC_SHORT
            tid_bytes = topic.encode()
        else:
            if topic not in client.id_by_topic:
                tid = client.reg_topic(topic)
                self.send(client.addr, mk(
                    REGISTER,
                    struct.pack("!HH", tid, client.next_msg_id()) + topic.encode(),
                ))
            flags = TOPIC_NORMAL
            tid_bytes = struct.pack("!H", client.id_by_topic[topic])
        qos = min(msg.qos, 1)
        flags |= qos << 5
        if msg.retain:
            flags |= FLAG_RETAIN
        msg_id = client.next_msg_id() if qos else 0
        self.send(client.addr, mk(
            PUBLISH,
            bytes([flags]) + tid_bytes + struct.pack("!H", msg_id) + msg.payload,
        ))
