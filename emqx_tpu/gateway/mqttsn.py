"""MQTT-SN 1.2 gateway over UDP — `apps/emqx_gateway/src/mqttsn` analog.

Wire format per the MQTT-SN 1.2 spec: 1-byte (or 3-byte escaped)
length, message type, variable part.  Feature set mirrors the reference
gateway (`emqx_sn_gateway.erl`):

* SEARCHGW/GWINFO + periodic ADVERTISE;
* CONNECT with will setup (WILLTOPICREQ/WILLTOPIC/WILLMSGREQ/WILLMSG)
  and later will updates (WILLTOPICUPD/WILLMSGUPD);
* REGISTER/REGACK both directions; predefined and short topic ids;
* PUBLISH QoS 0/1/2 in both directions (PUBREC/PUBREL/PUBCOMP), plus
  QoS -1 publish-without-connect on predefined/short topics;
* SUBSCRIBE/UNSUBSCRIBE, PINGREQ/PINGRESP;
* sleeping clients: DISCONNECT(duration) parks the session, deliveries
  buffer, PINGREQ(clientid) drains them ("awake" cycle per spec 6.14);
* keepalive sweep: an expired client's will is published and its
  session closed (the reference's asleep/keepalive timers).

Subscriptions/publishes flow through `GatewayContext`, so MQTT-SN
sensors interoperate with MQTT/STOMP/CoAP clients on the same broker.
"""

from __future__ import annotations

import asyncio
import logging
import struct
import time
from typing import Dict, List, Optional, Tuple

from ..broker.access_control import ClientInfo
from ..broker.broker import Broker
from ..utils.net import UdpProtocolMixin
from .core import GatewayContext

log = logging.getLogger("emqx_tpu.gateway.mqttsn")

# message types
ADVERTISE = 0x00
SEARCHGW = 0x01
GWINFO = 0x02
CONNECT = 0x04
CONNACK = 0x05
WILLTOPICREQ = 0x06
WILLTOPIC = 0x07
WILLMSGREQ = 0x08
WILLMSG = 0x09
REGISTER = 0x0A
REGACK = 0x0B
PUBLISH = 0x0C
PUBACK = 0x0D
PUBCOMP = 0x0E
PUBREC = 0x0F
PUBREL = 0x10
SUBSCRIBE = 0x12
SUBACK = 0x13
UNSUBSCRIBE = 0x14
UNSUBACK = 0x15
PINGREQ = 0x16
PINGRESP = 0x17
DISCONNECT = 0x18
WILLTOPICUPD = 0x1A
WILLTOPICRESP = 0x1B
WILLMSGUPD = 0x1C
WILLMSGRESP = 0x1D

RC_ACCEPTED = 0x00
RC_CONGESTION = 0x01
RC_INVALID_TOPIC = 0x02
RC_NOT_SUPPORTED = 0x03

FLAG_DUP = 0x80
FLAG_QOS_MASK = 0x60
FLAG_RETAIN = 0x10
FLAG_WILL = 0x08
FLAG_CLEAN = 0x04
FLAG_TOPIC_TYPE = 0x03

TOPIC_NORMAL = 0  # registered topic id
TOPIC_PREDEF = 1
TOPIC_SHORT = 2

QOS_NEG1 = 3  # 0b11 in the QoS field: publish-without-connection


def mk(msg_type: int, body: bytes) -> bytes:
    n = len(body) + 2
    if n < 256:
        return bytes([n, msg_type]) + body
    return b"\x01" + struct.pack("!H", n + 2) + bytes([msg_type]) + body


def parse(datagram: bytes) -> Tuple[int, bytes]:
    if not datagram:
        raise ValueError("empty datagram")
    if datagram[0] == 0x01:
        if len(datagram) < 4:
            raise ValueError("truncated escaped length")
        (n,) = struct.unpack_from("!H", datagram, 1)
        if len(datagram) < n or n < 4:
            raise ValueError("bad length")
        return datagram[3], datagram[4:n]
    n = datagram[0]
    if len(datagram) < n or n < 2:
        raise ValueError("bad length")
    return datagram[1], datagram[2:n]


def qos_field(flags: int) -> int:
    return (flags & FLAG_QOS_MASK) >> 5


def qos_of(flags: int) -> int:
    q = qos_field(flags)
    return 0 if q == QOS_NEG1 else q


ACTIVE, ASLEEP, AWAKE = "active", "asleep", "awake"


class SnClient:
    def __init__(self, addr, clientid: str):
        self.addr = addr
        self.clientid = clientid
        self.session = None
        self.clientinfo: Optional[ClientInfo] = None
        self.connected = False
        self.state = ACTIVE
        self.keepalive = 0.0  # CONNECT duration (seconds)
        self.last_rx = time.monotonic()
        # topic registry, both directions
        self.topic_by_id: Dict[int, str] = {}
        self.id_by_topic: Dict[str, int] = {}
        self._next_topic_id = 1
        self._next_msg_id = 1
        self.gateway: Optional["MqttSnGateway"] = None
        # will state
        self.will_topic: Optional[str] = None
        self.will_msg: bytes = b""
        self.will_qos = 0
        self.will_retain = False
        self._pending_connect: Optional[tuple] = None  # (flags, duration)
        # QoS2 inbound: msg_id -> (topic, payload, retain)
        self.awaiting_rel: Dict[int, tuple] = {}
        # QoS2 outbound: msg_id -> awaiting PUBREC; then PUBCOMP
        self.wait_rec: Dict[int, object] = {}
        # buffered deliveries while asleep
        self.buffer: List[object] = []
        # True while a reconnect reuses this object: the cm's takeover
        # kick targets the "old connection", which IS this one — ignore it
        self.reconnecting = False

    def reg_topic(self, topic: str) -> int:
        tid = self.id_by_topic.get(topic)
        if tid is None:
            tid = self._next_topic_id
            self._next_topic_id += 1
            self.id_by_topic[topic] = tid
            self.topic_by_id[tid] = topic
        return tid

    def next_msg_id(self) -> int:
        mid = self._next_msg_id
        self._next_msg_id = mid % 0xFFFF + 1
        return mid

    # ChannelLike: broker -> datagrams
    def deliver(self, delivers) -> None:
        if self.gateway is None:
            return
        for _filt, msg in delivers:
            if self.state == ASLEEP:
                # spec 6.14: messages for a sleeping client are buffered
                # at the gateway until the next awake cycle
                self.buffer.append(msg)
                if len(self.buffer) > self.gateway.max_sleep_buffer:
                    self.buffer.pop(0)
            else:
                self.gateway.deliver_publish(self, msg)

    def kick(self, rc: int = 0) -> None:
        if self.reconnecting:
            return  # takeover kick of our own previous incarnation
        if self.gateway is not None:
            self.gateway.send(self.addr, mk(DISCONNECT, b""))
            self.gateway.drop_client(self)


class MqttSnGateway(UdpProtocolMixin, asyncio.DatagramProtocol):
    def __init__(self, broker: Broker, host: str = "127.0.0.1", port: int = 0,
                 gateway_id: int = 1, predefined: Optional[Dict[int, str]] = None,
                 advertise_interval: float = 0.0, advertise_addr=None,
                 max_sleep_buffer: int = 100, keepalive_factor: float = 1.5):
        self.ctx = GatewayContext(broker, "mqttsn")
        self.host = host
        self.port = port
        self.gateway_id = gateway_id
        self.predefined = dict(predefined or {})
        self.advertise_interval = advertise_interval
        self.advertise_addr = advertise_addr
        self.max_sleep_buffer = max_sleep_buffer
        self.keepalive_factor = keepalive_factor
        self.clients: Dict[tuple, SnClient] = {}
        self.transport: Optional[asyncio.DatagramTransport] = None
        self._tasks: List[asyncio.Task] = []

    # ----------------------------------------------------------- lifecycle

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self.transport, _ = await loop.create_datagram_endpoint(
            lambda: self, local_addr=(self.host, self.port)
        )
        self.port = self.transport.get_extra_info("sockname")[1]
        self._tasks.append(loop.create_task(self._keepalive_sweep()))
        if self.advertise_interval > 0 and self.advertise_addr is not None:
            self._tasks.append(loop.create_task(self._advertise_loop()))
        log.info("mqtt-sn gateway on %s:%s", self.host, self.port)

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks.clear()
        for client in list(self.clients.values()):
            if client.connected:
                self.ctx.close_session(client)
        self.clients.clear()
        if self.transport is not None:
            await self._close_transport(self.transport)
            self.transport = None

    async def _advertise_loop(self) -> None:
        """Periodic ADVERTISE (gwid + next interval), spec 6.1."""
        body = bytes([self.gateway_id]) + struct.pack(
            "!H", max(1, int(self.advertise_interval))
        )
        while True:
            self.send(self.advertise_addr, mk(ADVERTISE, body))
            await asyncio.sleep(self.advertise_interval)

    async def _keepalive_sweep(self) -> None:
        """Expire silent clients (active: keepalive window; asleep: the
        sleep duration rides the same field) and reap half-open will
        handshakes so a spoofed-source CONNECT flood cannot grow
        self.clients without bound."""
        while True:
            await asyncio.sleep(1.0)
            now = time.monotonic()
            for client in list(self.clients.values()):
                if not client.connected:
                    if (
                        client._pending_connect is not None
                        and now - client.last_rx > 15.0
                    ):
                        self.drop_client(client)
                    continue
                ka = client.keepalive
                if ka and now - client.last_rx > ka * self.keepalive_factor:
                    self._lost(client)

    def _lost(self, client: SnClient) -> None:
        """Keepalive/sleep expiry: fire the will, close the session."""
        if client.will_topic and client.clientinfo is not None:
            if self.ctx.authorize(
                client.clientinfo, "publish", client.will_topic
            ):
                self.ctx.publish(
                    client.clientinfo, client.will_topic, client.will_msg,
                    qos=client.will_qos, retain=client.will_retain,
                )
        if client.connected:
            self.ctx.close_session(client, normal=False)
            client.connected = False
        self.drop_client(client)

    def send(self, addr, datagram: bytes) -> None:
        if self.transport is not None:
            self.transport.sendto(datagram, addr)

    def drop_client(self, client: SnClient) -> None:
        self.clients.pop(client.addr, None)

    # ------------------------------------------------------------ datagrams

    def datagram_received(self, data: bytes, addr) -> None:
        try:
            msg_type, body = parse(data)
        except ValueError:
            return
        client = self.clients.get(addr)
        if client is not None:
            client.last_rx = time.monotonic()
        handler = {
            SEARCHGW: self._searchgw,
            CONNECT: self._connect,
            WILLTOPIC: self._willtopic,
            WILLMSG: self._willmsg,
            WILLTOPICUPD: self._willtopicupd,
            WILLMSGUPD: self._willmsgupd,
            REGISTER: self._register,
            PUBLISH: self._publish,
            PUBREL: self._pubrel,
            PUBREC: self._pubrec,
            PUBCOMP: lambda a, b: None,
            SUBSCRIBE: self._subscribe,
            UNSUBSCRIBE: self._unsubscribe,
            PINGREQ: self._pingreq,
            DISCONNECT: self._disconnect,
            REGACK: lambda a, b: None,
            PUBACK: lambda a, b: None,
        }.get(msg_type)
        if handler is not None:
            try:
                handler(addr, body)
            except Exception:
                log.exception("mqtt-sn handler failed (type=%#x)", msg_type)

    def _searchgw(self, addr, body: bytes) -> None:
        self.send(addr, mk(GWINFO, bytes([self.gateway_id])))

    # ------------------------------------------------------------- connect

    def _find_by_clientid(self, clientid: str) -> Optional[SnClient]:
        for c in self.clients.values():
            if c.clientid == clientid:
                return c
        return None

    def _rebind(self, client: SnClient, addr) -> None:
        """A known device reappears from a new source address (NAT
        rebind): move its state, never leave a stale entry for the
        keepalive sweep to fire the will on."""
        if client.addr != addr:
            self.clients.pop(client.addr, None)
            client.addr = addr
            self.clients[addr] = client

    def _connect(self, addr, body: bytes) -> None:
        if len(body) < 4:
            return
        flags, _proto = body[0], body[1]
        (duration,) = struct.unpack_from("!H", body, 2)
        clientid = body[4:].decode("utf-8", "replace") or f"sn-{addr[0]}-{addr[1]}"
        existing = self._find_by_clientid(clientid)
        if existing is not None:
            # returning device (possibly a waking sleeper): keep its
            # buffered deliveries, topic registry, and will state
            self._rebind(existing, addr)
            client = existing
            client.last_rx = time.monotonic()
        else:
            client = SnClient(addr, clientid)
            client.gateway = self
        client.keepalive = float(duration)
        ci = ClientInfo(clientid=clientid, peerhost=addr[0], protocol="mqtt-sn")
        client.clientinfo = ci
        if not self.ctx.authenticate(ci):
            self.send(addr, mk(CONNACK, bytes([RC_NOT_SUPPORTED])))
            return
        self.clients[addr] = client
        if flags & FLAG_WILL:
            # three-way will setup before CONNACK (spec 6.3)
            client._pending_connect = (flags, duration)
            self.send(addr, mk(WILLTOPICREQ, b""))
            return
        self._finish_connect(client, flags)

    def _finish_connect(self, client: SnClient, flags: int) -> None:
        # the takeover kick during open_session targets this same object
        # when the device is reconnecting; scoping the flag here (not in
        # _connect) guarantees it can never stick on an aborted handshake
        client.reconnecting = True
        try:
            self.ctx.open_session(
                bool(flags & FLAG_CLEAN), client.clientinfo, client
            )
        finally:
            client.reconnecting = False
        client.connected = True
        client.state = ACTIVE
        self.send(client.addr, mk(CONNACK, bytes([RC_ACCEPTED])))
        # returning sleeper resumed by reconnect: drain anything buffered
        self._drain_buffer(client)

    def _willtopic(self, addr, body: bytes) -> None:
        client = self.clients.get(addr)
        if client is None or client._pending_connect is None:
            return
        if body:
            wflags = body[0]
            client.will_topic = body[1:].decode("utf-8", "replace")
            client.will_qos = qos_of(wflags)
            client.will_retain = bool(wflags & FLAG_RETAIN)
            self.send(addr, mk(WILLMSGREQ, b""))
        else:  # empty WILLTOPIC = no will after all
            flags, _ = client._pending_connect
            client._pending_connect = None
            self._finish_connect(client, flags)

    def _willmsg(self, addr, body: bytes) -> None:
        client = self.clients.get(addr)
        if client is None or client._pending_connect is None:
            return
        client.will_msg = bytes(body)
        flags, _ = client._pending_connect
        client._pending_connect = None
        self._finish_connect(client, flags)

    def _willtopicupd(self, addr, body: bytes) -> None:
        client = self.clients.get(addr)
        if client is None:
            return
        if body:
            wflags = body[0]
            client.will_topic = body[1:].decode("utf-8", "replace")
            client.will_qos = qos_of(wflags)
            client.will_retain = bool(wflags & FLAG_RETAIN)
        else:
            client.will_topic = None  # empty update deletes the will
            client.will_msg = b""
        self.send(addr, mk(WILLTOPICRESP, bytes([RC_ACCEPTED])))

    def _willmsgupd(self, addr, body: bytes) -> None:
        client = self.clients.get(addr)
        if client is None:
            return
        client.will_msg = bytes(body)
        self.send(addr, mk(WILLMSGRESP, bytes([RC_ACCEPTED])))

    # ------------------------------------------------------------ registry

    def _register(self, addr, body: bytes) -> None:
        client = self.clients.get(addr)
        if client is None or len(body) < 4:
            return
        _tid, msg_id = struct.unpack_from("!HH", body)
        topic = body[4:].decode("utf-8", "replace")
        tid = client.reg_topic(topic)
        self.send(addr, mk(REGACK, struct.pack("!HHB", tid, msg_id, RC_ACCEPTED)))

    def _resolve_topic(self, client: Optional[SnClient], flags: int,
                       tid_bytes: bytes) -> Optional[str]:
        ttype = flags & FLAG_TOPIC_TYPE
        if ttype == TOPIC_SHORT:
            return tid_bytes.decode("utf-8", "replace").rstrip("\x00")
        (tid,) = struct.unpack("!H", tid_bytes)
        if ttype == TOPIC_PREDEF:
            return self.predefined.get(tid)
        return client.topic_by_id.get(tid) if client is not None else None

    # ------------------------------------------------------------- publish

    def _publish(self, addr, body: bytes) -> None:
        client = self.clients.get(addr)
        if len(body) < 5:
            return
        flags = body[0]
        msg_id = struct.unpack_from("!H", body, 3)[0]
        if client is None:
            # QoS -1: publish without a connection, predefined/short
            # topics only (spec 6.8; `emqx_sn_gateway` idle-state publish)
            if qos_field(flags) == QOS_NEG1 and (
                flags & FLAG_TOPIC_TYPE in (TOPIC_PREDEF, TOPIC_SHORT)
            ):
                topic = self._resolve_topic(None, flags, body[1:3])
                if topic:
                    anon = ClientInfo(
                        clientid=f"sn-anon-{addr[0]}", peerhost=addr[0],
                        protocol="mqtt-sn",
                    )
                    if self.ctx.authorize(anon, "publish", topic):
                        self.ctx.publish(
                            anon, topic, body[5:], qos=0,
                            retain=bool(flags & FLAG_RETAIN),
                        )
            return
        topic = self._resolve_topic(client, flags, body[1:3])
        qos = qos_of(flags)
        if topic is None:
            self.send(addr, mk(PUBACK, body[1:3] + struct.pack("!HB", msg_id, RC_INVALID_TOPIC)))
            return
        if not self.ctx.authorize(client.clientinfo, "publish", topic):
            self.send(addr, mk(PUBACK, body[1:3] + struct.pack("!HB", msg_id, RC_NOT_SUPPORTED)))
            return
        if qos == 2:
            # exactly-once inbound: park until PUBREL (spec 6.13)
            client.awaiting_rel[msg_id] = (
                topic, body[5:], bool(flags & FLAG_RETAIN)
            )
            self.send(addr, mk(PUBREC, struct.pack("!H", msg_id)))
            return
        self.ctx.publish(client.clientinfo, topic, body[5:], qos=qos,
                         retain=bool(flags & FLAG_RETAIN))
        if qos == 1:
            self.send(addr, mk(PUBACK, body[1:3] + struct.pack("!HB", msg_id, RC_ACCEPTED)))

    def _pubrel(self, addr, body: bytes) -> None:
        client = self.clients.get(addr)
        if client is None or len(body) < 2:
            return
        (msg_id,) = struct.unpack_from("!H", body)
        parked = client.awaiting_rel.pop(msg_id, None)
        if parked is not None:
            topic, payload, retain = parked
            self.ctx.publish(client.clientinfo, topic, payload, qos=2,
                             retain=retain)
        self.send(addr, mk(PUBCOMP, struct.pack("!H", msg_id)))

    def _pubrec(self, addr, body: bytes) -> None:
        client = self.clients.get(addr)
        if client is None or len(body) < 2:
            return
        (msg_id,) = struct.unpack_from("!H", body)
        if msg_id in client.wait_rec:
            client.wait_rec.pop(msg_id, None)
            self.send(addr, mk(PUBREL, struct.pack("!H", msg_id)))

    # ----------------------------------------------------------- subscribe

    def _subscribe(self, addr, body: bytes) -> None:
        client = self.clients.get(addr)
        if client is None or len(body) < 3:
            return
        flags = body[0]
        (msg_id,) = struct.unpack_from("!H", body, 1)
        ttype = flags & FLAG_TOPIC_TYPE
        tid = 0
        if ttype == TOPIC_NORMAL:
            topic = body[3:].decode("utf-8", "replace")
            if "+" not in topic and "#" not in topic:
                tid = client.reg_topic(topic)
        else:
            topic = self._resolve_topic(client, flags, body[3:5])
        qos = qos_of(flags)
        if topic is None or not self.ctx.authorize(client.clientinfo, "subscribe", topic):
            self.send(addr, mk(SUBACK, struct.pack("!BHHB", 0, 0, msg_id, RC_INVALID_TOPIC)))
            return
        self.ctx.subscribe(client, topic, qos=qos)
        self.send(addr, mk(
            SUBACK, struct.pack("!BHHB", (qos << 5), tid, msg_id, RC_ACCEPTED)
        ))

    def _unsubscribe(self, addr, body: bytes) -> None:
        client = self.clients.get(addr)
        if client is None or len(body) < 3:
            return
        flags = body[0]
        (msg_id,) = struct.unpack_from("!H", body, 1)
        if flags & FLAG_TOPIC_TYPE == TOPIC_NORMAL:
            topic = body[3:].decode("utf-8", "replace")
        else:
            topic = self._resolve_topic(client, flags, body[3:5])
        if topic is not None:
            self.ctx.unsubscribe(client, topic)
        self.send(addr, mk(UNSUBACK, struct.pack("!H", msg_id)))

    # --------------------------------------------------------- sleep cycle

    def _pingreq(self, addr, body: bytes) -> None:
        if body:
            # PINGREQ with clientid = a sleeper's awake cycle (spec 6.14):
            # drain buffered messages, then PINGRESP, back to sleep
            clientid = body.decode("utf-8", "replace")
            client = self.clients.get(addr)
            if client is None or client.clientid != clientid:
                client = self._find_by_clientid(clientid)
            if client is not None and client.state == ASLEEP:
                # the device may wake from a new source port (NAT rebind):
                # deliveries must chase the PINGREQ's address
                self._rebind(client, addr)
                client.state = AWAKE
                self._drain_buffer(client)
                client.state = ASLEEP
                client.last_rx = time.monotonic()
        self.send(addr, mk(PINGRESP, b""))

    def _drain_buffer(self, client: SnClient) -> None:
        buffered, client.buffer = client.buffer, []
        for msg in buffered:
            self.deliver_publish(client, msg)

    def _disconnect(self, addr, body: bytes) -> None:
        client = self.clients.get(addr)
        if client is None:
            self.send(addr, mk(DISCONNECT, b""))
            return
        if len(body) >= 2:
            # DISCONNECT(duration): enter sleep, keep the session parked
            (duration,) = struct.unpack_from("!H", body)
            client.state = ASLEEP
            client.keepalive = float(duration)
            client.last_rx = time.monotonic()
            self.send(addr, mk(DISCONNECT, b""))
            return
        self.clients.pop(addr, None)
        if client.connected:
            client.will_topic = None  # clean disconnect cancels the will
            self.ctx.close_session(client)
            client.connected = False
        self.send(addr, mk(DISCONNECT, b""))

    # ------------------------------------------------------------ outbound

    def deliver_publish(self, client: SnClient, msg) -> None:
        """Broker delivery -> REGISTER (if unknown topic id) + PUBLISH."""
        topic = msg.topic
        if len(topic) == 2 and "+" not in topic and "#" not in topic:
            flags = TOPIC_SHORT
            tid_bytes = topic.encode()
        else:
            if topic not in client.id_by_topic:
                tid = client.reg_topic(topic)
                self.send(client.addr, mk(
                    REGISTER,
                    struct.pack("!HH", tid, client.next_msg_id()) + topic.encode(),
                ))
            flags = TOPIC_NORMAL
            tid_bytes = struct.pack("!H", client.id_by_topic[topic])
        qos = min(msg.qos, 2)
        flags |= qos << 5
        if msg.retain:
            flags |= FLAG_RETAIN
        msg_id = client.next_msg_id() if qos else 0
        if qos == 2:
            client.wait_rec[msg_id] = msg
        self.send(client.addr, mk(
            PUBLISH,
            bytes([flags]) + tid_bytes + struct.pack("!H", msg_id) + msg.payload,
        ))
