"""LwM2M gateway over CoAP/UDP — `apps/emqx_gateway/src/lwm2m` analog.

Implements the LwM2M 1.0 client-registration interface and the
MQTT<->LwM2M command mapping of the reference
(`emqx_lwm2m_channel.erl`, `emqx_lwm2m_session.erl`, `emqx_lwm2m_cmd.erl`):

- **Register**: `POST /rd?ep={endpoint}&lt={lifetime}&lwm2m={ver}&b={binding}`
  with a CoRE link-format payload of object instances.  Replies 2.01
  Created + `Location-Path: rd/{loc}`.  Update `POST /rd/{loc}` -> 2.04;
  deregister `DELETE /rd/{loc}` -> 2.02.
- **Uplink topics** (`emqx_lwm2m_session.erl:640-652`):
  register/update events -> `lwm2m/{ep}/up/resp`; observe notifications
  -> `lwm2m/{ep}/up/notify`.
- **Downlink**: the gateway subscribes each endpoint to
  `lwm2m/{ep}/dn/#`.  JSON commands `{reqID, msgType, data:{path,...}}`
  with msgType read/write/execute/discover/observe/cancel-observe are
  translated to CoAP requests to the device; device responses come back
  on `up/resp` as `{reqID, msgType, data:{code, codeMsg, content}}`.
- **TLV**: `application/vnd.oma.lwm2m+tlv` (ct=11542) payloads are
  decoded with an OMA-TLV codec (`emqx_lwm2m_tlv.erl` analog) into
  `{type, id, value}` entries; other content-formats pass through as
  text.
"""

from __future__ import annotations

import asyncio
import json
import logging
import struct
import time
from typing import Dict, List, Optional, Tuple, Union

from ..broker.access_control import ClientInfo
from ..broker.broker import Broker
from .coap import (
    ACK, CON, NON, RST,
    GET, POST, PUT, DELETE,
    CREATED, CHANGED, DELETED, BAD_REQUEST, UNAUTHORIZED, NOT_FOUND,
    OPT_OBSERVE, OPT_URI_PATH, OPT_CONTENT_FORMAT,
    CoapMessage, parse, serialize,
)
from ..utils.net import UdpProtocolMixin
from .core import GatewayContext

log = logging.getLogger("emqx_tpu.gateway.lwm2m")

OPT_LOCATION_PATH = 8
OPT_ACCEPT = 17

CT_LINK_FORMAT = 40
CT_LWM2M_TLV = 11542
CT_LWM2M_JSON = 11543

# TLV identifier types (OMA-TS-LightweightM2M §6.3.3)
TLV_OBJECT_INSTANCE = 0
TLV_RESOURCE_INSTANCE = 1
TLV_MULTI_RESOURCE = 2
TLV_RESOURCE = 3

_TLV_NAMES = {
    TLV_OBJECT_INSTANCE: "obj_inst",
    TLV_RESOURCE_INSTANCE: "res_inst",
    TLV_MULTI_RESOURCE: "multi_res",
    TLV_RESOURCE: "resource",
}


# ------------------------------------------------------------------ TLV codec

TlvEntry = Dict[str, Union[int, str, bytes, list]]


def tlv_decode(data: bytes) -> List[TlvEntry]:
    """Decode OMA-TLV into [{type, id, value}] (nested for containers)."""
    out: List[TlvEntry] = []
    pos = 0
    while pos < len(data):
        b0 = data[pos]
        pos += 1
        ident_type = (b0 >> 6) & 0x3
        ident_len = 2 if b0 & 0x20 else 1
        len_type = (b0 >> 3) & 0x3
        ident = int.from_bytes(data[pos:pos + ident_len], "big")
        pos += ident_len
        if len_type == 0:
            length = b0 & 0x7
        else:
            length = int.from_bytes(data[pos:pos + len_type], "big")
            pos += len_type
        val = data[pos:pos + length]
        if len(val) != length:
            raise ValueError("truncated TLV")
        pos += length
        entry: TlvEntry = {"type": _TLV_NAMES[ident_type], "id": ident}
        if ident_type in (TLV_OBJECT_INSTANCE, TLV_MULTI_RESOURCE):
            entry["value"] = tlv_decode(val)
        else:
            entry["value"] = _tlv_value(val)
        out.append(entry)
    return out


def _tlv_value(val: bytes) -> Union[int, str]:
    """Leaf values: decode as UTF-8 when printable, else big-endian int."""
    try:
        s = val.decode("utf-8")
        if s.isprintable():
            return s
    except UnicodeDecodeError:
        pass
    return int.from_bytes(val, "big") if val else 0


def tlv_encode(entries: List[TlvEntry]) -> bytes:
    out = bytearray()
    names = {v: k for k, v in _TLV_NAMES.items()}
    for e in entries:
        ident_type = names[str(e["type"])]
        ident = int(e["id"])  # type: ignore[arg-type]
        v = e["value"]
        if isinstance(v, list):
            payload = tlv_encode(v)
        elif isinstance(v, bytes):
            payload = v
        elif isinstance(v, int):
            n = max(1, (v.bit_length() + 7) // 8)
            payload = v.to_bytes(n, "big")
        else:
            payload = str(v).encode()
        b0 = ident_type << 6
        if ident > 0xFF:
            b0 |= 0x20
            ident_b = struct.pack("!H", ident)
        else:
            ident_b = bytes([ident])
        n = len(payload)
        if n < 8:
            out += bytes([b0 | n]) + ident_b
        elif n < 256:
            out += bytes([b0 | 0x08]) + ident_b + bytes([n])
        elif n < 65536:
            out += bytes([b0 | 0x10]) + ident_b + struct.pack("!H", n)
        else:
            out += bytes([b0 | 0x18]) + ident_b + n.to_bytes(3, "big")
        out += payload
    return bytes(out)


def code_str(code: int) -> Tuple[str, str]:
    """CoAP response code -> ("2.05", "content") like emqx_lwm2m_cmd."""
    cls, detail = code >> 5, code & 0x1F
    names = {
        0x41: "created", 0x42: "deleted", 0x43: "valid", 0x44: "changed",
        0x45: "content", 0x80: "bad_request", 0x81: "unauthorized",
        0x83: "forbidden", 0x84: "not_found", 0x85: "method_not_allowed",
        0xA0: "internal_server_error",
    }
    return f"{cls}.{detail:02d}", names.get(code, "unknown")


# ------------------------------------------------------------------ endpoint

class Lwm2mEndpoint:
    """One registered device: broker session + pending downlink commands."""

    def __init__(self, addr, endpoint: str, location: str):
        self.addr = addr
        self.endpoint = endpoint
        self.location = location
        self.lifetime = 86400
        self.version = "1.0"
        self.binding = "U"
        self.object_list: List[str] = []
        self.registered_at = time.monotonic()
        self.session = None
        self.clientid = endpoint
        self.clientinfo: Optional[ClientInfo] = None
        self.connected = False
        # coap token -> (reqID, msgType, observe-path or None)
        self.pending: Dict[bytes, Tuple[object, str, Optional[str]]] = {}
        # observe path -> token
        self.observations: Dict[str, bytes] = {}
        self.gateway: Optional["Lwm2mGateway"] = None
        self._next_token = 1
        self._next_msg_id = 1

    def alive(self) -> bool:
        return time.monotonic() - self.registered_at < self.lifetime

    def new_token(self) -> bytes:
        t = self._next_token
        self._next_token = (t + 1) % 0xFFFFFF or 1
        return t.to_bytes(3, "big")

    def next_msg_id(self) -> int:
        mid = self._next_msg_id
        self._next_msg_id = mid % 0xFFFF + 1
        return mid

    # ChannelLike: downlink MQTT messages -> CoAP commands
    def deliver(self, delivers) -> None:
        if self.gateway is None:
            return
        for _filt, msg in delivers:
            self.gateway.send_command(self, msg)

    def kick(self, rc: int = 0) -> None:
        if self.gateway is not None:
            self.gateway.drop_endpoint(self)


class Lwm2mGateway(UdpProtocolMixin, asyncio.DatagramProtocol):
    """UDP server on the LwM2M port (default 5683 in the reference conf)."""

    def __init__(self, broker: Broker, host: str = "127.0.0.1", port: int = 0,
                 mountpoint: str = "lwm2m", qos: int = 0):
        self.ctx = GatewayContext(broker, "lwm2m")
        self.host = host
        self.port = port
        self.mountpoint = mountpoint
        self.qos = qos
        self.by_addr: Dict[tuple, Lwm2mEndpoint] = {}
        self.by_location: Dict[str, Lwm2mEndpoint] = {}
        self.transport: Optional[asyncio.DatagramTransport] = None
        self._next_loc = 1
        self._sweeper: Optional[asyncio.Task] = None
        self.sweep_interval = 30.0

    # ----------------------------------------------------------- lifecycle

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self.transport, _ = await loop.create_datagram_endpoint(
            lambda: self, local_addr=(self.host, self.port)
        )
        self.port = self.transport.get_extra_info("sockname")[1]
        self._sweeper = loop.create_task(self._sweep_loop())
        log.info("lwm2m gateway on %s:%s", self.host, self.port)

    async def stop(self) -> None:
        if self._sweeper is not None:
            self._sweeper.cancel()
            self._sweeper = None
        for ep in list(self.by_addr.values()):
            if ep.connected:
                self.ctx.close_session(ep)
        self.by_addr.clear()
        self.by_location.clear()
        if self.transport is not None:
            await self._close_transport(self.transport)
            self.transport = None

    async def _sweep_loop(self) -> None:
        """Expire registrations whose lifetime lapsed without an update."""
        while True:
            await asyncio.sleep(self.sweep_interval)
            for ep in list(self.by_location.values()):
                if not ep.alive():
                    if ep.connected:
                        self.ctx.close_session(ep)
                        ep.connected = False
                    self.drop_endpoint(ep)

    def send(self, addr, msg: CoapMessage) -> None:
        if self.transport is not None:
            self.transport.sendto(serialize(msg), addr)

    def drop_endpoint(self, ep: Lwm2mEndpoint) -> None:
        self.by_addr.pop(ep.addr, None)
        self.by_location.pop(ep.location, None)

    # ------------------------------------------------------------- topics

    def up_topic(self, ep: Lwm2mEndpoint, kind: str) -> str:
        sub = "up/notify" if kind == "notify" else "up/resp"
        return f"{self.mountpoint}/{ep.endpoint}/{sub}"

    def dn_filter(self, ep: Lwm2mEndpoint) -> str:
        return f"{self.mountpoint}/{ep.endpoint}/dn/#"

    def publish_up(self, ep: Lwm2mEndpoint, kind: str, body: dict) -> None:
        self.ctx.publish(ep.clientinfo, self.up_topic(ep, kind),
                         json.dumps(body).encode(), qos=self.qos)

    # ------------------------------------------------------------- inbound

    def datagram_received(self, data: bytes, addr) -> None:
        try:
            msg = parse(data)
        except ValueError:
            return
        try:
            if msg.code in (GET, POST, PUT, DELETE):
                self._handle_request(addr, msg)
            elif msg.code == 0:
                if msg.type == CON:
                    self.send(addr, CoapMessage(RST, 0, msg.msg_id))
            else:
                self._handle_response(addr, msg)
        except Exception:
            log.exception("lwm2m handler failed")

    def _reply(self, addr, req: CoapMessage, code: int,
               options=None, payload: bytes = b"") -> None:
        mtype = ACK if req.type == CON else NON
        self.send(addr, CoapMessage(mtype, code, req.msg_id, req.token,
                                    options or [], payload))

    # -------------------------------------------------- registration iface

    def _handle_request(self, addr, msg: CoapMessage) -> None:
        path = msg.uri_path()
        if not path or path[0] != "rd":
            self._reply(addr, msg, NOT_FOUND)
            return
        if msg.code == POST and len(path) == 1:
            self._register(addr, msg)
        elif msg.code == POST and len(path) == 2:
            self._update(addr, msg, path[1])
        elif msg.code == DELETE and len(path) == 2:
            self._deregister(addr, msg, path[1])
        else:
            self._reply(addr, msg, BAD_REQUEST)

    def _register(self, addr, msg: CoapMessage) -> None:
        q = msg.uri_queries()
        endpoint = q.get("ep")
        if not endpoint:
            self._reply(addr, msg, BAD_REQUEST)
            return
        loc = str(self._next_loc)
        self._next_loc += 1
        ep = Lwm2mEndpoint(addr, endpoint, loc)
        ep.gateway = self
        ep.lifetime = int(q.get("lt", "86400") or 86400)
        ep.version = q.get("lwm2m", "1.0")
        ep.binding = q.get("b", "U")
        ep.object_list = [
            s.strip().strip("<>;") for s in msg.payload.decode("utf-8", "replace").split(",")
            if s.strip()
        ]
        ci = ClientInfo(clientid=endpoint, username=q.get("imei") or endpoint,
                        peerhost=addr[0], protocol="lwm2m")
        ep.clientinfo = ci
        # authenticate BEFORE touching any existing registration: a failing
        # (spoofable-UDP) register attempt must not tear down a live session
        if not self.ctx.authenticate(ci):
            self._reply(addr, msg, UNAUTHORIZED)
            return
        old = self.by_addr.get(addr)
        if old is not None and old.connected:
            self.ctx.close_session(old)
            self.drop_endpoint(old)
        self.ctx.open_session(True, ci, ep)
        ep.connected = True
        self.by_addr[addr] = ep
        self.by_location[loc] = ep
        # subscribe the endpoint to its downlink command topic
        self.ctx.subscribe(ep, self.dn_filter(ep), qos=self.qos)
        self._reply(addr, msg, CREATED,
                    options=[(OPT_LOCATION_PATH, b"rd"),
                             (OPT_LOCATION_PATH, loc.encode())])
        self.publish_up(ep, "register", {
            "msgType": "register",
            "data": {
                "ep": ep.endpoint, "lt": ep.lifetime, "lwm2m": ep.version,
                "b": ep.binding, "alternatePath": "/",
                "objectList": ep.object_list,
            },
        })

    def _update(self, addr, msg: CoapMessage, loc: str) -> None:
        ep = self.by_location.get(loc)
        if ep is None:
            self._reply(addr, msg, NOT_FOUND)
            return
        q = msg.uri_queries()
        if "lt" in q:
            ep.lifetime = int(q["lt"] or ep.lifetime)
        ep.registered_at = time.monotonic()
        if ep.addr != addr:  # NAT rebind: retire the old address key
            self.by_addr.pop(ep.addr, None)
        ep.addr = addr
        self.by_addr[addr] = ep
        if msg.payload:
            ep.object_list = [
                s.strip().strip("<>;") for s in msg.payload.decode("utf-8", "replace").split(",")
                if s.strip()
            ]
        self._reply(addr, msg, CHANGED)
        self.publish_up(ep, "update", {
            "msgType": "update",
            "data": {"ep": ep.endpoint, "lt": ep.lifetime,
                     "objectList": ep.object_list},
        })

    def _deregister(self, addr, msg: CoapMessage, loc: str) -> None:
        ep = self.by_location.get(loc)
        if ep is None:
            self._reply(addr, msg, NOT_FOUND)
            return
        self._reply(addr, msg, DELETED)
        if ep.connected:
            self.ctx.close_session(ep)
            ep.connected = False
        self.drop_endpoint(ep)

    # ----------------------------------------------- downlink MQTT -> CoAP

    def send_command(self, ep: Lwm2mEndpoint, msg) -> None:
        """Translate `lwm2m/{ep}/dn` JSON command to a CoAP request
        (`emqx_lwm2m_cmd.erl` mqtt_to_coap semantics)."""
        try:
            cmd = json.loads(msg.payload.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            log.warning("lwm2m: bad downlink payload for %s", ep.endpoint)
            return
        msg_type = cmd.get("msgType", "")
        data = cmd.get("data", {}) or {}
        path = str(data.get("path", "")).strip()
        if not path.startswith("/"):
            path = "/" + path
        req_id = cmd.get("reqID")
        segs = [s for s in path.split("/") if s]
        opts: List[Tuple[int, bytes]] = [(OPT_URI_PATH, s.encode()) for s in segs]
        token = ep.new_token()
        observe_path: Optional[str] = None

        if msg_type == "read":
            code = GET
            payload = b""
        elif msg_type == "discover":
            code = GET
            opts.append((OPT_ACCEPT, bytes([CT_LINK_FORMAT])))
            payload = b""
        elif msg_type == "write":
            code = PUT
            payload = str(data.get("value", "")).encode()
            opts.append((OPT_CONTENT_FORMAT, b""))  # text/plain (0)
        elif msg_type == "execute":
            code = POST
            payload = str(data.get("args", "")).encode()
        elif msg_type == "observe":
            code = GET
            payload = b""
            opts.append((OPT_OBSERVE, b""))  # register (0)
            observe_path = path
        elif msg_type == "cancel-observe":
            code = GET
            payload = b""
            opts.append((OPT_OBSERVE, b"\x01"))
            old = ep.observations.pop(path, None)
            if old is not None:
                ep.pending.pop(old, None)
        else:
            self.publish_up(ep, "resp", {
                "reqID": req_id, "msgType": msg_type,
                "data": {"code": "4.00", "codeMsg": "bad_request",
                         "content": f"unknown msgType {msg_type!r}"},
            })
            return
        ep.pending[token] = (req_id, msg_type, observe_path)
        self.send(ep.addr, CoapMessage(CON, code, ep.next_msg_id(), token,
                                       opts, payload))

    # ----------------------------------------------- device CoAP responses

    def _decode_content(self, msg: CoapMessage):
        ct = 0
        for n, v in msg.options:
            if n == OPT_CONTENT_FORMAT:
                ct = int.from_bytes(v, "big") if v else 0
        if ct == CT_LWM2M_TLV:
            try:
                return tlv_decode(msg.payload)
            except ValueError:
                return msg.payload.hex()
        if ct == CT_LINK_FORMAT:
            return [s.strip() for s in msg.payload.decode("utf-8", "replace").split(",") if s]
        try:
            return msg.payload.decode("utf-8")
        except UnicodeDecodeError:
            return msg.payload.hex()

    def _handle_response(self, addr, msg: CoapMessage) -> None:
        ep = self.by_addr.get(addr)
        if ep is None:
            return
        pend = ep.pending.get(msg.token)
        if pend is None:
            return
        req_id, msg_type, observe_path = pend
        is_notify = msg.observe() is not None and observe_path is not None
        code, code_msg = code_str(msg.code)
        body = {
            "reqID": req_id, "msgType": msg_type,
            "data": {"code": code, "codeMsg": code_msg,
                     "content": self._decode_content(msg)},
        }
        if is_notify:
            # first response = observe ack (up/resp); later ones = notify
            if observe_path in ep.observations:
                body["seqNum"] = msg.observe()
                self.publish_up(ep, "notify", body)
            else:
                ep.observations[observe_path] = msg.token
                self.publish_up(ep, "resp", body)
            if msg.type == CON:
                self.send(addr, CoapMessage(ACK, 0, msg.msg_id))
            return
        ep.pending.pop(msg.token, None)
        self.publish_up(ep, "resp", body)
        if msg.type == CON:
            self.send(addr, CoapMessage(ACK, 0, msg.msg_id))
