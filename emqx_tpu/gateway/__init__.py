"""Gateways: non-MQTT protocol front-ends onto the broker core.

Analog of `apps/emqx_gateway` (SURVEY.md §1.10): the reference defines
impl/channel/frame/conn behaviors plus a per-gateway CM, and each
protocol (STOMP, MQTT-SN, CoAP, LwM2M, ExProto) adapts its sessions
onto the broker's pub/sub via `emqx_gateway_ctx`.

Here `core.GatewayContext` is that ctx: gateway channels authenticate,
subscribe, and publish through the SAME broker facade (hooks, authz,
retainer, TPU matcher) as MQTT clients, and register in a per-gateway
`ConnectionManager`.  Implemented protocols: STOMP 1.2 over TCP
(`stomp.py`), MQTT-SN 1.2 over UDP (`mqttsn.py`), CoAP over UDP
(`coap.py`, RFC 7252 + pubsub draft), LwM2M over CoAP (`lwm2m.py`), and
ExProto (`exproto.py`) — custom protocols out of process over the same
framed wire transport the exhook boundary uses (grpcio is absent in
this image).
"""

from .coap import CoapGateway, CoapMessage
from .core import GatewayContext, GatewayRegistry
from .exproto import ExProtoGateway
from .lwm2m import Lwm2mGateway
from .mqttsn import MqttSnGateway
from .stomp import StompFrame, StompGateway

__all__ = [
    "CoapGateway",
    "CoapMessage",
    "ExProtoGateway",
    "Lwm2mGateway",
    "GatewayContext",
    "GatewayRegistry",
    "MqttSnGateway",
    "StompFrame",
    "StompGateway",
]
