"""ExProto gateway — `apps/emqx_gateway/src/exproto` analog.

The reference lets users implement ANY custom TCP protocol out of
process: the broker streams socket events to a user-supplied gRPC
`ConnectionHandler` service and exposes a `ConnectionAdapter` service
the handler calls back into (`exproto.proto:23-60`).

grpcio is absent in this image, so both services ride the same framed
transport the exhook boundary uses (`exhook/wire.py`: u32 length | JSON
frames) over ONE duplex TCP stream:

- gateway -> handler, stream events (ConnectionHandler):
  `{"stream": "OnSocketCreated"|"OnSocketClosed"|"OnReceivedBytes"|
    "OnTimerTimeout"|"OnReceivedMessages", "data": {...}}`
- handler -> gateway, unary calls (ConnectionAdapter):
  `{"id": n, "method": "send"|"close"|"authenticate"|"start_timer"|
    "publish"|"subscribe"|"unsubscribe", "params": {...}}`
  answered with `{"id": n, "code": ResultCode, "message": str}`.

Raw socket bytes are base64 in the JSON frames.  ResultCodes mirror the
proto enum: 0 SUCCESS, 1 UNKNOWN, 2 CONN_PROCESS_NOT_ALIVE,
3 REQUIRED_PARAMS_MISSED, 5 PERMISSION_DENY.
"""

from __future__ import annotations

import asyncio
import base64
import itertools
import json
import logging
import time
from typing import Dict, Optional

from ..broker.access_control import ClientInfo
from ..broker.broker import Broker
from ..exhook.wire import MAX_FRAME, pack
from .core import GatewayContext

log = logging.getLogger("emqx_tpu.gateway.exproto")

SUCCESS = 0
UNKNOWN = 1
CONN_PROCESS_NOT_ALIVE = 2
REQUIRED_PARAMS_MISSED = 3
PARAMS_TYPE_ERROR = 4
PERMISSION_DENY = 5

KEEPALIVE = "KEEPALIVE"


async def read_frame(reader: asyncio.StreamReader) -> dict:
    head = await reader.readexactly(4)
    n = int.from_bytes(head, "big")
    if not 0 < n <= MAX_FRAME:
        raise ConnectionError(f"bad frame length {n}")
    return json.loads(await reader.readexactly(n))


class ExProtoConn:
    """One raw device socket owned by the gateway (the reference's
    per-connection emqx_exproto channel process)."""

    def __init__(self, conn_id: str, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self.conn_id = conn_id
        self.reader = reader
        self.writer = writer
        self.session = None
        self.clientid: Optional[str] = None
        self.clientinfo: Optional[ClientInfo] = None
        self.authenticated = False
        self.keepalive: float = 0.0
        self.last_rx = time.monotonic()
        self.gateway: Optional["ExProtoGateway"] = None
        self.closed = False

    # ChannelLike: broker deliveries -> OnReceivedMessages stream event
    def deliver(self, delivers) -> None:
        if self.gateway is None:
            return
        msgs = [
            {
                "id": getattr(m, "msg_id", "") or "",
                "qos": m.qos,
                "from": m.from_client or "",
                "topic": m.topic,
                "payload": base64.b64encode(m.payload).decode(),
                "timestamp": int(m.timestamp * 1000) if getattr(m, "timestamp", None) else 0,
            }
            for _f, m in delivers
        ]
        self.gateway.emit("OnReceivedMessages",
                          {"conn": self.conn_id, "messages": msgs})

    def kick(self, rc: int = 0) -> None:
        if self.gateway is not None:
            self.gateway.close_conn(self, reason="kicked")


class ExProtoGateway:
    """Two TCP servers: one for raw device sockets, one for the handler
    service connection (the ConnectionHandler/Adapter duplex stream)."""

    def __init__(self, broker: Broker, host: str = "127.0.0.1",
                 port: int = 0, handler_port: int = 0):
        self.ctx = GatewayContext(broker, "exproto")
        self.host = host
        self.port = port
        self.handler_port = handler_port
        self.conns: Dict[str, ExProtoConn] = {}
        self._ids = itertools.count(1)
        self._device_srv: Optional[asyncio.AbstractServer] = None
        self._handler_srv: Optional[asyncio.AbstractServer] = None
        self._handler_writer: Optional[asyncio.StreamWriter] = None
        self._sweeper: Optional[asyncio.Task] = None

    # ----------------------------------------------------------- lifecycle

    async def start(self) -> None:
        self._device_srv = await asyncio.start_server(
            self._on_device, self.host, self.port)
        self.port = self._device_srv.sockets[0].getsockname()[1]
        self._handler_srv = await asyncio.start_server(
            self._on_handler, self.host, self.handler_port)
        self.handler_port = self._handler_srv.sockets[0].getsockname()[1]
        self._sweeper = asyncio.get_running_loop().create_task(self._sweep_loop())
        log.info("exproto gateway: devices on :%s, handler on :%s",
                 self.port, self.handler_port)

    async def stop(self) -> None:
        if self._sweeper is not None:
            self._sweeper.cancel()
            self._sweeper = None
        for conn in list(self.conns.values()):
            self.close_conn(conn, reason="gateway_stopped", notify=False)
        if self._handler_writer is not None:
            self._handler_writer.close()
            self._handler_writer = None
        for srv in (self._device_srv, self._handler_srv):
            if srv is not None:
                srv.close()
                await srv.wait_closed()
        self._device_srv = self._handler_srv = None

    async def _sweep_loop(self) -> None:
        while True:
            await asyncio.sleep(1.0)
            now = time.monotonic()
            for conn in list(self.conns.values()):
                if conn.keepalive and now - conn.last_rx > conn.keepalive * 1.5:
                    self.emit("OnTimerTimeout",
                              {"conn": conn.conn_id, "type": KEEPALIVE})
                    self.close_conn(conn, reason="keepalive_timeout")

    # ---------------------------------------------------- device side (raw)

    async def _on_device(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        conn_id = f"exproto-{next(self._ids)}"
        conn = ExProtoConn(conn_id, reader, writer)
        conn.gateway = self
        self.conns[conn_id] = conn
        peer = writer.get_extra_info("peername") or ("?", 0)
        self.emit("OnSocketCreated", {
            "conn": conn_id,
            "conninfo": {"peername": {"host": peer[0], "port": peer[1]},
                         "socktype": "tcp"},
        })
        try:
            while True:
                data = await reader.read(65536)
                if not data:
                    break
                conn.last_rx = time.monotonic()
                self.emit("OnReceivedBytes", {
                    "conn": conn_id,
                    "bytes": base64.b64encode(data).decode(),
                })
                # backpressure: a fast device must not grow the handler
                # writer's buffer without bound — pause this read loop until
                # the handler drains below its high-water mark
                await self._handler_drain()
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            self.close_conn(conn, reason="sock_closed")

    def close_conn(self, conn: ExProtoConn, reason: str = "",
                   notify: bool = True) -> None:
        if conn.closed:
            return
        conn.closed = True
        self.conns.pop(conn.conn_id, None)
        if conn.authenticated:
            self.ctx.close_session(conn)
        try:
            conn.writer.close()
        except Exception:
            pass
        if notify:
            self.emit("OnSocketClosed", {"conn": conn.conn_id, "reason": reason})

    # ------------------------------------------------- handler side (duplex)

    def emit(self, stream: str, data: dict) -> None:
        """ConnectionHandler stream event -> the connected handler."""
        w = self._handler_writer
        if w is None or w.is_closing():
            return
        try:
            w.write(pack({"stream": stream, "data": data}))
        except Exception:
            log.exception("exproto: emit failed")

    async def _handler_drain(self) -> None:
        """Await the handler writer's flow control (no-op when absent)."""
        w = self._handler_writer
        if w is None or w.is_closing():
            return
        try:
            await w.drain()
        except ConnectionError:
            pass

    async def _on_handler(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        old = self._handler_writer
        self._handler_writer = writer
        if old is not None and not old.is_closing():
            old.close()
        try:
            while True:
                req = await read_frame(reader)
                rsp = self._dispatch(req)
                if rsp is not None:
                    writer.write(pack(rsp))
                    await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError, ValueError):
            pass
        finally:
            if self._handler_writer is writer:
                self._handler_writer = None
            writer.close()

    # ------------------------------------------- ConnectionAdapter methods

    def _dispatch(self, req: dict) -> Optional[dict]:
        rid = req.get("id")
        method = req.get("method", "")
        params = req.get("params", {}) or {}
        fn = getattr(self, f"_rpc_{method}", None)
        if fn is None:
            return {"id": rid, "code": PARAMS_TYPE_ERROR,
                    "message": f"unknown method {method!r}"}
        conn = None
        if method != "noop":
            conn = self.conns.get(params.get("conn", ""))
            if conn is None:
                return {"id": rid, "code": CONN_PROCESS_NOT_ALIVE,
                        "message": "connection not alive"}
        try:
            code, message = fn(conn, params)
        except KeyError as e:
            code, message = REQUIRED_PARAMS_MISSED, f"missing param {e}"
        except Exception as e:  # pragma: no cover
            log.exception("exproto rpc %s failed", method)
            code, message = UNKNOWN, str(e)
        return {"id": rid, "code": code, "message": message}

    # a slow device past this much buffered outbound data is dropped rather
    # than buffering without bound (the handler RPC loop must stay sync)
    DEVICE_HIGH_WATER = 1 << 20

    def _rpc_send(self, conn: ExProtoConn, params: dict):
        data = base64.b64decode(params["bytes"])
        transport = conn.writer.transport
        if (transport.get_write_buffer_size() + len(data)
                > self.DEVICE_HIGH_WATER):
            self.close_conn(conn, reason="send_buffer_overflow")
            return CONN_PROCESS_NOT_ALIVE, "device send buffer overflow"
        conn.writer.write(data)
        return SUCCESS, ""

    def _rpc_close(self, conn: ExProtoConn, params: dict):
        self.close_conn(conn, reason="handler_closed")
        return SUCCESS, ""

    def _rpc_authenticate(self, conn: ExProtoConn, params: dict):
        info = params["clientinfo"]
        clientid = info.get("clientid", "")
        if not clientid:
            return REQUIRED_PARAMS_MISSED, "clientid required"
        ci = ClientInfo(
            clientid=clientid,
            username=info.get("username") or None,
            password=params.get("password") or None,
            peerhost=(conn.writer.get_extra_info("peername") or ("?",))[0],
            protocol=info.get("proto_name", "exproto"),
        )
        if not self.ctx.authenticate(ci):
            return PERMISSION_DENY, "authentication failed"
        conn.clientinfo = ci
        self.ctx.open_session(True, ci, conn)
        conn.authenticated = True
        conn.keepalive = float(info.get("keepalive", 0) or 0)
        return SUCCESS, ""

    def _rpc_start_timer(self, conn: ExProtoConn, params: dict):
        if params.get("type", KEEPALIVE) != KEEPALIVE:
            return PARAMS_TYPE_ERROR, "unsupported timer type"
        conn.keepalive = float(params["interval"])
        conn.last_rx = time.monotonic()
        return SUCCESS, ""

    def _rpc_publish(self, conn: ExProtoConn, params: dict):
        if not conn.authenticated:
            return PERMISSION_DENY, "not authenticated"
        topic = params["topic"]
        if not self.ctx.authorize(conn.clientinfo, "publish", topic):
            return PERMISSION_DENY, "publish denied"
        self.ctx.publish(conn.clientinfo, topic,
                         base64.b64decode(params.get("payload", "")),
                         qos=int(params.get("qos", 0)))
        return SUCCESS, ""

    def _rpc_subscribe(self, conn: ExProtoConn, params: dict):
        if not conn.authenticated:
            return PERMISSION_DENY, "not authenticated"
        topic = params["topic"]
        if not self.ctx.authorize(conn.clientinfo, "subscribe", topic):
            return PERMISSION_DENY, "subscribe denied"
        self.ctx.subscribe(conn, topic, qos=int(params.get("qos", 0)))
        return SUCCESS, ""

    def _rpc_unsubscribe(self, conn: ExProtoConn, params: dict):
        if not conn.authenticated:
            return PERMISSION_DENY, "not authenticated"
        self.ctx.unsubscribe(conn, params["topic"])
        return SUCCESS, ""


class HandlerClient:
    """Async helper for writing ConnectionHandler services in Python
    (test harness + reference implementation for users)."""

    def __init__(self):
        self.events: asyncio.Queue = asyncio.Queue()
        self._responses: Dict[int, asyncio.Future] = {}
        self._ids = itertools.count(1)
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self._task: Optional[asyncio.Task] = None

    async def connect(self, host: str, port: int) -> "HandlerClient":
        self.reader, self.writer = await asyncio.open_connection(host, port)
        self._task = asyncio.get_running_loop().create_task(self._read_loop())
        return self

    async def _read_loop(self) -> None:
        try:
            while True:
                frame = await read_frame(self.reader)
                if "stream" in frame:
                    self.events.put_nowait(frame)
                else:
                    fut = self._responses.pop(frame.get("id"), None)
                    if fut is not None and not fut.done():
                        fut.set_result(frame)
        except (ConnectionError, asyncio.IncompleteReadError):
            pass

    async def call(self, method: str, **params) -> dict:
        rid = next(self._ids)
        fut = asyncio.get_running_loop().create_future()
        self._responses[rid] = fut
        self.writer.write(pack({"id": rid, "method": method, "params": params}))
        await self.writer.drain()
        return await asyncio.wait_for(fut, 5)

    async def next_event(self, stream: Optional[str] = None, timeout: float = 5):
        while True:
            ev = await asyncio.wait_for(self.events.get(), timeout)
            if stream is None or ev["stream"] == stream:
                return ev

    def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
        if self.writer is not None:
            self.writer.close()
