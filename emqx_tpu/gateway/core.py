"""Gateway framework: context + registry (`emqx_gateway_ctx`/`_registry`).

`GatewayContext` is the narrow facade every protocol channel uses:
authenticate (broker authn chain + banned check), authorize, connect
(per-gateway CM registration with takeover), subscribe/unsubscribe
(broker route tables -> TPU matcher), publish (hooks + retain +
batched match), disconnect.  Gateway clients are full broker citizens:
an MQTT client can subscribe to topics a STOMP client publishes and
vice versa — same equivalence the reference gets by routing every
gateway through emqx_broker.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

from ..broker.access_control import AccessControl, ALLOW, ClientInfo
from ..broker.broker import Broker
from ..broker.cm import ConnectionManager
from ..broker.message import Message
from ..broker.packet import SubOpts
from ..broker.session import Session

log = logging.getLogger("emqx_tpu.gateway")


class GatewayContext:
    def __init__(self, broker: Broker, gateway: str, mountpoint: str = ""):
        self.broker = broker
        self.gateway = gateway
        # per-gateway clientid namespace + CM (emqx_gateway_cm)
        self.cm = ConnectionManager()
        self.cm.on_discard = self._on_discard
        self.access = AccessControl(broker.hooks)
        self.mountpoint = mountpoint

    def _on_discard(self, session: Session) -> None:
        self.broker.client_down(
            self._scoped(session.clientid), list(session.subscriptions)
        )

    def _scoped(self, clientid: str) -> str:
        """Broker-side id, namespaced per gateway like the reference's
        per-gateway clientid registries."""
        return f"{self.gateway}:{clientid}"

    # ----------------------------------------------------------- lifecycle

    def authenticate(self, clientinfo: ClientInfo) -> bool:
        out = self.access.authenticate(clientinfo)
        return out.get("result", ALLOW) == ALLOW

    def open_session(self, clean_start: bool, clientinfo: ClientInfo,
                     channel) -> Tuple[Session, bool]:
        session, present = self.cm.open_session(
            clean_start, clientinfo.clientid,
            lambda: Session(clientid=clientinfo.clientid),
        )
        channel.session = session
        channel.clientid = clientinfo.clientid
        self.cm.register_channel(channel)
        self.broker.hooks.run("client.connected", (clientinfo,))
        return session, present

    def close_session(self, channel, normal: bool = True) -> None:
        ci = getattr(channel, "clientinfo", None)
        self.cm.disconnect_channel(channel)
        if channel.session is not None and channel.session.expiry_interval == 0:
            pass  # on_discard already cleaned routes
        if ci is not None:
            self.broker.hooks.run("client.disconnected", (ci, normal))

    # ------------------------------------------------------------- pub/sub

    def authorize(self, clientinfo: ClientInfo, action: str, topic: str) -> bool:
        return self.access.authorize(clientinfo, action, topic) == ALLOW

    def subscribe(self, channel, filt: str, qos: int = 0) -> bool:
        scoped = self._scoped(channel.clientid)
        opts = SubOpts(qos=qos)
        channel.session.subscribe(filt, opts)
        self.broker.subscribe(scoped, filt, opts)
        # route deliveries for the scoped id back to the gateway channel
        self.broker.cm.register_channel(
            _ScopedChannel(scoped, channel)
        )
        return True

    def unsubscribe(self, channel, filt: str) -> bool:
        scoped = self._scoped(channel.clientid)
        if channel.session.unsubscribe(filt) is None:
            return False
        self.broker.unsubscribe(scoped, filt)
        return True

    def publish(self, clientinfo: ClientInfo, topic: str, payload: bytes,
                qos: int = 0, retain: bool = False,
                properties: Optional[dict] = None) -> int:
        msg = Message(
            topic=topic, payload=payload, qos=qos, retain=retain,
            from_client=clientinfo.clientid,
            from_username=clientinfo.username,
            headers={"proto": self.gateway},
            properties=properties or {},
        )
        return self.broker.publish(msg)


class _ScopedChannel:
    """Adapter registered in the BROKER cm under the scoped id; relays
    deliveries to the gateway channel (which speaks its own protocol)."""

    def __init__(self, clientid: str, target):
        self.clientid = clientid
        self.target = target
        self.session = target.session

    def deliver(self, delivers) -> None:
        self.target.deliver(delivers)

    def kick(self, rc: int = 0) -> None:
        kick = getattr(self.target, "kick", None)
        if kick is not None:
            kick(rc)


class GatewayRegistry:
    """Named gateway instances (`emqx_gateway_registry`)."""

    def __init__(self):
        self._gateways: Dict[str, object] = {}

    def register(self, name: str, gw) -> None:
        if name in self._gateways:
            raise ValueError(f"gateway {name!r} already registered")
        self._gateways[name] = gw

    def unregister(self, name: str):
        return self._gateways.pop(name, None)

    def lookup(self, name: str):
        return self._gateways.get(name)

    def list(self) -> List[str]:
        return sorted(self._gateways)
