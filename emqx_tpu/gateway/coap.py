"""CoAP gateway over UDP — `apps/emqx_gateway/src/coap` analog.

RFC 7252 message codec (4-byte header, token, delta-encoded options,
0xFF payload marker) plus the two handlers the reference exposes
(`emqx_coap_pubsub_handler.erl`, `emqx_coap_mqtt_handler.erl`):

- **PubSub handler** (`ps/{+topic}` URI space, per
  draft-ietf-core-coap-pubsub): POST publishes (2.04 Changed), GET with
  Observe=0 subscribes (2.05 Content + observe notifications), GET with
  Observe=1 unsubscribes (2.07 Deleted analog -> 2.05).
- **MQTT/connection handler** (`mqtt/connection` URI): POST opens an
  authenticated "connection" and returns a session token; DELETE closes
  it.  When `connection_required` is on, every ps/ request must carry
  matching `clientid` + `token` uri-queries or is rejected 4.01
  (`emqx_coap_channel.erl:349-368` check_token semantics).

Query-string options mirror the reference's Shared Options: clientid,
username, password, qos, retain, token.  Observe notifications carry an
incrementing Observe sequence per subscription.
"""

from __future__ import annotations

import asyncio
import logging
import secrets
import struct
import time
from typing import Dict, List, Optional, Tuple

from ..broker.access_control import ClientInfo
from ..broker.broker import Broker
from ..utils.net import UdpProtocolMixin
from .core import GatewayContext

log = logging.getLogger("emqx_tpu.gateway.coap")

VERSION = 1

# message types
CON, NON, ACK, RST = 0, 1, 2, 3

# method / response codes: (class, detail) packed as class*32+detail
GET, POST, PUT, DELETE = 1, 2, 3, 4
CREATED = 0x41    # 2.01
DELETED = 0x42    # 2.02
VALID = 0x43      # 2.03
CHANGED = 0x44    # 2.04
CONTENT = 0x45    # 2.05
BAD_REQUEST = 0x80      # 4.00
UNAUTHORIZED = 0x81     # 4.01
FORBIDDEN = 0x83        # 4.03
NOT_FOUND = 0x84        # 4.04
NOT_ALLOWED = 0x85      # 4.05
INTERNAL_ERROR = 0xA0   # 5.00

# option numbers (emqx_coap_frame.erl:36-53)
OPT_OBSERVE = 6
OPT_URI_PATH = 11
OPT_CONTENT_FORMAT = 12
OPT_MAX_AGE = 14
OPT_URI_QUERY = 15


class CoapMessage:
    def __init__(self, mtype: int = CON, code: int = GET, msg_id: int = 0,
                 token: bytes = b"", options: Optional[List[Tuple[int, bytes]]] = None,
                 payload: bytes = b""):
        self.type = mtype
        self.code = code
        self.msg_id = msg_id
        self.token = token
        self.options = options or []
        self.payload = payload

    # ------------------------------------------------------------ helpers

    def uri_path(self) -> List[str]:
        return [v.decode("utf-8", "replace") for n, v in self.options if n == OPT_URI_PATH]

    def uri_queries(self) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for n, v in self.options:
            if n == OPT_URI_QUERY:
                s = v.decode("utf-8", "replace")
                k, _, val = s.partition("=")
                out[k] = val
        return out

    def observe(self) -> Optional[int]:
        for n, v in self.options:
            if n == OPT_OBSERVE:
                return int.from_bytes(v, "big") if v else 0
        return None


def _opt_ext(x: int) -> Tuple[int, bytes]:
    """Option delta/length nibble + extended bytes per RFC 7252 §3.1."""
    if x < 13:
        return x, b""
    if x < 269:
        return 13, bytes([x - 13])
    return 14, struct.pack("!H", x - 269)


def serialize(msg: CoapMessage) -> bytes:
    tkl = len(msg.token)
    if tkl > 8:
        raise ValueError("token too long")
    out = bytearray()
    out.append((VERSION << 6) | (msg.type << 4) | tkl)
    out.append(msg.code)
    out += struct.pack("!H", msg.msg_id)
    out += msg.token
    prev = 0
    for num, val in sorted(msg.options, key=lambda o: o[0]):
        dn, dext = _opt_ext(num - prev)
        ln, lext = _opt_ext(len(val))
        out.append((dn << 4) | ln)
        out += dext + lext + val
        prev = num
    if msg.payload:
        out.append(0xFF)
        out += msg.payload
    return bytes(out)


def parse(data: bytes) -> CoapMessage:
    try:
        return _parse(data)
    except (IndexError, struct.error) as e:
        # truncated inside an extended option delta/length — treat the same
        # as any other malformed datagram so callers' ValueError guard holds
        raise ValueError(f"truncated datagram: {e}") from e


def _parse(data: bytes) -> CoapMessage:
    if len(data) < 4:
        raise ValueError("short datagram")
    b0 = data[0]
    if b0 >> 6 != VERSION:
        raise ValueError("bad version")
    mtype = (b0 >> 4) & 0x3
    tkl = b0 & 0xF
    if tkl > 8:
        raise ValueError("bad TKL")
    code = data[1]
    (msg_id,) = struct.unpack_from("!H", data, 2)
    pos = 4
    token = data[pos:pos + tkl]
    if len(token) != tkl:
        raise ValueError("short token")
    pos += tkl
    options: List[Tuple[int, bytes]] = []
    num = 0
    while pos < len(data):
        if data[pos] == 0xFF:
            pos += 1
            break
        dn, ln = data[pos] >> 4, data[pos] & 0xF
        pos += 1
        if dn == 13:
            dn = data[pos] + 13
            pos += 1
        elif dn == 14:
            dn = struct.unpack_from("!H", data, pos)[0] + 269
            pos += 2
        elif dn == 15:
            raise ValueError("reserved option delta")
        if ln == 13:
            ln = data[pos] + 13
            pos += 1
        elif ln == 14:
            ln = struct.unpack_from("!H", data, pos)[0] + 269
            pos += 2
        elif ln == 15:
            raise ValueError("reserved option length")
        num += dn
        options.append((num, data[pos:pos + ln]))
        pos += ln
    return CoapMessage(mtype, code, msg_id, token, options, data[pos:])


class CoapClient:
    """Per-peer state: broker session + observe registry + token."""

    def __init__(self, addr, clientid: str):
        self.addr = addr
        self.clientid = clientid
        self.session = None
        self.clientinfo: Optional[ClientInfo] = None
        self.connected = False
        self.token: Optional[str] = None
        self.heartbeat_at = time.monotonic()
        # topic filter -> (observe token from subscribe request, seq counter)
        self.observes: Dict[str, Tuple[bytes, int]] = {}
        self.gateway: Optional["CoapGateway"] = None
        self._next_msg_id = 1

    def next_msg_id(self) -> int:
        mid = self._next_msg_id
        self._next_msg_id = mid % 0xFFFF + 1
        return mid

    # ChannelLike
    def deliver(self, delivers) -> None:
        if self.gateway is None:
            return
        for filt, msg in delivers:
            self.gateway.deliver_publish(self, filt, msg)

    def kick(self, rc: int = 0) -> None:
        if self.gateway is not None:
            self.gateway.drop_client(self)


class CoapGateway(UdpProtocolMixin, asyncio.DatagramProtocol):
    def __init__(self, broker: Broker, host: str = "127.0.0.1", port: int = 0,
                 connection_required: bool = False, heartbeat: float = 30.0):
        self.ctx = GatewayContext(broker, "coap")
        self.host = host
        self.port = port
        self.connection_required = connection_required
        self.heartbeat = heartbeat
        self.clients: Dict[tuple, CoapClient] = {}
        self.transport: Optional[asyncio.DatagramTransport] = None
        self._sweeper: Optional[asyncio.Task] = None

    # ----------------------------------------------------------- lifecycle

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self.transport, _ = await loop.create_datagram_endpoint(
            lambda: self, local_addr=(self.host, self.port)
        )
        self.port = self.transport.get_extra_info("sockname")[1]
        self._sweeper = loop.create_task(self._sweep_loop())
        log.info("coap gateway on %s:%s", self.host, self.port)

    async def stop(self) -> None:
        if self._sweeper is not None:
            self._sweeper.cancel()
            self._sweeper = None
        for client in list(self.clients.values()):
            if client.connected:
                self.ctx.close_session(client)
        self.clients.clear()
        if self.transport is not None:
            await self._close_transport(self.transport)
            self.transport = None

    async def _sweep_loop(self) -> None:
        """Evict clients idle past the heartbeat window; without this,
        connectionless peers (one per NATed source port) pile up forever."""
        while True:
            await asyncio.sleep(self.heartbeat / 2)
            deadline = time.monotonic() - self.heartbeat * 1.5
            for client in list(self.clients.values()):
                if client.heartbeat_at < deadline:
                    if client.connected:
                        self.ctx.close_session(client)
                        client.connected = False
                    self.drop_client(client)

    def send(self, addr, msg: CoapMessage) -> None:
        if self.transport is not None:
            self.transport.sendto(serialize(msg), addr)

    def drop_client(self, client: CoapClient) -> None:
        self.clients.pop(client.addr, None)

    # ------------------------------------------------------------ inbound

    def datagram_received(self, data: bytes, addr) -> None:
        try:
            msg = parse(data)
        except ValueError:
            return
        try:
            self._handle(addr, msg)
        except Exception:
            log.exception("coap handler failed")
            self._reply(addr, msg, INTERNAL_ERROR)

    def _reply(self, addr, req: CoapMessage, code: int, payload: bytes = b"",
               options: Optional[List[Tuple[int, bytes]]] = None) -> None:
        mtype = ACK if req.type == CON else NON
        self.send(addr, CoapMessage(mtype, code, req.msg_id, req.token,
                                    options or [], payload))

    def _handle(self, addr, msg: CoapMessage) -> None:
        if msg.code == 0:  # empty message: ping (CON) or ack/reset — heartbeat
            client = self.clients.get(addr)
            if client is not None:
                client.heartbeat_at = time.monotonic()
            if msg.type == CON:
                self.send(addr, CoapMessage(RST, 0, msg.msg_id))
            return
        if msg.code not in (GET, POST, PUT, DELETE):
            return  # response from peer (observe ack etc.)
        path = msg.uri_path()
        if len(path) >= 2 and path[0] == "mqtt" and path[1] == "connection":
            self._handle_connection(addr, msg)
        elif path and path[0] == "ps":
            self._handle_pubsub(addr, msg, "/".join(path[1:]))
        else:
            self._reply(addr, msg, NOT_FOUND)

    # -------------------------------------------------- mqtt/connection mode

    def _handle_connection(self, addr, msg: CoapMessage) -> None:
        queries = msg.uri_queries()
        if msg.code == POST:
            old = self.clients.pop(addr, None)
            if old is not None and old.connected:
                self.ctx.close_session(old)
            clientid = queries.get("clientid") or f"coap-{addr[0]}-{addr[1]}"
            ci = ClientInfo(
                clientid=clientid, username=queries.get("username"),
                password=queries.get("password"), peerhost=addr[0],
                protocol="coap",
            )
            if not self.ctx.authenticate(ci):
                self._reply(addr, msg, UNAUTHORIZED)
                return
            client = CoapClient(addr, clientid)
            client.gateway = self
            client.clientinfo = ci
            client.token = secrets.token_hex(8)
            self.ctx.open_session(True, ci, client)
            client.connected = True
            self.clients[addr] = client
            self._reply(addr, msg, CREATED, payload=client.token.encode())
        elif msg.code == DELETE:
            client = self.clients.pop(addr, None)
            if client is not None and client.connected:
                self.ctx.close_session(client)
            self._reply(addr, msg, DELETED)
        else:
            self._reply(addr, msg, NOT_ALLOWED)

    def _check_token(self, client: Optional[CoapClient],
                     queries: Dict[str, str]) -> bool:
        """`emqx_coap_channel.erl:349-368`: in connection mode the request
        must name the connected clientid with its session token."""
        if not self.connection_required:
            return True
        if client is None or not client.connected:
            return False
        return (queries.get("clientid") == client.clientid
                and queries.get("token") == client.token)

    # ------------------------------------------------------- pubsub handler

    def _ensure_client(self, addr, queries: Dict[str, str]) -> Optional[CoapClient]:
        """Connectionless mode: autoconnect on first ps/ request, keyed by
        peer address (the reference generates a guid clientid)."""
        client = self.clients.get(addr)
        if client is not None:
            return client
        clientid = queries.get("clientid") or f"coap-{addr[0]}-{addr[1]}"
        ci = ClientInfo(
            clientid=clientid, username=queries.get("username"),
            password=queries.get("password"), peerhost=addr[0], protocol="coap",
        )
        if not self.ctx.authenticate(ci):
            return None
        client = CoapClient(addr, clientid)
        client.gateway = self
        client.clientinfo = ci
        self.ctx.open_session(True, ci, client)
        client.connected = True
        self.clients[addr] = client
        return client

    def _handle_pubsub(self, addr, msg: CoapMessage, topic: str) -> None:
        queries = msg.uri_queries()
        if not topic:
            self._reply(addr, msg, BAD_REQUEST)
            return
        existing = self.clients.get(addr)
        if self.connection_required:
            if not self._check_token(existing, queries):
                self._reply(addr, msg, UNAUTHORIZED)
                return
            client: Optional[CoapClient] = existing
        else:
            client = self._ensure_client(addr, queries)
        if client is None:
            self._reply(addr, msg, UNAUTHORIZED)
            return
        client.heartbeat_at = time.monotonic()

        if msg.code == POST or msg.code == PUT:  # publish
            if not self.ctx.authorize(client.clientinfo, "publish", topic):
                self._reply(addr, msg, FORBIDDEN)
                return
            qos = int(queries.get("qos", "0") or 0)
            retain = queries.get("retain", "false").lower() in ("1", "true")
            self.ctx.publish(client.clientinfo, topic, msg.payload,
                             qos=min(qos, 2), retain=retain)
            self._reply(addr, msg, CHANGED)
        elif msg.code == GET:
            obs = msg.observe()
            if obs == 0:  # subscribe
                filt = topic
                if not self.ctx.authorize(client.clientinfo, "subscribe", filt):
                    self._reply(addr, msg, FORBIDDEN)
                    return
                qos = int(queries.get("qos", "0") or 0)
                self.ctx.subscribe(client, filt, qos=min(qos, 2))
                client.observes[filt] = (msg.token, 0)
                self._reply(addr, msg, CONTENT,
                            options=[(OPT_OBSERVE, b"\x00")])
            elif obs == 1:  # unsubscribe
                client.observes.pop(topic, None)
                self.ctx.unsubscribe(client, topic)
                self._reply(addr, msg, CONTENT)
            else:
                self._reply(addr, msg, BAD_REQUEST)
        else:
            self._reply(addr, msg, NOT_ALLOWED)

    # ------------------------------------------------------------ outbound

    def deliver_publish(self, client: CoapClient, filt: str, msg) -> None:
        """Observe notification: NON 2.05 with the subscription's token and
        an incrementing Observe sequence (RFC 7641)."""
        entry = client.observes.get(filt)
        if entry is None:
            # subscription made via another filter form: attribute the
            # notification to an observe entry whose filter matches the
            # delivered topic (RFC 7641 tokens are per-registration; never
            # borrow an unrelated registration's token/sequence)
            from ..broker import topic as topiclib

            name = topiclib.words(msg.topic)
            for ofilt in client.observes:
                if topiclib.match_words(name, topiclib.words(ofilt)):
                    filt, entry = ofilt, client.observes[ofilt]
                    break
            else:
                return
        token, seq = entry
        seq = (seq + 1) % (1 << 24)
        client.observes[filt] = (token, seq)
        out = CoapMessage(
            NON, CONTENT, client.next_msg_id(), token,
            options=[(OPT_OBSERVE, seq.to_bytes(3, "big").lstrip(b"\x00") or b"\x00"),
                     (OPT_URI_PATH, b"ps")] +
                    [(OPT_URI_PATH, seg.encode()) for seg in msg.topic.split("/")],
            payload=msg.payload,
        )
        self.send(client.addr, out)
