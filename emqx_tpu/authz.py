"""Authorization (ACL) sources.

Analog of `apps/emqx_authz` (SURVEY.md §1.11): an ordered list of sources
evaluated on 'client.authorize'; each source returns allow/deny/nomatch.
Rule model mirrors the reference's acl.conf/built-in-database rules:

    Rule(permission, who, action, topics)
      who:    all | {clientid: x} | {username: x} | {ipaddr: prefix}
      action: publish | subscribe | all
      topics: filters with %c/%u placeholders; "eq " prefix = literal match

plus a per-client ACL claim source (JWT 'acl' claim) and an HTTP source
with injectable transport.
"""

from __future__ import annotations

import fnmatch
import json
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from .broker import topic as topiclib
from .broker.access_control import ALLOW, DENY, PUB, ClientInfo
from .broker.hooks import Hooks, STOP

NOMATCH = "nomatch"


@dataclass
class Rule:
    permission: str  # allow | deny
    who: Any = "all"  # "all" | ("clientid", x) | ("username", x) | ("ipaddr", p)
    action: str = "all"  # publish | subscribe | all
    topics: List[str] = field(default_factory=list)

    def match_who(self, ci: ClientInfo) -> bool:
        if self.who == "all":
            return True
        kind, val = self.who
        if kind == "clientid":
            return ci.clientid == val
        if kind == "username":
            return ci.username == val
        if kind == "ipaddr":
            from .utils.net import peer_host

            host = peer_host(ci.peerhost)
            return fnmatch.fnmatch(host, val)
        return False

    def match_action(self, action: str) -> bool:
        return self.action in ("all", action)

    def match_topic(self, ci: ClientInfo, topic: str) -> bool:
        for t in self.topics:
            t = t.replace("%c", ci.clientid).replace("%u", ci.username or "")
            if t.startswith("eq "):
                if t[3:] == topic:
                    return True
            elif topiclib.match(topic, t) or topic == t:
                return True
        return False

    def check(self, ci: ClientInfo, action: str, topic: str) -> str:
        if self.match_who(ci) and self.match_action(action) and self.match_topic(ci, topic):
            return self.permission
        return NOMATCH


class AuthzSource:
    name = "base"
    enabled = True

    def authorize(self, ci: ClientInfo, action: str, topic: str) -> str:
        raise NotImplementedError


class FileSource(AuthzSource):
    """Static rule list (`emqx_authz_file` / acl.conf analog)."""

    name = "file"

    def __init__(self, rules: Optional[List[Rule]] = None):
        self.rules = rules or []

    def authorize(self, ci: ClientInfo, action: str, topic: str) -> str:
        for r in self.rules:
            v = r.check(ci, action, topic)
            if v != NOMATCH:
                return v
        return NOMATCH


class BuiltInSource(AuthzSource):
    """Per-client/user rule store (`emqx_authz_mnesia` analog)."""

    name = "built_in_database"

    def __init__(self):
        self.by_clientid: Dict[str, List[Rule]] = {}
        self.by_username: Dict[str, List[Rule]] = {}
        self.all_rules: List[Rule] = []

    def authorize(self, ci: ClientInfo, action: str, topic: str) -> str:
        for ruleset in (
            self.by_clientid.get(ci.clientid, ()),
            self.by_username.get(ci.username or "", ()),
            self.all_rules,
        ):
            for r in ruleset:
                v = r.check(ci, action, topic)
                if v != NOMATCH:
                    return v
        return NOMATCH


class ClientAclSource(AuthzSource):
    """ACL from authentication extras (JWT acl claim; `acl` in clientinfo).

    Claim format (reference-compatible): {"pub": [...], "sub": [...],
    "all": [...]} of topic filters with %c/%u placeholders.
    """

    name = "client_acl"

    def authorize(self, ci: ClientInfo, action: str, topic: str) -> str:
        acl = ci.attrs.get("acl")
        if not acl:
            return NOMATCH
        key = "pub" if action == PUB else "sub"
        allowed = list(acl.get(key, [])) + list(acl.get("all", []))
        for t in allowed:
            t = t.replace("%c", ci.clientid).replace("%u", ci.username or "")
            if topiclib.match(topic, t) or topic == t:
                return ALLOW
        return DENY  # an ACL claim is a whitelist


class HttpSource(AuthzSource):
    name = "http"

    def __init__(self, url: str, request_fn: Optional[Callable] = None, timeout: float = 5.0):
        self.url = url
        self.timeout = timeout
        self.request_fn = request_fn or self._default_request

    def _default_request(self, body: Dict[str, Any]) -> Tuple[int, bytes]:
        import urllib.request

        req = urllib.request.Request(
            self.url,
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.status, resp.read()

    def authorize(self, ci: ClientInfo, action: str, topic: str) -> str:
        try:
            status, raw = self.request_fn(
                {
                    "clientid": ci.clientid,
                    "username": ci.username,
                    "action": action,
                    "topic": topic,
                }
            )
        except Exception:
            return NOMATCH
        if status == 204:
            return ALLOW
        if status != 200:
            return NOMATCH
        try:
            result = json.loads(raw).get("result", "ignore")
        except Exception:
            return NOMATCH
        return {"allow": ALLOW, "deny": DENY}.get(result, NOMATCH)


class DbSource(AuthzSource):
    """ACL rows from an injected database driver.

    The analog of `emqx_authz_{mysql,pgsql,redis}.erl`: a query template
    returns (permission, action, topic) rows evaluated in order; Redis
    uses command("HGETALL", key) with topic->action hashes like the
    reference's redis source.  Driver errors -> NOMATCH (fail to the
    chain default), matching the reference's ignore-on-resource-error.
    """

    name = "db"

    def __init__(self, kind: str, query: str, driver=None, **driver_cfg):
        from . import drivers

        self.kind = kind
        self.name = kind
        self.query = query
        self.driver = driver if driver is not None else drivers.make_driver(
            kind, **driver_cfg
        )

    def authorize(self, ci: ClientInfo, action: str, topic: str) -> str:
        from . import drivers

        params = drivers.render_vars(ci)
        try:
            if self.kind == "redis":
                key = drivers.render_template(self.query, params)
                row = self.driver.command("HGETALL", key) or {}
                # topic_filter -> "publish"|"subscribe"|"all" (allow-only,
                # like the reference's redis source)
                for filt, act in row.items():
                    if act not in ("publish", "subscribe", "all"):
                        continue
                    if act != "all" and (
                        (act == "publish") != (action == PUB)
                    ):
                        continue
                    if topiclib.match(topic, filt):
                        return ALLOW
                return NOMATCH
            rows = self.driver.query(self.query, params)
        except Exception:
            return NOMATCH
        for row in rows or []:
            rule = Rule(
                permission=row.get("permission", "allow"),
                who="all",  # the query already filtered by client vars
                action=row.get("action", "all"),
                topics=[row.get("topic", "#")],
            )
            v = rule.check(ci, action, topic)
            if v != NOMATCH:
                return v
        return NOMATCH


class AuthzChain:
    """Source list evaluated in order; default verdict on no match.

    Registered on 'client.authorize' (the facade's hook,
    `emqx_access_control.erl:31-68`).
    """

    def __init__(self, default: str = ALLOW):
        self.sources: List[AuthzSource] = []
        self.default = default

    def add(self, s: AuthzSource, front: bool = False) -> None:
        if front:
            self.sources.insert(0, s)
        else:
            self.sources.append(s)

    def remove(self, name: str) -> None:
        self.sources = [s for s in self.sources if s.name != name]

    def __call__(self, ci: ClientInfo, action: str, topic: str, acc):
        for s in self.sources:
            if not s.enabled:
                continue
            v = s.authorize(ci, action, topic)
            if v in (ALLOW, DENY):
                return (STOP, v)
        return (STOP, self.default)

    def install(self, hooks: Hooks, priority: int = 0) -> None:
        hooks.put("client.authorize", self, priority)

    def uninstall(self, hooks: Hooks) -> None:
        hooks.delete("client.authorize", self)
