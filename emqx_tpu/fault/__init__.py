"""Fault-injection plane (see plane.py) + the lint-checked site registry."""

from .plane import (
    Action,
    FaultError,
    FaultPlane,
    ainject,
    configure,
    enabled,
    inject,
    mangle,
    peek,
    reset,
    stats,
)
from .sites import SITES

__all__ = [
    "Action",
    "FaultError",
    "FaultPlane",
    "SITES",
    "ainject",
    "configure",
    "enabled",
    "inject",
    "mangle",
    "peek",
    "reset",
    "stats",
]
