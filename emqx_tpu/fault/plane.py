"""Seeded, config-driven fault-injection plane.

The cluster data plane claims to self-heal (PeerLink backoff + breaker,
forward spool + replay, engine device breaker) — this module is the
deterministic way to prove it.  A fault *site* is a named point in
production code; a *schedule* (the `fault.spec` config map, or
`configure()` at runtime) arms sites with actions:

    fault.configure({
        "transport.send": {"action": "drop", "p": 0.3},
        "engine.collect": {"action": "drop"},
        "transport.dial": {"action": "delay", "delay": 0.5, "times": 10},
    }, seed=7)

Actions:
    delay    sleep `delay` seconds (async sites use `ainject`), proceed
             — rejected for LOOP_SYNC_SITES (sites.py): a blocking
             sleep at a sync site on the event loop would freeze the
             whole loop, not just the targeted path
    drop     the call site discards the frame / reports failure
    error    raise (the site's natural exception type, or FaultError)
    corrupt  the call site mangles the payload (`Action.corrupt`)

Spec fields per site: `action` (required), `p` (fire probability,
default 1.0), `delay` (seconds, delay action), `times` (max fires,
0 = unlimited), `after` (skip the first N arrivals at the site).

Determinism: every site draws from its own PRNG seeded from
(global seed, site name) — `random.Random(str)` hashes via sha512, so
the same seed reproduces the same fault sequence across processes and
platforms.  `tools/chaos_soak.py` runs the same schedule under multiple
seeds and asserts the healing invariants hold for all of them.

Zero-overhead when disarmed: every entry point is one module-global
boolean test away from returning — the plane costs nothing on the bench
hot path until `configure()` arms it.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, Optional

from ..observe.tracepoints import tp
from .sites import LOOP_SYNC_SITES, SITES

ACTIONS = ("delay", "drop", "error", "corrupt")


class FaultError(Exception):
    """Default exception for `error`-action sites with no natural type."""


class Action:
    """One decided fault firing, interpreted by the call site."""

    __slots__ = ("site", "kind", "delay", "_rng")

    def __init__(self, site: str, kind: str, delay: float, rng: random.Random):
        self.site = site
        self.kind = kind
        self.delay = delay
        self._rng = rng

    def corrupt(self, data: bytes) -> bytes:
        """Flip a handful of bytes at PRNG-chosen offsets."""
        if not data:
            return data
        buf = bytearray(data)
        for _ in range(min(4, len(buf))):
            buf[self._rng.randrange(len(buf))] ^= 0xFF
        return bytes(buf)


class _Site:
    __slots__ = ("name", "kind", "p", "delay", "times", "after",
                 "rng", "fired", "arrivals")

    def __init__(self, name: str, spec: Dict[str, Any], seed: int):
        kind = spec.get("action")
        if kind not in ACTIONS:
            raise ValueError(
                f"fault site {name!r}: action {kind!r} not in {ACTIONS}"
            )
        if kind == "delay" and name in LOOP_SYNC_SITES:
            raise ValueError(
                f"fault site {name!r}: 'delay' runs time.sleep on the "
                f"asyncio event loop at this sync site, freezing every "
                f"link/heartbeat/replay — use drop/error/corrupt here, "
                f"or delay an async site (transport.dial/recv)"
            )
        self.name = name
        self.kind = kind
        self.p = float(spec.get("p", 1.0))
        self.delay = float(spec.get("delay", 0.05))
        self.times = int(spec.get("times", 0))
        self.after = int(spec.get("after", 0))
        self.rng = random.Random(f"{seed}:{name}")
        self.fired = 0
        self.arrivals = 0


class FaultPlane:
    """Site table + per-site deterministic decision state."""

    def __init__(self) -> None:
        self._sites: Dict[str, _Site] = {}
        self._lock = threading.Lock()
        self.seed = 0

    def configure(self, spec: Dict[str, Dict[str, Any]], seed: int = 0) -> None:
        unknown = set(spec) - set(SITES)
        if unknown:
            raise ValueError(
                f"unknown fault sites {sorted(unknown)} "
                f"(registered: {sorted(SITES)})"
            )
        with self._lock:
            self.seed = int(seed)
            self._sites = {
                name: _Site(name, dict(cfg or {}), self.seed)
                for name, cfg in spec.items()
            }

    def reset(self) -> None:
        with self._lock:
            self._sites = {}

    def decide(self, site: str) -> Optional[Action]:
        with self._lock:
            s = self._sites.get(site)
            if s is None:
                return None
            s.arrivals += 1
            if s.arrivals <= s.after:
                return None
            if s.times and s.fired >= s.times:
                return None
            if s.p < 1.0 and s.rng.random() >= s.p:
                return None
            s.fired += 1
            fired = s.fired
        tp("fault.inject", site=site, action=s.kind, n=fired)
        return Action(site, s.kind, s.delay, s.rng)

    def stats(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {
                name: {"fired": s.fired, "arrivals": s.arrivals}
                for name, s in self._sites.items()
            }


_plane = FaultPlane()
_on = False  # fast-path gate: inject() is one bool test when disarmed


def configure(spec: Dict[str, Dict[str, Any]], seed: int = 0) -> None:
    """Arm the plane with a schedule (validated against SITES)."""
    global _on
    _plane.configure(spec, seed=seed)
    _on = bool(spec)


def reset() -> None:
    """Disarm every site (back to zero-overhead pass-through)."""
    global _on
    _plane.reset()
    _on = False


def enabled() -> bool:
    return _on


def stats() -> Dict[str, Dict[str, int]]:
    """Per-site fired/arrival counts (soak assertions, /status surfacing)."""
    return _plane.stats()


def inject(site: str, err: Any = None) -> Optional[Action]:
    """Synchronous fault point.  Returns None when nothing fires.

    delay   sleeps here, returns the action (call site proceeds)
    error   raises `err` (FaultError when None); pass ``err=False`` to
            get the action back instead of raising (sites that must not
            unwind, e.g. the engine collect path)
    drop / corrupt   returned for the call site to apply
    """
    if not _on:
        return None
    a = _plane.decide(site)
    if a is None:
        return None
    if a.kind == "delay":
        # sync injection point: only worker/pool call sites use inject();
        # every loop-role site goes through ainject (PR 4 fix #3)
        time.sleep(a.delay)  # analysis: allow-blocking(sync sites are worker-role; loop sites use ainject)
    elif a.kind == "error" and err is not False:
        raise (err or FaultError)(f"fault injected at {site}")
    return a


async def ainject(site: str, err: Any = None) -> Optional[Action]:
    """`inject` for async call sites (delay = asyncio.sleep)."""
    if not _on:
        return None
    a = _plane.decide(site)
    if a is None:
        return None
    if a.kind == "delay":
        import asyncio

        await asyncio.sleep(a.delay)
    elif a.kind == "error" and err is not False:
        raise (err or FaultError)(f"fault injected at {site}")
    return a


def peek(site: str) -> Optional[Action]:
    """Decide without applying anything: no sleep, no raise.  For sites
    that interpret every action themselves (probe harvest)."""
    if not _on:
        return None
    return _plane.decide(site)


def mangle(site: str, data: bytes) -> bytes:
    """Corrupt `data` when the site fires with a corrupt action;
    otherwise return it unchanged (other actions are ignored here)."""
    if not _on:
        return data
    a = _plane.decide(site)
    if a is not None and a.kind == "corrupt":
        return a.corrupt(data)
    return data
