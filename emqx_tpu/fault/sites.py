"""Fault-site registry.

Every `fault.inject("<site>", ...)` / `fault.ainject` / `fault.peek` /
`fault.mangle` call in production code (emqx_tpu/**) MUST name a site
registered here — the static-analysis gate (`tools/analysis/`) lints
call sites against this dict, the same contract as the tracepoint
KNOWN_KINDS registry.
A site that is not registered cannot be scheduled from `fault.spec`
config, so an unregistered call site is dead chaos surface by contract.

Site names are stable identifiers: chaos schedules (`tools/chaos_soak.py`,
`fault.spec` config) and dashboards key on them.
"""

from __future__ import annotations

from typing import Dict

SITES: Dict[str, str] = {
    # cluster transport (cluster/transport.py)
    "transport.dial": "PeerLink outbound connect attempt",
    "transport.send": "outbound frame write on a peer link "
                      "(drop = send_nowait returns False / request frame "
                      "lost before the wire)",
    "transport.recv": "inbound frame on the server handler or the link "
                      "read loop (drop = frame discarded; error = "
                      "connection reset)",
    # forward + rpc planes (cluster/node.py)
    "cluster.forward": "one destination node's forward batch on the "
                       "publish path (drop = treat every send as failed)",
    "cluster.rpc": "outbound cluster RPC call (error/drop = RpcError)",
    # checkpoint IO (checkpoint/store.py)
    "ckpt.write": "snapshot store save (error = OSError mid-write)",
    "ckpt.read": "snapshot file load (any action = frame check failure, "
                 "exercising the older-snapshot fallback)",
    # device collect (models/engine.py, parallel/sharded.py)
    "engine.collect": "single-chip device result fetch (drop/error = "
                      "simulated link stall: the tick times out to the "
                      "host path and feeds the device breaker)",
    "engine.probe": "hybrid warm-keeping probe harvest (drop = probe "
                    "looks stalled, keeping the breaker open)",
    "sharded.collect": "sharded engine device resolve (delay only: the "
                       "mesh path has no host fallback)",
    # prep-ahead stage (ops/prep.py PrepStage worker)
    "engine.prep": "prep-ahead worker tick (delay = a stalled prep "
                   "stage: match_submit's ticket claim times out and "
                   "degrades to inline prep — the window never freezes)",
    # shared-memory match plane (shm/client.py)
    "shm.submit": "worker-side submit-ring enqueue (drop/error/corrupt "
                  "= the tick is served from the local host trie — the "
                  "degrade path the hub-death ladder rides)",
    "shm.sem.submit": "worker-side K_SEM semantic-tick enqueue "
                      "(drop/error = the publish is matched by the "
                      "worker's exact host path over its own queries — "
                      "the semantic twin of shm.submit's degrade)",
    # ds append replication (ds/repl.py)
    "ds.repl.send": "leader-side ship of one flushed range (delay = "
                    "slow follower hop; drop/error = the ship fails "
                    "and the shard degrades to leader-only appends)",
    "ds.repl.ack": "follower-side mirror append + ack (drop = range "
                   "discarded unacked, the leader times out like real "
                   "ack loss; error = explicit nack)",
}

# Sites whose injector runs SYNCHRONOUSLY on the asyncio event-loop
# thread (send_nowait/request writes, the forward fan-out): a `delay`
# action there would time.sleep the whole loop — every link, heartbeat,
# and replay stalls, not just the targeted site — so `configure()`
# rejects delay specs for them.  To slow these paths, delay the async
# sites around them (transport.dial/recv) instead.  ckpt.* runs on
# worker/boot threads and the engine collect paths block by design
# (a delay there IS the simulated device stall), so they stay eligible.
LOOP_SYNC_SITES = frozenset(
    {"transport.send", "cluster.forward", "ds.repl.ack"}
)  # ds.repl.ack fires in the server read-loop's REPL handler
