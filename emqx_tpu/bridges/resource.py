"""Resource instance lifecycle — `emqx_resource_instance` analog.

A resource is any object with async `start()`, `stop()`,
`health_check() -> bool`.  The manager tracks per-resource status
(connected / disconnected / stopped), runs periodic health checks, and
auto-restarts unhealthy resources (`emqx_resource_health_check`
semantics), counting successes/failures for the management API.
"""

from __future__ import annotations

import asyncio
import enum
import logging
import time
from typing import Dict, Optional

log = logging.getLogger("emqx_tpu.resource")


class ResourceStatus(str, enum.Enum):
    CONNECTING = "connecting"
    CONNECTED = "connected"
    DISCONNECTED = "disconnected"
    STOPPED = "stopped"


class _Entry:
    def __init__(self, resource, health_interval: float, auto_restart: bool):
        self.resource = resource
        self.health_interval = health_interval
        self.auto_restart = auto_restart
        self.status = ResourceStatus.CONNECTING
        self.task: Optional[asyncio.Task] = None
        self.restarts = 0
        self.last_error: Optional[str] = None
        self.started_at = time.time()


class ResourceManager:
    def __init__(self):
        self._r: Dict[str, _Entry] = {}

    async def create(self, resource_id: str, resource,
                     health_interval: float = 15.0,
                     auto_restart: bool = True) -> ResourceStatus:
        if resource_id in self._r:
            raise ValueError(f"resource {resource_id!r} exists")
        ent = _Entry(resource, health_interval, auto_restart)
        self._r[resource_id] = ent
        await self._start(resource_id, ent)
        ent.task = asyncio.get_running_loop().create_task(
            self._health_loop(resource_id, ent)
        )
        return ent.status

    async def _start(self, rid: str, ent: _Entry) -> None:
        try:
            await ent.resource.start()
            ok = await ent.resource.health_check()
            ent.status = (
                ResourceStatus.CONNECTED if ok else ResourceStatus.DISCONNECTED
            )
            ent.last_error = None
        except Exception as e:
            ent.status = ResourceStatus.DISCONNECTED
            ent.last_error = f"{type(e).__name__}: {e}"

    async def _health_loop(self, rid: str, ent: _Entry) -> None:
        while True:
            await asyncio.sleep(ent.health_interval)
            if ent.status == ResourceStatus.STOPPED:
                continue
            try:
                ok = await ent.resource.health_check()
            except Exception as e:
                ok = False
                ent.last_error = f"{type(e).__name__}: {e}"
            if ok:
                ent.status = ResourceStatus.CONNECTED
            else:
                ent.status = ResourceStatus.DISCONNECTED
                if ent.auto_restart:
                    log.info("restarting unhealthy resource %s", rid)
                    try:
                        await ent.resource.stop()
                    except Exception:
                        pass
                    ent.restarts += 1
                    await self._start(rid, ent)

    async def remove(self, resource_id: str) -> bool:
        ent = self._r.pop(resource_id, None)
        if ent is None:
            return False
        if ent.task:
            ent.task.cancel()
            try:
                await ent.task
            except (asyncio.CancelledError, Exception):
                pass
        try:
            await ent.resource.stop()
        except Exception:
            pass
        ent.status = ResourceStatus.STOPPED
        return True

    async def restart(self, resource_id: str) -> ResourceStatus:
        ent = self._r[resource_id]
        try:
            await ent.resource.stop()
        except Exception:
            pass
        ent.restarts += 1
        await self._start(resource_id, ent)
        return ent.status

    def status(self, resource_id: str) -> Optional[ResourceStatus]:
        ent = self._r.get(resource_id)
        return ent.status if ent else None

    def get(self, resource_id: str):
        ent = self._r.get(resource_id)
        return ent.resource if ent else None

    def list(self) -> Dict[str, dict]:
        return {
            rid: {
                "status": ent.status.value,
                "restarts": ent.restarts,
                "last_error": ent.last_error,
                "uptime": time.time() - ent.started_at,
            }
            for rid, ent in self._r.items()
        }

    async def stop_all(self) -> None:
        for rid in list(self._r):
            await self.remove(rid)
