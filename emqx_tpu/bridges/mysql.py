"""MySQL client — real client/server protocol, pooled, stdlib-only.

The analog of the reference's mysql-otp-backed connector
(`/root/reference/apps/emqx_connector/src/emqx_connector_mysql.erl`:
pooled clients, parameterized queries, ping health checks), speaking the
MySQL client/server protocol over plain TCP — no external client
library, so the "mysql" kind of the driver seam is a real driver out of
the box.

Implements:
* the v10 initial handshake + HandshakeResponse41, with
  `mysql_native_password` (SHA1 challenge) and `caching_sha2_password`
  (SHA256 challenge, fast-auth path) plugins and AuthSwitchRequest
  handling — caching_sha2 *full* auth needs TLS or an RSA exchange and
  fails loudly rather than sending a cleartext password;
* COM_QUERY text resultsets (lenenc column count, column definitions,
  EOF-delimited rows) with NULL handling and numeric-type decoding;
* COM_PING health checks (the reference's do_health_check);
* `${var}` template placeholders bound by escaping into quoted SQL
  literals (`_escape`), matching how text-protocol clients bind
  parameters — values never splice into SQL unescaped.
"""

from __future__ import annotations

import hashlib
import socket
import struct
from typing import Any, Dict, List, Optional, Tuple

from .dbpool import PooledDriver

# capability flags (include/mysql_com.h)
CLIENT_LONG_PASSWORD = 0x00000001
CLIENT_LONG_FLAG = 0x00000004
CLIENT_CONNECT_WITH_DB = 0x00000008
CLIENT_PROTOCOL_41 = 0x00000200
CLIENT_TRANSACTIONS = 0x00002000
CLIENT_SECURE_CONNECTION = 0x00008000
CLIENT_PLUGIN_AUTH = 0x00080000

_UTF8MB4 = 45  # utf8mb4_general_ci

# column type codes that decode beyond str (enum_field_types)
_INT_TYPES = {1, 2, 3, 8, 9, 13}  # tiny/short/long/longlong/int24/year
_FLOAT_TYPES = {4, 5, 246}  # float/double/newdecimal


class MySqlError(Exception):
    """Server ERR packet; .code and .sqlstate hold the details."""

    def __init__(self, code: int, sqlstate: str, message: str):
        self.code = code
        self.sqlstate = sqlstate
        super().__init__(f"({code}) [{sqlstate}] {message}")


class MySqlProtocolError(Exception):
    """Malformed wire data / unsupported server requirement."""


def native_password_scramble(password: bytes, nonce: bytes) -> bytes:
    """mysql_native_password: SHA1(pw) XOR SHA1(nonce + SHA1(SHA1(pw)))."""
    if not password:
        return b""
    h1 = hashlib.sha1(password).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(nonce + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


def caching_sha2_scramble(password: bytes, nonce: bytes) -> bytes:
    """caching_sha2_password fast path:
    SHA256(pw) XOR SHA256(SHA256(SHA256(pw)) + nonce)."""
    if not password:
        return b""
    h1 = hashlib.sha256(password).digest()
    h2 = hashlib.sha256(h1).digest()
    h3 = hashlib.sha256(h2 + nonce).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


def _scramble_for(plugin: str, password: bytes, nonce: bytes) -> bytes:
    if plugin == "mysql_native_password":
        return native_password_scramble(password, nonce)
    if plugin == "caching_sha2_password":
        return caching_sha2_scramble(password, nonce)
    if plugin == "mysql_clear_password":
        raise MySqlProtocolError(
            "refusing mysql_clear_password on an insecure connection"
        )
    raise MySqlProtocolError(f"unsupported auth plugin {plugin!r}")


def escape_literal(value: Any, no_backslash: bool = False) -> str:
    """Bind one template value as a SQL literal (text protocol).

    Quotes are doubled (`''`) — valid in every sql_mode.  Backslashes
    and control characters get backslash escapes in the default mode;
    under NO_BACKSLASH_ESCAPES a backslash is an ordinary character
    (escaping it would corrupt the value) and a NUL cannot be
    represented at all, so it is rejected.  The connection's actual
    mode is probed at dial time (`SELECT @@sql_mode`)."""
    if value is None:
        return "NULL"
    if isinstance(value, bool):
        return "TRUE" if value else "FALSE"
    if isinstance(value, (int, float)):
        return str(value)
    s = str(value)
    out = []
    for ch in s:
        if ch == "'":
            out.append("''")
        elif no_backslash:
            if ch == "\x00":
                raise ValueError(
                    "NUL byte in a literal cannot be escaped under "
                    "NO_BACKSLASH_ESCAPES"
                )
            out.append(ch)
        elif ch == "\x00":
            out.append("\\0")
        elif ch == "\\":
            out.append("\\\\")
        elif ch == "\n":
            out.append("\\n")
        elif ch == "\r":
            out.append("\\r")
        elif ch == "\x1a":
            out.append("\\Z")
        else:
            out.append(ch)
    return "'" + "".join(out) + "'"


def render_sql(template: str, params: Dict[str, Any],
               no_backslash: bool = False) -> str:
    """`... WHERE u = ${username}` → escaped literal SQL."""
    import re

    def sub(m) -> str:
        return escape_literal(params.get(m.group(1)), no_backslash)

    return re.sub(r"\$\{(\w+)\}", sub, template)


def _lenenc_int(buf: bytes, off: int) -> Tuple[Optional[int], int]:
    """Length-encoded integer → (value, new offset); None for NULL."""
    first = buf[off]
    if first < 0xFB:
        return first, off + 1
    if first == 0xFB:
        return None, off + 1
    if first == 0xFC:
        return struct.unpack_from("<H", buf, off + 1)[0], off + 3
    if first == 0xFD:
        return int.from_bytes(buf[off + 1:off + 4], "little"), off + 4
    if first == 0xFE:
        return struct.unpack_from("<Q", buf, off + 1)[0], off + 9
    raise MySqlProtocolError(f"bad lenenc prefix {first:#x}")


def _lenenc_str(buf: bytes, off: int) -> Tuple[Optional[bytes], int]:
    n, off = _lenenc_int(buf, off)
    if n is None:
        return None, off
    return buf[off:off + n], off + n


def _decode_col(value: Optional[bytes], ftype: int) -> Any:
    if value is None:
        return None
    text = value.decode("utf-8", "replace")
    if ftype in _INT_TYPES:
        return int(text)
    if ftype in _FLOAT_TYPES:
        return float(text)
    return text


class _Conn:
    """One blocking socket speaking the MySQL packet stream."""

    def __init__(self, host: str, port: int, timeout: float):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.buf = b""
        self.seq = 0
        self.server_version = ""
        self.no_backslash = False  # sql_mode probe result (dial time)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    # ------------------------------------------------------------ wire

    def _read_more(self) -> None:
        chunk = self.sock.recv(65536)
        if not chunk:
            raise ConnectionError("mysql connection closed by peer")
        self.buf += chunk

    def read_packet(self) -> bytes:
        """One logical packet; a 0xffffff-length wire packet means a
        continuation follows (rows ≥ 16 MB are split)."""
        payload = b""
        while True:
            while len(self.buf) < 4:
                self._read_more()
            length = int.from_bytes(self.buf[:3], "little")
            self.seq = (self.buf[3] + 1) & 0xFF
            while len(self.buf) < 4 + length:
                self._read_more()
            payload += self.buf[4:4 + length]
            self.buf = self.buf[4 + length:]
            if length < 0xFFFFFF:
                return payload

    def send_packet(self, payload: bytes) -> None:
        off = 0
        while True:
            chunk = payload[off:off + 0xFFFFFF]
            self.sock.sendall(
                len(chunk).to_bytes(3, "little")
                + bytes((self.seq,)) + chunk
            )
            self.seq = (self.seq + 1) & 0xFF
            off += len(chunk)
            if len(chunk) < 0xFFFFFF:
                return

    @staticmethod
    def _parse_err(payload: bytes) -> MySqlError:
        code = struct.unpack_from("<H", payload, 1)[0]
        off = 3
        state = ""
        if payload[off:off + 1] == b"#":
            state = payload[off + 1:off + 6].decode()
            off += 6
        return MySqlError(code, state,
                          payload[off:].decode("utf-8", "replace"))

    # ------------------------------------------------------- handshake

    def handshake(self, user: str, password: str, database: str) -> None:
        greeting = self.read_packet()
        if greeting[:1] == b"\xff":
            raise self._parse_err(greeting)
        if greeting[0] != 10:
            raise MySqlProtocolError(
                f"unsupported handshake protocol {greeting[0]}"
            )
        off = 1
        end = greeting.index(b"\x00", off)
        self.server_version = greeting[off:end].decode()
        off = end + 1 + 4  # thread id
        nonce = greeting[off:off + 8]
        off += 8 + 1  # filler
        caps = struct.unpack_from("<H", greeting, off)[0]
        off += 2
        plugin = "mysql_native_password"
        if len(greeting) > off:
            off += 1 + 2  # charset + status
            caps |= struct.unpack_from("<H", greeting, off)[0] << 16
            off += 2
            auth_len = greeting[off]
            off += 1 + 10  # reserved
            if caps & CLIENT_SECURE_CONNECTION:
                n2 = max(13, auth_len - 8)
                nonce += greeting[off:off + n2].rstrip(b"\x00")
                off += n2
            if caps & CLIENT_PLUGIN_AUTH:
                end = greeting.index(b"\x00", off)
                plugin = greeting[off:end].decode()

        client_caps = (
            CLIENT_LONG_PASSWORD | CLIENT_LONG_FLAG | CLIENT_PROTOCOL_41
            | CLIENT_TRANSACTIONS | CLIENT_SECURE_CONNECTION
            | CLIENT_PLUGIN_AUTH
        )
        if database:
            client_caps |= CLIENT_CONNECT_WITH_DB
        auth = _scramble_for(plugin, password.encode(), nonce)
        resp = struct.pack("<IIB23x", client_caps, 1 << 24, _UTF8MB4)
        resp += user.encode() + b"\x00"
        resp += bytes((len(auth),)) + auth
        if database:
            resp += database.encode() + b"\x00"
        resp += plugin.encode() + b"\x00"
        self.send_packet(resp)
        self._auth_loop(password, nonce)

    def _auth_loop(self, password: str, nonce: bytes) -> None:
        while True:
            p = self.read_packet()
            first = p[0]
            if first == 0x00:  # OK
                return
            if first == 0xFF:
                raise self._parse_err(p)
            if first == 0xFE:  # AuthSwitchRequest
                end = p.index(b"\x00", 1)
                plugin = p[1:end].decode()
                new_nonce = p[end + 1:].rstrip(b"\x00")
                self.send_packet(
                    _scramble_for(plugin, password.encode(), new_nonce)
                )
                continue
            if first == 0x01:  # AuthMoreData (caching_sha2)
                if p[1:2] == b"\x03":  # fast-auth success; OK follows
                    continue
                if p[1:2] == b"\x04":  # full auth required
                    raise MySqlProtocolError(
                        "caching_sha2_password full authentication "
                        "requires TLS or an RSA key exchange; add the "
                        "account to the server's auth cache or use "
                        "mysql_native_password"
                    )
            raise MySqlProtocolError(
                f"unexpected auth packet {first:#x}"
            )

    # ----------------------------------------------------------- query

    def ping(self) -> None:
        self.seq = 0
        self.send_packet(b"\x0e")
        p = self.read_packet()
        if p[0] == 0xFF:
            raise self._parse_err(p)

    def query(self, sql: str) -> List[Dict[str, Any]]:
        """COM_QUERY with a text resultset → rows as dicts."""
        self.seq = 0
        self.send_packet(b"\x03" + sql.encode("utf-8"))
        p = self.read_packet()
        if p[0] == 0xFF:
            raise self._parse_err(p)
        if p[0] == 0x00:  # OK: no resultset (INSERT/UPDATE/...)
            return []
        ncols, off = _lenenc_int(p, 0)
        cols: List[Tuple[str, int]] = []
        for _ in range(ncols or 0):
            cp = self.read_packet()
            cols.append(self._parse_coldef(cp))
        p = self.read_packet()
        if not self._is_eof(p):
            raise MySqlProtocolError("expected EOF after column defs")
        rows: List[Dict[str, Any]] = []
        while True:
            p = self.read_packet()
            if self._is_eof(p):
                return rows
            if p[0] == 0xFF:
                raise self._parse_err(p)
            off = 0
            row: Dict[str, Any] = {}
            for name, ftype in cols:
                v, off = _lenenc_str(p, off)
                row[name] = _decode_col(v, ftype)
            rows.append(row)

    @staticmethod
    def _is_eof(p: bytes) -> bool:
        return p[:1] == b"\xfe" and len(p) < 9

    @staticmethod
    def _parse_coldef(p: bytes) -> Tuple[str, int]:
        """ColumnDefinition41: catalog/schema/table/org_table/name/
        org_name (lenenc strings) then fixed fields incl. type."""
        off = 0
        fields = []
        for _ in range(6):
            v, off = _lenenc_str(p, off)
            fields.append(v or b"")
        name = fields[4].decode("utf-8", "replace")
        _n, off = _lenenc_int(p, off)  # fixed-length fields marker
        off += 2 + 4  # charset + column length
        ftype = p[off]
        return name, ftype


class MySqlDriver(PooledDriver):
    """Pooled MySQL client satisfying the emqx_tpu driver contract
    (`query(template, params)` with ${var} placeholders)."""

    KIND = "mysql"
    RECOVERABLE = (MySqlError,)

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 3306,
        username: str = "root",
        password: str = "",
        database: str = "",
        pool_size: int = 4,
        timeout: float = 5.0,
        **_ignored,
    ):
        super().__init__(pool_size=pool_size, timeout=timeout)
        self.host = host
        self.port = int(port)
        self.username = username
        self.password = password or ""
        self.database = database

    def _dial(self) -> _Conn:
        conn = _Conn(self.host, self.port, self.timeout)
        try:
            conn.handshake(self.username, self.password, self.database)
            # escaping depends on the session's sql_mode — probe once
            rows = conn.query("SELECT @@sql_mode AS m")
            mode = str(rows[0].get("m", "")) if rows else ""
            conn.no_backslash = "NO_BACKSLASH_ESCAPES" in mode.upper()
        except Exception:
            conn.close()
            raise
        return conn

    # --------------------------------------------------------- contract

    @staticmethod
    def _is_read(sql: str) -> bool:
        head = sql.lstrip().split(None, 1)
        return bool(head) and head[0].upper() in (
            "SELECT", "SHOW", "DESCRIBE", "DESC", "EXPLAIN", "WITH"
        )

    def query(self, template: str, params: Dict[str, Any]
              ) -> List[Dict[str, Any]]:
        """Run a ${var} template with escaped-literal binding; the
        escaping style follows the connection's probed sql_mode."""
        return self._run(
            lambda conn: conn.query(
                render_sql(template, params, conn.no_backslash)
            ),
            retryable=self._is_read(template),
        )

    def command(self, sql: str) -> List[Dict[str, Any]]:
        """Raw SQL (no template binding)."""
        return self._run(lambda conn: conn.query(sql),
                         retryable=self._is_read(sql))

    def health_check(self) -> bool:
        """COM_PING like the reference's do_health_check
        (`emqx_connector_mysql.erl` mysql:query ping)."""
        try:
            self._run(lambda conn: conn.ping())
            return True
        except Exception:
            return False
