"""PostgreSQL client — real frontend/backend protocol v3, stdlib-only.

The analog of the reference's epgsql-backed connector
(`/root/reference/apps/emqx_connector/src/emqx_connector_pgsql.erl`:
pooled clients, `epgsql:equery` parameterized queries, `SELECT count(1)`
health checks), speaking the PostgreSQL wire protocol over plain TCP —
no external client library, so the "pgsql" kind of the driver seam is a
real driver out of the box.

Implements:
* StartupMessage + authentication: trust, cleartext, MD5, and
  SCRAM-SHA-256 (SASL, reusing the RFC 5802 `ScramClient`);
* the extended query protocol (Parse/Bind/Describe/Execute/Sync) with
  text-format parameters and results — the epgsql `equery` analog, so
  `${var}` template placeholders become `$n` wire parameters and never
  touch the SQL string;
* rows as dicts keyed by column name, with int/bool/float OIDs decoded
  to Python values;
* ErrorResponse drained to ReadyForQuery so a failed query leaves the
  connection in sync (no reconnect needed), matching backend behavior.
"""

from __future__ import annotations

import hashlib
import re
import socket
import struct
from typing import Any, Dict, List, Optional, Tuple

from .dbpool import PooledDriver

PROTOCOL_V3 = 196608  # (3 << 16)

# auth request codes (AuthenticationRequest 'R' payloads)
_AUTH_OK = 0
_AUTH_CLEARTEXT = 3
_AUTH_MD5 = 5
_AUTH_SASL = 10
_AUTH_SASL_CONTINUE = 11
_AUTH_SASL_FINAL = 12

# type OIDs worth decoding beyond text (pg_type.dat)
_OID_BOOL = 16
_OID_INT8 = 20
_OID_INT2 = 21
_OID_INT4 = 23
_OID_FLOAT4 = 700
_OID_FLOAT8 = 701


class PgError(Exception):
    """Server ErrorResponse; .fields holds the code→value map."""

    def __init__(self, fields: Dict[str, str]):
        self.fields = fields
        sev = fields.get("S", "ERROR")
        code = fields.get("C", "")
        msg = fields.get("M", "")
        super().__init__(f"{sev} {code}: {msg}")


class PgProtocolError(Exception):
    """Malformed wire data from the server."""


def _cstr(b: bytes) -> bytes:
    return b + b"\x00"


def md5_password(user: str, password: str, salt: bytes) -> bytes:
    """The AuthenticationMD5Password response:
    'md5' + md5hex(md5hex(password+user) + salt)."""
    inner = hashlib.md5(password.encode() + user.encode()).hexdigest()
    outer = hashlib.md5(inner.encode() + salt).hexdigest()
    return b"md5" + outer.encode()


def template_to_wire(template: str) -> Tuple[str, List[str]]:
    """`... WHERE username = ${username}` → (`... = $1`, ["username"]).

    Repeated placeholders reuse one wire parameter, mirroring how the
    reference pre-processes authn/authz query templates
    (`emqx_authn_pgsql.erl` parse_query)."""
    order: List[str] = []

    def sub(m) -> str:
        name = m.group(1)
        if name not in order:
            order.append(name)
        return f"${order.index(name) + 1}"

    sql = re.sub(r"\$\{(\w+)\}", sub, template)
    return sql, order


def _decode_col(value: Optional[bytes], oid: int) -> Any:
    if value is None:
        return None
    text = value.decode("utf-8")
    if oid in (_OID_INT2, _OID_INT4, _OID_INT8):
        return int(text)
    if oid == _OID_BOOL:
        return text == "t"
    if oid in (_OID_FLOAT4, _OID_FLOAT8):
        return float(text)
    return text


class _Conn:
    """One blocking socket speaking the v3 message stream."""

    def __init__(self, host: str, port: int, timeout: float):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.buf = b""
        self.parameters: Dict[str, str] = {}  # ParameterStatus pairs
        self.backend_pid = 0
        self.secret_key = 0

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    # ------------------------------------------------------------ wire

    def _read_more(self) -> None:
        chunk = self.sock.recv(65536)
        if not chunk:
            raise ConnectionError("pgsql connection closed by peer")
        self.buf += chunk

    def read_message(self) -> Tuple[bytes, bytes]:
        """One backend message → (type byte, payload)."""
        while len(self.buf) < 5:
            self._read_more()
        mtype = self.buf[:1]
        (length,) = struct.unpack("!i", self.buf[1:5])
        if length < 4:
            raise PgProtocolError(f"bad message length {length}")
        total = 1 + length
        while len(self.buf) < total:
            self._read_more()
        payload = self.buf[5:total]
        self.buf = self.buf[total:]
        return mtype, payload

    def send(self, mtype: bytes, payload: bytes = b"") -> None:
        self.sock.sendall(mtype + struct.pack("!i", len(payload) + 4)
                          + payload)

    # ------------------------------------------------------- handshake

    def startup(self, user: str, database: str, password: Optional[str]
                ) -> None:
        body = struct.pack("!i", PROTOCOL_V3)
        body += _cstr(b"user") + _cstr(user.encode())
        body += _cstr(b"database") + _cstr(database.encode())
        body += b"\x00"
        self.sock.sendall(struct.pack("!i", len(body) + 4) + body)
        scram = None
        while True:
            mtype, payload = self.read_message()
            if mtype == b"R":
                (code,) = struct.unpack("!i", payload[:4])
                if code == _AUTH_OK:
                    continue
                if password is None:
                    raise PgError({"S": "FATAL", "C": "28P01",
                                   "M": "password required"})
                if code == _AUTH_CLEARTEXT:
                    self.send(b"p", _cstr(password.encode()))
                elif code == _AUTH_MD5:
                    salt = payload[4:8]
                    self.send(b"p", _cstr(md5_password(user, password,
                                                       salt)))
                elif code == _AUTH_SASL:
                    mechs = payload[4:].split(b"\x00")
                    if b"SCRAM-SHA-256" not in mechs:
                        raise PgProtocolError(
                            f"no supported SASL mechanism in {mechs!r}"
                        )
                    from ..scram import ScramClient

                    # pg takes the username from the startup packet;
                    # the SCRAM n= attribute is ignored (libpq sends
                    # an empty name)
                    scram = ScramClient("", password)
                    first = scram.client_first()
                    self.send(b"p", _cstr(b"SCRAM-SHA-256")
                              + struct.pack("!i", len(first)) + first)
                elif code == _AUTH_SASL_CONTINUE:
                    if scram is None:
                        raise PgProtocolError("SASL continue before start")
                    self.send(b"p", scram.client_final(payload[4:]))
                elif code == _AUTH_SASL_FINAL:
                    if scram is None or not scram.verify_server_final(
                        payload[4:]
                    ):
                        raise PgProtocolError(
                            "server SCRAM signature verification failed"
                        )
                else:
                    raise PgProtocolError(
                        f"unsupported auth request code {code}"
                    )
            elif mtype == b"E":
                raise PgError(parse_error_fields(payload))
            elif mtype == b"S":
                k, v = payload.split(b"\x00")[:2]
                self.parameters[k.decode()] = v.decode()
            elif mtype == b"K":
                self.backend_pid, self.secret_key = struct.unpack(
                    "!ii", payload
                )
            elif mtype == b"N":
                continue  # NoticeResponse
            elif mtype == b"Z":
                return  # ReadyForQuery
            else:
                raise PgProtocolError(
                    f"unexpected message {mtype!r} during startup"
                )

    # ----------------------------------------------------------- query

    def extended_query(self, sql: str, args: List[Optional[str]]
                       ) -> List[Dict[str, Any]]:
        """Parse/Bind/Describe/Execute/Sync with text params+results —
        the epgsql equery analog (unnamed statement, single use)."""
        out = b""
        out += self._msg(b"P", _cstr(b"") + _cstr(sql.encode())
                         + struct.pack("!h", 0))
        bind = _cstr(b"") + _cstr(b"")  # portal, statement
        bind += struct.pack("!h", 0)  # all params text format
        bind += struct.pack("!h", len(args))
        for a in args:
            if a is None:
                bind += struct.pack("!i", -1)
            else:
                # text-format params: coerce ints/floats/bools from
                # generic callers (rule-engine sinks) to their pg
                # literal form rather than failing mid-checkout
                if isinstance(a, bool):
                    a = "t" if a else "f"
                ab = a.encode("utf-8") if isinstance(a, str) else \
                    str(a).encode("utf-8")
                bind += struct.pack("!i", len(ab)) + ab
        bind += struct.pack("!h", 0)  # all results text format
        out += self._msg(b"B", bind)
        out += self._msg(b"D", b"P" + _cstr(b""))
        out += self._msg(b"E", _cstr(b"") + struct.pack("!i", 0))
        out += self._msg(b"S", b"")
        self.sock.sendall(out)
        return self._collect_rows()

    def simple_query(self, sql: str) -> List[Dict[str, Any]]:
        self.send(b"Q", _cstr(sql.encode()))
        return self._collect_rows()

    @staticmethod
    def _msg(mtype: bytes, payload: bytes) -> bytes:
        return mtype + struct.pack("!i", len(payload) + 4) + payload

    def _collect_rows(self) -> List[Dict[str, Any]]:
        """Drain to ReadyForQuery, gathering DataRows; an ErrorResponse
        is raised only after Z so the connection stays in sync."""
        cols: List[Tuple[str, int]] = []  # (name, type oid)
        rows: List[Dict[str, Any]] = []
        error: Optional[PgError] = None
        while True:
            mtype, payload = self.read_message()
            if mtype == b"T":  # RowDescription
                cols = []
                (nfields,) = struct.unpack("!h", payload[:2])
                off = 2
                for _ in range(nfields):
                    end = payload.index(b"\x00", off)
                    name = payload[off:end].decode()
                    off = end + 1
                    _tab, _att, oid, _len, _mod, _fmt = struct.unpack(
                        "!ihihih", payload[off:off + 18]
                    )
                    off += 18
                    cols.append((name, oid))
            elif mtype == b"D":  # DataRow
                (ncols,) = struct.unpack("!h", payload[:2])
                off = 2
                row: Dict[str, Any] = {}
                for i in range(ncols):
                    (vlen,) = struct.unpack("!i", payload[off:off + 4])
                    off += 4
                    if vlen < 0:
                        val = None
                    else:
                        val = payload[off:off + vlen]
                        off += vlen
                    name, oid = cols[i] if i < len(cols) else (str(i), 0)
                    row[name] = _decode_col(val, oid)
                rows.append(row)
            elif mtype == b"E":
                error = PgError(parse_error_fields(payload))
            elif mtype == b"Z":
                if error is not None:
                    raise error
                return rows
            elif mtype in (b"C", b"1", b"2", b"3", b"n", b"I", b"s",
                           b"N", b"S"):
                continue  # Complete/NoData/Notice/ParameterStatus
            else:
                raise PgProtocolError(f"unexpected message {mtype!r}")


def parse_error_fields(payload: bytes) -> Dict[str, str]:
    """ErrorResponse/NoticeResponse: repeated (code byte + cstring)."""
    fields: Dict[str, str] = {}
    off = 0
    while off < len(payload) and payload[off:off + 1] != b"\x00":
        code = payload[off:off + 1].decode()
        end = payload.index(b"\x00", off + 1)
        fields[code] = payload[off + 1:end].decode("utf-8", "replace")
        off = end + 1
    return fields


class PgDriver(PooledDriver):
    """Pooled PostgreSQL client satisfying the emqx_tpu driver contract
    (`query(template, params)` with ${var} placeholders)."""

    KIND = "pgsql"
    RECOVERABLE = (PgError,)

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 5432,
        username: str = "postgres",
        password: Optional[str] = None,
        database: str = "postgres",
        pool_size: int = 4,
        timeout: float = 5.0,
        **_ignored,
    ):
        super().__init__(pool_size=pool_size, timeout=timeout)
        self.host = host
        self.port = int(port)
        self.username = username
        self.password = password
        self.database = database

    def _dial(self) -> _Conn:
        conn = _Conn(self.host, self.port, self.timeout)
        try:
            conn.startup(self.username, self.database, self.password)
        except Exception:
            conn.close()
            raise
        return conn

    # --------------------------------------------------------- contract

    @staticmethod
    def _is_read(sql: str) -> bool:
        """Reads are replayed on a fresh dial after a mid-command socket
        death; writes are not (they may have committed server-side)."""
        head = sql.lstrip().split(None, 1)
        return bool(head) and head[0].upper() in (
            "SELECT", "SHOW", "VALUES", "WITH", "EXPLAIN", "TABLE"
        )

    def query(self, template: str, params: Dict[str, str]
              ) -> List[Dict[str, Any]]:
        """Run a ${var} template as a parameterized extended query."""
        sql, order = template_to_wire(template)
        args = [params.get(name) for name in order]
        return self._run(lambda conn: conn.extended_query(sql, args),
                         retryable=self._is_read(sql))

    def command(self, sql: str) -> List[Dict[str, Any]]:
        """Raw simple query (no parameters) — epgsql squery analog."""
        return self._run(lambda conn: conn.simple_query(sql),
                         retryable=self._is_read(sql))

    def health_check(self) -> bool:
        """`SELECT count(1)` like the reference's do_health_check
        (`emqx_connector_pgsql.erl:112-113`)."""
        try:
            rows = self.command("SELECT count(1) AS t")
            return bool(rows)
        except Exception:
            return False
