"""Bridges: broker traffic <-> connectors — `emqx_bridge` analog.

Egress: a 'message.publish' hook matches a local topic filter, renders
${placeholder} templates (topic/payload/qos/clientid...), and enqueues
the render into a bounded buffer drained by an async worker that calls
the connector — send failures retry with backoff, overflow drops oldest
(the replayq-backed buffering model, in memory).

Ingress: the connector subscribes remotely; arriving messages are
re-published locally under a templated topic.
"""

from __future__ import annotations

import asyncio
import logging
from collections import deque
from typing import Callable, Dict, Optional

from ..broker import topic as topiclib
from ..broker.broker import Broker
from ..broker.message import Message
from ..rules.engine import render_template

log = logging.getLogger("emqx_tpu.bridge")


def _msg_env(msg: Message) -> Dict:
    return {
        "topic": msg.topic,
        "payload": msg.payload.decode("utf-8", "replace"),
        "qos": msg.qos,
        "retain": msg.retain,
        "clientid": msg.from_client,
        "username": msg.from_username,
        "id": msg.mid.hex(),
        "timestamp": msg.timestamp,
    }


class EgressBridge:
    def __init__(
        self,
        broker: Broker,
        connector,
        local_filter: str,
        remote_topic: str = "${topic}",
        payload_template: str = "${payload}",
        qos: int = 0,
        max_buffer: int = 10_000,
        retry_interval: float = 1.0,
        send: Optional[Callable] = None,
    ):
        self.broker = broker
        self.connector = connector
        self.local_filter = local_filter
        self.remote_topic = remote_topic
        self.payload_template = payload_template
        self.qos = qos
        self.buffer: deque = deque(maxlen=max_buffer)
        self.retry_interval = retry_interval
        self.dropped = 0
        self.sent = 0
        self.failed = 0
        self._send = send or self._send_default
        self._worker: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self.broker.hooks.put("message.publish", self._on_publish, priority=-300)
        self._worker = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self.broker.hooks.delete("message.publish", self._on_publish)
        if self._worker:
            self._worker.cancel()
            try:
                await self._worker
            except (asyncio.CancelledError, Exception):
                pass

    # -------------------------------------------------------------- egress

    def _on_publish(self, msg):
        if not isinstance(msg, Message) or msg.headers.get("bridged"):
            return None
        if not topiclib.match(msg.topic, self.local_filter):
            return None
        env = _msg_env(msg)
        item = (
            render_template(self.remote_topic, env, env),
            render_template(self.payload_template, env, env).encode(),
        )
        if len(self.buffer) == self.buffer.maxlen:
            self.dropped += 1
        self.buffer.append(item)
        self._wake.set()
        return None

    async def _run(self) -> None:
        while True:
            if not self.buffer:
                self._wake.clear()
                await self._wake.wait()
            topic, payload = self.buffer[0]
            try:
                await self._send(topic, payload)
                self.buffer.popleft()
                self.sent += 1
            except Exception as e:
                self.failed += 1
                log.debug("bridge send failed: %s", e)
                await asyncio.sleep(self.retry_interval)

    async def _send_default(self, topic: str, payload: bytes) -> None:
        await self.connector.publish(topic, payload, qos=self.qos)

    def stats(self) -> dict:
        return {
            "sent": self.sent,
            "failed": self.failed,
            "dropped": self.dropped,
            "buffered": len(self.buffer),
        }


class HttpEgressBridge(EgressBridge):
    """Egress variant posting JSON to an HttpConnector path (webhook)."""

    def __init__(self, broker, connector, local_filter: str, path: str = "/",
                 **kw):
        super().__init__(broker, connector, local_filter, send=self._post, **kw)
        self.path = path

    async def _post(self, topic: str, payload: bytes) -> None:
        status, _ = await self.connector.post_json(
            self.path, {"topic": topic, "payload": payload.decode("utf-8", "replace")}
        )
        if status >= 300:
            raise ConnectionError(f"webhook status {status}")


class IngressBridge:
    def __init__(
        self,
        broker: Broker,
        connector,
        remote_filter: str,
        local_topic: str = "${topic}",
        qos: int = 0,
    ):
        self.broker = broker
        self.connector = connector
        self.remote_filter = remote_filter
        self.local_topic = local_topic
        self.qos = qos
        self.received = 0

    async def start(self) -> None:
        self.connector.on_message = self._on_remote
        await self.connector.subscribe(self.remote_filter, qos=self.qos)

    def _on_remote(self, msg) -> None:
        env = {
            "topic": msg.topic,
            "payload": msg.payload.decode("utf-8", "replace"),
            "qos": msg.qos,
        }
        self.received += 1
        self.broker.publish(Message(
            topic=render_template(self.local_topic, env, env),
            payload=msg.payload,
            qos=self.qos,
            headers={"bridged": True},  # loop guard for paired bridges
        ))
