"""Bridges: broker traffic <-> connectors — `emqx_bridge` analog.

Egress: a 'message.publish' hook matches a local topic filter, renders
${placeholder} templates (topic/payload/qos/clientid...), and enqueues
the render into a bounded buffer drained by an async worker that calls
the connector — send failures retry with backoff, overflow drops
oldest.  With `queue_dir` set the buffer is the disk-backed replay
queue (`utils/replayq.py`, the replayq analog): messages survive a
node restart and unconfirmed sends are replayed, like the reference's
replayq-buffered bridges.

Ingress: the connector subscribes remotely; arriving messages are
re-published locally under a templated topic.
"""

from __future__ import annotations

import asyncio
import logging
import struct
from collections import deque
from typing import Callable, Dict, Optional

from ..broker import topic as topiclib
from ..broker.broker import Broker
from ..broker.message import Message
from ..rules.engine import render_template

log = logging.getLogger("emqx_tpu.bridge")


def _msg_env(msg: Message) -> Dict:
    return {
        "topic": msg.topic,
        "payload": msg.payload.decode("utf-8", "replace"),
        "qos": msg.qos,
        "retain": msg.retain,
        "clientid": msg.from_client,
        "username": msg.from_username,
        "id": msg.mid.hex(),
        "timestamp": msg.timestamp,
    }


class EgressBridge:
    def __init__(
        self,
        broker: Broker,
        connector,
        local_filter: str,
        remote_topic: str = "${topic}",
        payload_template: str = "${payload}",
        qos: int = 0,
        max_buffer: int = 10_000,
        retry_interval: float = 1.0,
        send: Optional[Callable] = None,
        queue_dir: Optional[str] = None,
        max_queue_bytes: int = 0,
    ):
        self.broker = broker
        self.connector = connector
        self.local_filter = local_filter
        self.remote_topic = remote_topic
        self.payload_template = payload_template
        self.qos = qos
        self.queue = None
        if queue_dir is not None:
            from ..utils.replayq import ReplayQ

            self.queue = ReplayQ(queue_dir,
                                 max_total_bytes=max_queue_bytes)
        self.buffer: deque = deque(maxlen=max_buffer)
        self.retry_interval = retry_interval
        self.dropped = 0
        self.sent = 0
        self.failed = 0
        self._send = send or self._send_default
        self._worker: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self.broker.hooks.put("message.publish", self._on_publish, priority=-300)
        self._worker = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self.broker.hooks.delete("message.publish", self._on_publish)
        if self._worker:
            self._worker.cancel()
            try:
                await self._worker
            except (asyncio.CancelledError, Exception):
                pass
        if self.queue is not None:
            self.queue.close()

    # -------------------------------------------------------------- egress

    @staticmethod
    def _marshal(topic: str, payload: bytes) -> bytes:
        tb = topic.encode("utf-8")
        return struct.pack("<I", len(tb)) + tb + payload

    @staticmethod
    def _unmarshal(item: bytes):
        (tlen,) = struct.unpack_from("<I", item, 0)
        return (item[4:4 + tlen].decode("utf-8"), item[4 + tlen:])

    def _on_publish(self, msg):
        if not isinstance(msg, Message) or msg.headers.get("bridged"):
            return None
        if not topiclib.match(msg.topic, self.local_filter):
            return None
        env = _msg_env(msg)
        topic = render_template(self.remote_topic, env, env)
        payload = render_template(self.payload_template, env, env).encode()
        self.enqueue(topic, payload)
        return None

    def enqueue(self, topic: str, payload: bytes) -> None:
        """Buffer one item for delivery — the `emqx_bridge:send_message`
        entry point (rule-engine bridge outputs use it directly)."""
        if self.queue is not None:
            try:
                self.queue.append(self._marshal(topic, payload))
            except OSError as e:
                # disk trouble must not propagate into the caller's
                # publish path — account it like a buffer overflow
                self.dropped += 1
                log.warning("bridge queue append failed: %s", e)
                return
        else:
            if len(self.buffer) == self.buffer.maxlen:
                self.dropped += 1
            self.buffer.append((topic, payload))
        self._wake.set()

    def _buffered(self) -> int:
        return (self.queue.count() if self.queue is not None
                else len(self.buffer))

    _POP_BATCH = 32  # amortize the per-ack commit write

    async def _run(self) -> None:
        while True:
            if not self._buffered():
                self._wake.clear()
                if not self._buffered():  # append may race the clear
                    await self._wake.wait()
            try:
                if self.queue is not None:
                    await self._drain_queue_batch()
                else:
                    await self._drain_mem_one()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # never die silently
                self.failed += 1
                log.warning("bridge worker error: %s", e)
                await asyncio.sleep(self.retry_interval)

    async def _drain_mem_one(self) -> None:
        # pop BEFORE the await: leaving the item at the head lets a
        # full deque evict the in-flight message mid-send and popleft
        # would then discard a never-sent one
        topic, payload = self.buffer.popleft()
        try:
            await self._send(topic, payload)
            self.sent += 1
        except Exception as e:
            self.failed += 1
            log.debug("bridge send failed: %s", e)
            if len(self.buffer) == self.buffer.maxlen:
                self.dropped += 1  # retry displaced by newer traffic
            else:
                self.buffer.appendleft((topic, payload))
            await asyncio.sleep(self.retry_interval)

    async def _drain_queue_batch(self) -> None:
        ack_ref, items = self.queue.pop(self._POP_BATCH)
        if not items:
            return
        seq_before = ack_ref - len(items)  # seqno preceding the batch
        done = 0  # items fully sent this round
        try:
            for item in items:
                topic, payload = self._unmarshal(item)
                await self._send(topic, payload)
                self.sent += 1
                done += 1
        except (ValueError, struct.error, UnicodeDecodeError) as e:
            # damaged record: drop IT (ack past it), keep the rest
            log.warning("bridge dropping damaged queued record: %s", e)
            self.dropped += 1
            self.queue.ack(seq_before + done + 1)
            self.queue.requeue(ack_ref, items[done + 1:])
            return
        except Exception as e:
            self.failed += 1
            log.debug("bridge send failed: %s", e)
            # confirm the delivered prefix, put the rest back
            if done:
                self.queue.ack(seq_before + done)
            self.queue.requeue(ack_ref, items[done:])
            await asyncio.sleep(self.retry_interval)
            return
        self.queue.ack(ack_ref)

    async def _send_default(self, topic: str, payload: bytes) -> None:
        await self.connector.publish(topic, payload, qos=self.qos)

    def stats(self) -> dict:
        dropped = self.dropped + (self.queue.dropped
                                  if self.queue is not None else 0)
        return {
            "sent": self.sent,
            "failed": self.failed,
            "dropped": dropped,
            "buffered": self._buffered(),
        }


class HttpEgressBridge(EgressBridge):
    """Egress variant posting JSON to an HttpConnector path (webhook)."""

    def __init__(self, broker, connector, local_filter: str, path: str = "/",
                 **kw):
        super().__init__(broker, connector, local_filter, send=self._post, **kw)
        self.path = path

    async def _post(self, topic: str, payload: bytes) -> None:
        status, _ = await self.connector.post_json(
            self.path, {"topic": topic, "payload": payload.decode("utf-8", "replace")}
        )
        if status >= 300:
            raise ConnectionError(f"webhook status {status}")


class IngressBridge:
    def __init__(
        self,
        broker: Broker,
        connector,
        remote_filter: str,
        local_topic: str = "${topic}",
        qos: int = 0,
    ):
        self.broker = broker
        self.connector = connector
        self.remote_filter = remote_filter
        self.local_topic = local_topic
        self.qos = qos
        self.received = 0

    async def start(self) -> None:
        self.connector.on_message = self._on_remote
        await self.connector.subscribe(self.remote_filter, qos=self.qos)

    def _on_remote(self, msg) -> None:
        env = {
            "topic": msg.topic,
            "payload": msg.payload.decode("utf-8", "replace"),
            "qos": msg.qos,
        }
        self.received += 1
        self.broker.publish(Message(
            topic=render_template(self.local_topic, env, env),
            payload=msg.payload,
            qos=self.qos,
            headers={"bridged": True},  # loop guard for paired bridges
        ))
