"""Connector implementations — `emqx_connector` analogs (HTTP, MQTT).

HttpConnector: minimal asyncio HTTP/1.1 client with keep-alive
(`emqx_connector_http`/ehttpc analog).  MqttConnector: a client session
to a remote broker built on the in-repo MqttClient, supporting egress
publish and ingress subscriptions (`emqx_connector_mqtt` analog).
Database connectors (MySQL/PgSQL/Mongo/Redis/LDAP) need drivers absent
from this image; they register as unavailable stubs so configs naming
them fail loud at create time rather than silently.
"""

from __future__ import annotations

import asyncio
import json
from typing import Callable, Dict, List, Optional, Tuple
from urllib.parse import urlsplit

from ..broker.client import MqttClient


class HttpConnector:
    def __init__(self, base_url: str, timeout: float = 10.0,
                 headers: Optional[Dict[str, str]] = None):
        parts = urlsplit(base_url)
        if parts.scheme != "http":
            raise ValueError("only http:// supported (no TLS stack configured)")
        self.host = parts.hostname or "127.0.0.1"
        self.port = parts.port or 80
        self.base_path = parts.path.rstrip("/")
        self.timeout = timeout
        self.headers = headers or {}
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()

    async def start(self) -> None:
        await self._ensure()

    async def stop(self) -> None:
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
            self._reader = self._writer = None

    async def _ensure(self) -> None:
        if self._writer is None or self._writer.is_closing():
            self._reader, self._writer = await asyncio.wait_for(
                asyncio.open_connection(self.host, self.port), self.timeout
            )

    async def health_check(self) -> bool:
        try:
            await self._ensure()
            return True
        except Exception:
            return False

    async def request(self, method: str, path: str, body: Optional[bytes] = None,
                      headers: Optional[Dict[str, str]] = None) -> Tuple[int, bytes]:
        async with self._lock:  # keep-alive conn: serialize requests
            await self._ensure()
            h = {
                "Host": f"{self.host}:{self.port}",
                "Content-Length": str(len(body or b"")),
                "Connection": "keep-alive",
            }
            h.update(self.headers)
            h.update(headers or {})
            head = f"{method} {self.base_path}{path} HTTP/1.1\r\n"
            head += "".join(f"{k}: {v}\r\n" for k, v in h.items()) + "\r\n"
            try:
                self._writer.write(head.encode() + (body or b""))
                await self._writer.drain()
                return await asyncio.wait_for(self._read_response(), self.timeout)
            except (ConnectionError, asyncio.IncompleteReadError):
                await self.stop()
                raise

    async def _read_response(self) -> Tuple[int, bytes]:
        status_line = await self._reader.readline()
        status = int(status_line.split()[1])
        headers: Dict[str, str] = {}
        while True:
            line = await self._reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.decode().partition(":")
            headers[k.strip().lower()] = v.strip()
        n = int(headers.get("content-length", 0) or 0)
        body = await self._reader.readexactly(n) if n else b""
        if headers.get("connection", "").lower() == "close":
            await self.stop()
        return status, body

    async def post_json(self, path: str, obj) -> Tuple[int, bytes]:
        return await self.request(
            "POST", path, json.dumps(obj).encode(),
            {"Content-Type": "application/json"},
        )


class MqttConnector:
    """Session to a remote MQTT broker (bridge transport)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 1883,
                 clientid: str = "emqx_tpu_bridge", username: Optional[str] = None,
                 password: Optional[bytes] = None, keepalive: int = 60):
        self.host = host
        self.port = port
        self.clientid = clientid
        self.username = username
        self.password = password
        self.keepalive = keepalive
        self.client: Optional[MqttClient] = None
        self.on_message: Optional[Callable] = None
        self._subs: List[Tuple[str, int]] = []
        self._pump: Optional[asyncio.Task] = None

    async def start(self) -> None:
        self.client = MqttClient(
            clientid=self.clientid, username=self.username,
            password=self.password, keepalive=self.keepalive,
        )
        await self.client.connect(host=self.host, port=self.port)
        for filt, qos in self._subs:
            await self.client.subscribe(filt, qos=qos)
        self._pump = asyncio.get_running_loop().create_task(self._pump_loop())

    async def stop(self) -> None:
        if self._pump:
            self._pump.cancel()
            self._pump = None
        if self.client is not None:
            try:
                await self.client.disconnect()
            except Exception:
                pass
            self.client = None

    async def health_check(self) -> bool:
        return self.client is not None and not self.client.closed.is_set()

    async def subscribe(self, filt: str, qos: int = 0) -> None:
        self._subs.append((filt, qos))
        if self.client is not None:
            await self.client.subscribe(filt, qos=qos)

    async def publish(self, topic: str, payload: bytes, qos: int = 0,
                      retain: bool = False) -> None:
        if self.client is None:
            raise ConnectionError("bridge not connected")
        await self.client.publish(topic, payload, qos=qos, retain=retain)

    async def _pump_loop(self) -> None:
        try:
            while True:
                msg = await self.client.recv()
                if self.on_message is not None:
                    r = self.on_message(msg)
                    if asyncio.iscoroutine(r):
                        await r
        except asyncio.CancelledError:
            raise  # stop() cancelled the pump: report cancelled, not done
        except Exception:
            pass


class DbConnector:
    """Resource-manager adapter over an injected database driver
    (`emqx_connector_{mysql,pgsql,redis,mongo}` analog).  The sync
    driver contract (emqx_tpu.drivers) is bridged onto the async
    resource lifecycle with to_thread so a slow database cannot stall
    the event loop."""

    def __init__(self, kind: str, driver=None, **driver_cfg):
        from .. import drivers

        self.kind = kind
        self.driver = driver if driver is not None else drivers.make_driver(
            kind, **driver_cfg
        )

    async def start(self) -> None:
        fn = getattr(self.driver, "start", None)
        if fn is not None:
            await asyncio.to_thread(fn)

    async def stop(self) -> None:
        fn = getattr(self.driver, "stop", None)
        if fn is not None:
            await asyncio.to_thread(fn)

    async def health_check(self) -> bool:
        try:
            return bool(await asyncio.to_thread(self.driver.health_check))
        except Exception:
            return False

    async def query(self, statement: str, params: Optional[dict] = None):
        return await asyncio.to_thread(self.driver.query, statement, params or {})

    async def command(self, *args):
        return await asyncio.to_thread(self.driver.command, *args)


def make_connector(kind: str, **cfg):
    """Connector factory keyed like the reference's connector types.

    DB kinds resolve through the driver registry
    (emqx_tpu.drivers.register_driver); without a registered driver they
    raise DriverUnavailable at create time — loud, not silent."""
    from .. import drivers

    if kind == "http":
        return HttpConnector(**cfg)
    if kind == "mqtt":
        return MqttConnector(**cfg)
    if drivers.driver_available(kind):
        # bundled wire-protocol kinds plus any site-registered kind
        return DbConnector(kind, **cfg)
    raise ValueError(
        f"unknown connector kind {kind!r} — register a driver for it "
        f"via emqx_tpu.drivers.register_driver first"
    )
