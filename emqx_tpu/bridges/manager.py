"""Bridge manager — config-driven bridge lifecycle (`emqx_bridge`).

The reference's emqx_bridge app turns `bridges.{http,mqtt}.<name>`
config into resource-managed connector instances with egress/ingress
message flow and a REST surface (`emqx_bridge.erl`,
`emqx_bridge_api.erl` — this version ships HTTP and MQTT bridge types,
`emqx_bridge_schema.erl`).  Same here:

* each bridge definition creates a connector (HTTP webhook or remote
  MQTT session), registered in the ResourceManager for health checks
  and auto-restart;
* egress: local 'message.publish' traffic matching `local_topic` is
  templated and forwarded (optionally through the disk-backed replay
  queue — `durable: true`); ingress (mqtt only): remote subscriptions
  re-publish locally;
* a connector that is down at boot does NOT fail the node — the
  resource manager keeps probing and restarting, and the egress buffer
  absorbs traffic meanwhile (reference bridges behave the same);
* enable/disable/restart per bridge + stats, served over REST.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, List, Optional

from .bridge import EgressBridge, HttpEgressBridge, IngressBridge
from .connectors import HttpConnector, MqttConnector
from .resource import ResourceManager

log = logging.getLogger("emqx_tpu.bridges")


class _Managed:
    def __init__(self, definition: Dict[str, Any]):
        self.definition = definition
        self.connector = None
        self.bridge = None
        self.enabled = bool(definition.get("enable", True))


class BridgeManager:
    def __init__(self, broker, data_dir: str = "data",
                 definitions: Optional[List[Dict[str, Any]]] = None):
        self.broker = broker
        self.data_dir = data_dir
        self.resources = ResourceManager()
        self._bridges: Dict[str, _Managed] = {}
        self._defs = list(definitions or [])

    # ---------------------------------------------------------- lifecycle

    async def start(self) -> None:
        for d in self._defs:
            await self.create(d)

    async def stop(self) -> None:
        for name in list(self._bridges):
            await self._stop_bridge(self._bridges[name])
        await self.resources.stop_all()
        self._bridges.clear()

    def _auto_name(self, d: Dict[str, Any]) -> str:
        base = d.get("type", "bridge")
        i = 0
        while f"{base}_{i}" in self._bridges:
            i += 1
        return f"{base}_{i}"

    async def create(self, d: Dict[str, Any]) -> None:
        d = dict(d)
        d["name"] = name = d.get("name") or self._auto_name(d)
        if name in self._bridges:
            raise ValueError(f"bridge {name!r} exists")
        m = _Managed(d)
        # build everything BEFORE registering, so a bad definition
        # (unknown type, invalid direction) leaves no half-created
        # entry behind — a corrected re-create must succeed
        m.connector = self._make_connector(d)
        # resource-managed: a down endpoint -> DISCONNECTED + retries,
        # never a boot failure
        await self.resources.create(
            f"bridge:{name}", m.connector,
            health_interval=float(d.get("health_check_interval", 15.0)),
        )
        try:
            if m.enabled:
                await self._start_bridge(m)
        except Exception:
            await self.resources.remove(f"bridge:{name}")
            raise
        self._bridges[name] = m

    @staticmethod
    def _make_connector(d: Dict[str, Any]):
        typ = d.get("type", "http")
        cfg = dict(d.get("connector") or {})
        if typ == "http":
            return HttpConnector(cfg.pop("base_url",
                                         d.get("url", "http://127.0.0.1")),
                                 **cfg)
        if typ == "mqtt":
            return MqttConnector(**cfg)
        raise ValueError(
            f"unsupported bridge type {typ!r} (http|mqtt, matching the "
            f"reference's emqx_bridge_schema)"
        )

    def _queue_dir(self, name: str, d: Dict[str, Any]) -> Optional[str]:
        if not d.get("durable"):
            return None
        return os.path.join(self.data_dir, "bridges", name)

    async def _start_bridge(self, m: _Managed) -> None:
        d = m.definition
        name = d.get("name")
        direction = d.get("direction", "egress")
        if direction == "egress":
            kw = dict(
                qos=int(d.get("qos", 0)),
                max_buffer=int(d.get("max_buffer", 10_000)),
                retry_interval=float(d.get("retry_interval", 1.0)),
                queue_dir=self._queue_dir(name, d),
                max_queue_bytes=int(d.get("max_queue_bytes", 0)),
            )
            if d.get("type") == "http":
                m.bridge = HttpEgressBridge(
                    self.broker, m.connector,
                    d.get("local_topic", "#"),
                    path=d.get("path", "/"), **kw,
                )
            else:
                m.bridge = EgressBridge(
                    self.broker, m.connector,
                    d.get("local_topic", "#"),
                    remote_topic=d.get("remote_topic", "${topic}"),
                    payload_template=d.get("payload", "${payload}"),
                    **kw,
                )
            m.bridge.start()
        elif direction == "ingress":
            if d.get("type") != "mqtt":
                raise ValueError("ingress bridges require type mqtt")
            m.bridge = IngressBridge(
                self.broker, m.connector,
                d.get("remote_topic", "#"),
                local_topic=d.get("local_topic", "${topic}"),
                qos=int(d.get("qos", 0)),
            )
            try:
                await m.bridge.start()
            except Exception as e:
                # remote down: the resource manager will reconnect; the
                # subscription is replayed by MqttConnector.start
                log.info("ingress bridge %s deferred: %s", name, e)
        else:
            raise ValueError(f"unknown bridge direction {direction!r}")

    async def _stop_bridge(self, m: _Managed) -> None:
        if m.bridge is not None and hasattr(m.bridge, "stop"):
            try:
                await m.bridge.stop()
            except Exception:
                pass
        m.bridge = None

    # ------------------------------------------------------------- sending

    def send_message(self, name: str, topic: str, payload: bytes) -> None:
        """The `emqx_bridge:send_message(BridgeId, Selected)` analog
        (`emqx_rule_runtime.erl:270`): push one message into a named
        egress bridge's buffer."""
        m = self._bridges.get(name)
        if m is None:
            raise ValueError(f"no such bridge {name!r}")
        if not m.enabled or m.bridge is None:
            raise ValueError(f"bridge {name!r} is disabled")
        if not hasattr(m.bridge, "enqueue"):
            raise ValueError(f"bridge {name!r} is not an egress bridge")
        m.bridge.enqueue(topic, payload)

    # -------------------------------------------------------------- admin

    def names(self) -> List[str]:
        return list(self._bridges)

    def describe(self, name: str) -> Optional[Dict[str, Any]]:
        m = self._bridges.get(name)
        if m is None:
            return None
        d = m.definition
        info = {
            "name": name,
            "type": d.get("type", "http"),
            "direction": d.get("direction", "egress"),
            "enable": m.enabled,
            "local_topic": d.get("local_topic"),
            "resource": self.resources.list().get(f"bridge:{name}"),
        }
        if m.bridge is not None and hasattr(m.bridge, "stats"):
            info["stats"] = m.bridge.stats()
        elif m.bridge is not None:
            info["stats"] = {"received": m.bridge.received}
        return info

    def list(self) -> List[Dict[str, Any]]:
        return [self.describe(n) for n in self._bridges]

    async def enable(self, name: str) -> bool:
        m = self._bridges.get(name)
        if m is None:
            return False
        if not m.enabled:
            m.enabled = True
            await self._start_bridge(m)
        return True

    async def disable(self, name: str) -> bool:
        m = self._bridges.get(name)
        if m is None:
            return False
        if m.enabled:
            m.enabled = False
            await self._stop_bridge(m)
        return True

    async def restart(self, name: str) -> bool:
        m = self._bridges.get(name)
        if m is None:
            return False
        await self.resources.restart(f"bridge:{name}")
        if m.enabled:
            await self._stop_bridge(m)
            await self._start_bridge(m)
        return True

    async def remove(self, name: str) -> bool:
        m = self._bridges.pop(name, None)
        if m is None:
            return False
        await self._stop_bridge(m)
        await self.resources.remove(f"bridge:{name}")
        return True
