"""Data integration: resource lifecycle, connectors, bridges.

Analog of `emqx_resource` + `emqx_connector` + `emqx_bridge`
(SURVEY.md §1.9): resources are supervised instances with health
checks and auto-restart; connectors implement the transport (HTTP,
MQTT); bridges wire broker traffic to connectors (egress: local
publishes out; ingress: remote messages in) with ${placeholder}
templating and a bounded retry buffer (the replayq analog).
"""

from .bridge import EgressBridge, IngressBridge
from .connectors import HttpConnector, MqttConnector
from .resource import ResourceManager, ResourceStatus

__all__ = [
    "EgressBridge",
    "IngressBridge",
    "HttpConnector",
    "MqttConnector",
    "ResourceManager",
    "ResourceStatus",
]
