"""LDAP client — real LDAPv3 BER wire protocol, pooled, stdlib-only.

The analog of the reference's eldap-backed connector
(`/root/reference/apps/emqx_connector/src/emqx_connector_ldap.erl`:
pooled clients that simple-bind with a service DN on connect and run
`search(Base, Filter, Attributes)` queries), speaking LDAPv3 (RFC 4511)
BER over plain TCP — no external client library, so the "ldap" kind of
the driver seam is a real driver out of the box.

Implements:
* a BER codec for the LDAP subset: bind request/response, search
  request (scope/deref/limits), search result entries/done, unbind;
* an RFC 4515 filter-string parser — `(&(objectClass=mqttUser)
  (uid=${username}))`, equality / presence / substring / and / or /
  not — compiled to the BER filter CHOICE;
* `query(filter_template, params)`: render ${var} placeholders with
  RFC 4515 value escaping, search under the configured base DN, and
  return entries as dicts (attribute → value, multi-valued → list,
  plus "dn") so the authn/authz DB paths consume them unchanged;
* `command("bind", dn, password)`: the verify-by-bind flow of classic
  LDAP authentication, on a throwaway connection.
"""

from __future__ import annotations

import socket
from typing import Any, Dict, List, Optional, Tuple

from .dbpool import PooledDriver

# application tags (RFC 4511 §4)
_APP_BIND_REQ = 0x60
_APP_BIND_RESP = 0x61
_APP_UNBIND = 0x42
_APP_SEARCH_REQ = 0x63
_APP_SEARCH_ENTRY = 0x64
_APP_SEARCH_DONE = 0x65
_APP_SEARCH_REF = 0x73

_RESULT_SUCCESS = 0
_RESULT_INVALID_CREDENTIALS = 49


class LdapError(Exception):
    """Non-success LDAPResult; .code holds the resultCode."""

    def __init__(self, code: int, message: str = ""):
        self.code = code
        super().__init__(f"ldap resultCode={code} {message}".strip())


class LdapProtocolError(Exception):
    """Malformed BER / unexpected protocol op."""


# ----------------------------------------------------------------- BER

def ber_len(n: int) -> bytes:
    if n < 0x80:
        return bytes((n,))
    body = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes((0x80 | len(body),)) + body


def tlv(tag: int, payload: bytes) -> bytes:
    return bytes((tag,)) + ber_len(len(payload)) + payload


def ber_int(v: int, tag: int = 0x02) -> bytes:
    if v == 0:
        return tlv(tag, b"\x00")
    body = v.to_bytes((v.bit_length() // 8) + 1, "big", signed=True)
    return tlv(tag, body)


def ber_str(s, tag: int = 0x04) -> bytes:
    b = s.encode("utf-8") if isinstance(s, str) else bytes(s)
    return tlv(tag, b)


def parse_tlv(data: bytes, off: int) -> Tuple[int, bytes, int]:
    """→ (tag, payload, offset after the TLV)."""
    if off + 2 > len(data):
        raise LdapProtocolError("truncated TLV header")
    tag = data[off]
    first = data[off + 1]
    off += 2
    if first < 0x80:
        length = first
    else:
        nbytes = first & 0x7F
        if nbytes == 0 or off + nbytes > len(data):
            raise LdapProtocolError("bad BER length")
        length = int.from_bytes(data[off:off + nbytes], "big")
        off += nbytes
    if off + length > len(data):
        raise LdapProtocolError("truncated TLV payload")
    return tag, data[off:off + length], off + length


def parse_int(payload: bytes) -> int:
    return int.from_bytes(payload, "big", signed=True)


# -------------------------------------------------- RFC 4515 filters

def escape_filter_value(value: str) -> str:
    """RFC 4515 §3 value escaping — keeps rendered ${var} template
    values from injecting filter structure."""
    out = []
    for ch in value:
        if ch in ("*", "(", ")", "\\", "\x00"):
            out.append("\\%02x" % ord(ch))
        else:
            out.append(ch)
    return "".join(out)


def _unescape(value: str) -> str:
    out, i = [], 0
    while i < len(value):
        if value[i] == "\\" and i + 2 < len(value) + 1:
            out.append(chr(int(value[i + 1:i + 3], 16)))
            i += 3
        else:
            out.append(value[i])
            i += 1
    return "".join(out)


def compile_filter(text: str) -> bytes:
    """RFC 4515 string → BER filter CHOICE."""
    filt, off = _parse_filter(text.strip(), 0)
    if off != len(text.strip()):
        raise ValueError(f"trailing filter text at {off}: {text!r}")
    return filt


def _parse_filter(s: str, off: int) -> Tuple[bytes, int]:
    if off >= len(s) or s[off] != "(":
        raise ValueError(f"expected '(' at {off} in {s!r}")
    off += 1
    if s[off] in "&|":
        tag = 0xA0 if s[off] == "&" else 0xA1
        off += 1
        parts = []
        while off < len(s) and s[off] == "(":
            p, off = _parse_filter(s, off)
            parts.append(p)
        if not parts:
            raise ValueError("empty and/or filter")
        if off >= len(s) or s[off] != ")":
            raise ValueError("unterminated and/or filter")
        return tlv(tag, b"".join(parts)), off + 1
    if s[off] == "!":
        inner, off = _parse_filter(s, off + 1)
        if off >= len(s) or s[off] != ")":
            raise ValueError("unterminated not filter")
        return tlv(0xA2, inner), off + 1
    end = s.index(")", off)
    body = s[off:end]
    if "=" not in body:
        raise ValueError(f"no '=' in filter item {body!r}")
    attr, value = body.split("=", 1)
    if value == "*":  # presence
        return tlv(0x87, attr.encode()), end + 1
    if "*" in value:  # substrings
        chunks = value.split("*")
        subs = b""
        if chunks[0]:
            subs += ber_str(_unescape(chunks[0]), 0x80)  # initial
        for mid in chunks[1:-1]:
            if mid:
                subs += ber_str(_unescape(mid), 0x81)  # any
        if chunks[-1]:
            subs += ber_str(_unescape(chunks[-1]), 0x82)  # final
        return tlv(0xA4, ber_str(attr) + tlv(0x30, subs)), end + 1
    return (tlv(0xA3, ber_str(attr) + ber_str(_unescape(value))),
            end + 1)


# ---------------------------------------------------------------- conn

class _Conn:
    """One blocking socket speaking LDAPMessage TLVs."""

    def __init__(self, host: str, port: int, timeout: float):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.buf = b""
        self.msg_id = 0

    def close(self) -> None:
        try:
            self.sock.sendall(
                tlv(0x30, ber_int(self.msg_id + 1) + tlv(_APP_UNBIND, b""))
            )
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass

    def _read_more(self) -> None:
        chunk = self.sock.recv(65536)
        if not chunk:
            raise ConnectionError("ldap connection closed by peer")
        self.buf += chunk

    def read_message(self) -> Tuple[int, int, bytes]:
        """→ (messageID, protocolOp tag, op payload)."""
        while True:
            try:
                tag, payload, end = parse_tlv(self.buf, 0)
                break
            except LdapProtocolError:
                self._read_more()
        if tag != 0x30:
            raise LdapProtocolError(f"expected LDAPMessage, got {tag:#x}")
        self.buf = self.buf[end:]
        t, idbody, off = parse_tlv(payload, 0)
        if t != 0x02:
            raise LdapProtocolError("missing messageID")
        op_tag, op_payload, _ = parse_tlv(payload, off)
        return parse_int(idbody), op_tag, op_payload

    def request(self, op: bytes) -> int:
        self.msg_id += 1
        self.sock.sendall(tlv(0x30, ber_int(self.msg_id) + op))
        return self.msg_id

    # ------------------------------------------------------------- ops

    def bind(self, dn: str, password: str) -> None:
        op = tlv(_APP_BIND_REQ,
                 ber_int(3) + ber_str(dn) + ber_str(password, 0x80))
        mid = self.request(op)
        rid, tag, payload = self.read_message()
        if rid != mid or tag != _APP_BIND_RESP:
            raise LdapProtocolError(f"unexpected bind reply tag {tag:#x}")
        code, msg = self._parse_result(payload)
        if code != _RESULT_SUCCESS:
            raise LdapError(code, msg)

    def search(self, base: str, filter_ber: bytes,
               attributes: List[str]) -> List[Dict[str, Any]]:
        attrs = b"".join(ber_str(a) for a in attributes)
        op = tlv(_APP_SEARCH_REQ,
                 ber_str(base)
                 + ber_int(2, 0x0A)   # scope: wholeSubtree
                 + ber_int(0, 0x0A)   # deref: never
                 + ber_int(0) + ber_int(0)   # size/time limits
                 + tlv(0x01, b"\x00")  # typesOnly: false
                 + filter_ber
                 + tlv(0x30, attrs))
        mid = self.request(op)
        entries: List[Dict[str, Any]] = []
        while True:
            rid, tag, payload = self.read_message()
            if rid != mid:
                continue  # stale reply from an abandoned op
            if tag == _APP_SEARCH_ENTRY:
                entries.append(self._parse_entry(payload))
            elif tag == _APP_SEARCH_REF:
                continue  # referral (AD forests, referral entries):
                # skip like eldap's default, don't chase or fail
            elif tag == _APP_SEARCH_DONE:
                code, msg = self._parse_result(payload)
                if code != _RESULT_SUCCESS:
                    raise LdapError(code, msg)
                return entries
            else:
                raise LdapProtocolError(
                    f"unexpected search reply tag {tag:#x}"
                )

    @staticmethod
    def _parse_result(payload: bytes) -> Tuple[int, str]:
        tag, code_b, off = parse_tlv(payload, 0)
        _t, _matched, off = parse_tlv(payload, off)
        _t, diag, _ = parse_tlv(payload, off)
        return parse_int(code_b), diag.decode("utf-8", "replace")

    @staticmethod
    def _parse_entry(payload: bytes) -> Dict[str, Any]:
        tag, dn, off = parse_tlv(payload, 0)
        _t, attrs_seq, _ = parse_tlv(payload, off)
        entry: Dict[str, Any] = {"dn": dn.decode("utf-8", "replace")}
        off = 0
        while off < len(attrs_seq):
            _t, one, off = parse_tlv(attrs_seq, off)
            _t2, name_b, o2 = parse_tlv(one, 0)
            _t3, vals_set, _ = parse_tlv(one, o2)
            vals: List[str] = []
            vo = 0
            while vo < len(vals_set):
                _t4, v, vo = parse_tlv(vals_set, vo)
                vals.append(v.decode("utf-8", "replace"))
            name = name_b.decode("utf-8", "replace")
            entry[name] = vals[0] if len(vals) == 1 else vals
        return entry


class LdapDriver(PooledDriver):
    """Pooled LDAP client satisfying the emqx_tpu driver contract."""

    KIND = "ldap"
    RECOVERABLE = (LdapError,)

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 389,
        bind_dn: str = "",
        bind_password: str = "",
        base_dn: str = "",
        attributes: Optional[List[str]] = None,
        pool_size: int = 4,
        timeout: float = 5.0,
        **_ignored,
    ):
        super().__init__(pool_size=pool_size, timeout=timeout)
        self.host = host
        self.port = int(port)
        self.bind_dn = bind_dn
        self.bind_password = bind_password
        self.base_dn = base_dn
        self.attributes = list(attributes or [])

    def _dial(self) -> _Conn:
        conn = _Conn(self.host, self.port, self.timeout)
        try:
            if self.bind_dn:
                conn.bind(self.bind_dn, self.bind_password)
        except Exception:
            conn.close()
            raise
        return conn

    # --------------------------------------------------------- contract

    def query(self, template: str, params: Dict[str, str]
              ) -> List[Dict[str, Any]]:
        """Render a ${var} RFC 4515 filter template (values escaped)
        and search under the configured base DN."""
        escaped = {k: escape_filter_value(str(v))
                   for k, v in params.items()}
        from .. import drivers

        filter_text = drivers.render_template(template, escaped)
        filt = compile_filter(filter_text)
        return self._run(
            lambda conn: conn.search(self.base_dn, filt, self.attributes)
        )

    def search(self, base: str, filter_text: str,
               attributes: Optional[List[str]] = None
               ) -> List[Dict[str, Any]]:
        """eldap-style search with an explicit base."""
        filt = compile_filter(filter_text)
        return self._run(lambda conn: conn.search(
            base, filt, list(attributes or self.attributes)
        ))

    def command(self, *args) -> Any:
        """("bind", dn, password) → bool — classic verify-by-bind on a
        throwaway connection; ("search", base, filter[, attrs])."""
        op = str(args[0]).lower() if args else ""
        if op == "bind":
            conn = _Conn(self.host, self.port, self.timeout)
            try:
                conn.bind(args[1], args[2])
                return True
            except LdapError as e:
                if e.code == _RESULT_INVALID_CREDENTIALS:
                    return False
                raise
            finally:
                conn.close()
        if op == "search":
            return self.search(args[1], args[2], *args[3:])
        raise ValueError(f"unsupported ldap command {args!r}")

    def health_check(self) -> bool:
        """Checkout+checkin: the bind on dial is the probe (the
        reference's do_health_check is a no-op `{ok, true}` too)."""
        try:
            self._checkin(self._checkout())
            return True
        except Exception:
            return False
