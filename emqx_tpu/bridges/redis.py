"""Redis client — real RESP wire protocol, pooled, stdlib-only.

The analog of the reference's eredis-backed connector
(`apps/emqx_connector/src/emqx_connector_redis.erl`: pooled clients with
AUTH/SELECT on connect and a health check), speaking RESP2 (with RESP3
reply-type tolerance) over plain TCP sockets — no external client
library, so the "redis" kind of the driver seam (`emqx_tpu.drivers`) is
a real driver out of the box, not an injection point.

Contract (see drivers.py): sync `command(*args)`, `health_check()`,
`start()`/`stop()`.  HGETALL replies are returned as dicts (the shape
`DbAuthenticator`/`DbSource` consume); everything else is returned as
decoded Python values (str/int/list/None).
"""

from __future__ import annotations

import socket
import threading
from typing import Any, List, Optional

_CRLF = b"\r\n"


class RedisError(Exception):
    """Server-reported error reply (`-ERR ...`)."""


class RedisProtocolError(Exception):
    """Malformed RESP from the server."""


def encode_command(args) -> bytes:
    """RESP array-of-bulk-strings request framing."""
    parts = [b"*%d\r\n" % len(args)]
    for a in args:
        if isinstance(a, bytes):
            b = a
        elif isinstance(a, str):
            b = a.encode("utf-8")
        elif isinstance(a, (int, float)):
            b = str(a).encode()
        else:
            raise TypeError(f"unsupported redis arg type {type(a)!r}")
        parts.append(b"$%d\r\n" % len(b))
        parts.append(b)
        parts.append(_CRLF)
    return b"".join(parts)


def _decode(b: bytes) -> Any:
    try:
        return b.decode("utf-8")
    except UnicodeDecodeError:
        return b


class _Conn:
    """One blocking socket + incremental RESP reply reader."""

    def __init__(self, host: str, port: int, timeout: float):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.buf = b""

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def _read_more(self) -> None:
        chunk = self.sock.recv(65536)
        if not chunk:
            raise ConnectionError("redis connection closed by peer")
        self.buf += chunk

    def _read_line(self) -> bytes:
        while True:
            i = self.buf.find(_CRLF)
            if i >= 0:
                line, self.buf = self.buf[:i], self.buf[i + 2:]
                return line
            self._read_more()

    def _read_exact(self, n: int) -> bytes:
        while len(self.buf) < n + 2:
            self._read_more()
        data, self.buf = self.buf[:n], self.buf[n + 2:]  # strip CRLF
        return data

    def _read_value(self) -> Any:
        """One RESP value.  Error replies come back as RedisError VALUES
        (not raised): raising mid-array would abandon the rest of the
        reply in the buffer and desync the connection for its next
        user.  Top-level errors are raised by read_reply() after the
        parse is complete; nested errors (e.g. inside an EXEC reply)
        stay values, like mainstream clients."""
        line = self._read_line()
        if not line:
            raise RedisProtocolError("empty reply line")
        t, rest = line[:1], line[1:]
        if t == b"+":  # simple string
            return _decode(rest)
        if t == b"-":  # error
            return RedisError(rest.decode("utf-8", "replace"))
        if t == b":":  # integer
            return int(rest)
        if t == b"$":  # bulk string
            n = int(rest)
            if n < 0:
                return None
            return _decode(self._read_exact(n))
        if t == b"*" or t == b">":  # array / RESP3 push
            n = int(rest)
            if n < 0:
                return None
            return [self._read_value() for _ in range(n)]
        if t == b"%":  # RESP3 map
            n = int(rest)
            return {
                self._read_value(): self._read_value() for _ in range(n)
            }
        if t == b"_":  # RESP3 null
            return None
        if t == b"#":  # RESP3 boolean
            return rest == b"t"
        if t == b",":  # RESP3 double
            return float(rest)
        raise RedisProtocolError(f"unknown RESP type byte {t!r}")

    def read_reply(self) -> Any:
        v = self._read_value()
        if isinstance(v, RedisError):
            raise v
        return v

    def roundtrip(self, args) -> Any:
        self.sock.sendall(encode_command(args))
        return self.read_reply()


class RedisDriver:
    """Pooled Redis client satisfying the emqx_tpu driver contract.

    Pool semantics mirror ecpool's checkout/checkin: up to `pool_size`
    connections created on demand, reused round-robin; a connection
    that errors is dropped and the command retried once on a fresh one
    (the reference's eredis reconnect behavior)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 6379,
        password: Optional[str] = None,
        username: Optional[str] = None,
        database: int = 0,
        pool_size: int = 4,
        timeout: float = 5.0,
        **_ignored,
    ):
        self.host = host
        self.port = int(port)
        self.password = password
        self.username = username
        self.database = int(database)
        self.pool_size = int(pool_size)
        self.timeout = float(timeout)
        self._idle: List[_Conn] = []
        self._n_open = 0
        self._lock = threading.Condition()
        self._stopped = False

    # ------------------------------------------------------------- pool

    def _connect(self) -> _Conn:
        conn = _Conn(self.host, self.port, self.timeout)
        try:
            if self.password is not None:
                if self.username:
                    conn.roundtrip(("AUTH", self.username, self.password))
                else:
                    conn.roundtrip(("AUTH", self.password))
            if self.database:
                conn.roundtrip(("SELECT", self.database))
        except Exception:
            conn.close()
            raise
        return conn

    def _checkout(self) -> _Conn:
        import time as _time

        deadline = _time.monotonic() + self.timeout
        with self._lock:
            while True:
                if self._stopped:
                    raise RedisError("driver stopped")
                if self._idle:
                    return self._idle.pop()
                if self._n_open < self.pool_size:
                    self._n_open += 1
                    break
                left = deadline - _time.monotonic()
                if left <= 0:
                    raise TimeoutError("redis pool exhausted")
                self._lock.wait(left)
        try:
            return self._connect()
        except Exception:
            with self._lock:
                self._n_open -= 1
                self._lock.notify()
            raise

    def _checkin(self, conn: Optional[_Conn]) -> None:
        with self._lock:
            if conn is None or self._stopped:
                self._n_open -= 1
                if conn is not None:
                    conn.close()
            else:
                self._idle.append(conn)
            self._lock.notify()

    # --------------------------------------------------------- contract

    def start(self) -> None:
        """Open one connection eagerly so misconfiguration fails loudly
        at resource start, not first use."""
        self._checkin(self._checkout())

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            for c in self._idle:
                c.close()
            self._n_open -= len(self._idle)
            self._idle.clear()
            self._lock.notify_all()

    def _flush_idle(self) -> None:
        """Drop every idle connection: after one socket dies (typically a
        server restart) the rest of the pool is stale too — the retry
        must dial fresh, not pop the next dead socket."""
        with self._lock:
            for c in self._idle:
                c.close()
            self._n_open -= len(self._idle)
            self._idle.clear()
            self._lock.notify_all()

    def command(self, *args) -> Any:
        """Run one command; HGETALL replies come back as dicts."""
        last_err: Optional[Exception] = None
        for _attempt in range(2):  # retry once on a fresh connection
            conn = self._checkout()
            try:
                reply = conn.roundtrip(args)
            except RedisError:
                # top-level error reply: the parse completed, the
                # connection is in sync and safe to reuse
                self._checkin(conn)
                raise
            except Exception as e:  # socket died: drop pool + retry
                conn.close()
                self._checkin(None)
                self._flush_idle()
                last_err = e
                continue
            self._checkin(conn)
            if (
                isinstance(reply, list)
                and args
                and str(args[0]).upper() == "HGETALL"
            ):
                it = iter(reply)
                return dict(zip(it, it))
            return reply
        raise ConnectionError(f"redis command failed after retry: {last_err}")

    def health_check(self) -> bool:
        try:
            return self.command("PING") == "PONG"
        except Exception:
            return False
