"""Redis client — real RESP wire protocol, pooled, stdlib-only.

The analog of the reference's eredis-backed connector
(`apps/emqx_connector/src/emqx_connector_redis.erl`: pooled clients with
AUTH/SELECT on connect and a health check), speaking RESP2 (with RESP3
reply-type tolerance) over plain TCP sockets — no external client
library, so the "redis" kind of the driver seam (`emqx_tpu.drivers`) is
a real driver out of the box, not an injection point.

Contract (see drivers.py): sync `command(*args)`, `health_check()`,
`start()`/`stop()`.  HGETALL replies are returned as dicts (the shape
`DbAuthenticator`/`DbSource` consume); everything else is returned as
decoded Python values (str/int/list/None).
"""

from __future__ import annotations

import socket
from typing import Any, Optional

from .dbpool import PooledDriver

_CRLF = b"\r\n"


class RedisError(Exception):
    """Server-reported error reply (`-ERR ...`)."""


class RedisProtocolError(Exception):
    """Malformed RESP from the server."""


def encode_command(args) -> bytes:
    """RESP array-of-bulk-strings request framing."""
    parts = [b"*%d\r\n" % len(args)]
    for a in args:
        if isinstance(a, bytes):
            b = a
        elif isinstance(a, str):
            b = a.encode("utf-8")
        elif isinstance(a, (int, float)):
            b = str(a).encode()
        else:
            raise TypeError(f"unsupported redis arg type {type(a)!r}")
        parts.append(b"$%d\r\n" % len(b))
        parts.append(b)
        parts.append(_CRLF)
    return b"".join(parts)


def _decode(b: bytes) -> Any:
    try:
        return b.decode("utf-8")
    except UnicodeDecodeError:
        return b


class _Conn:
    """One blocking socket + incremental RESP reply reader."""

    def __init__(self, host: str, port: int, timeout: float):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.buf = b""

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def _read_more(self) -> None:
        chunk = self.sock.recv(65536)
        if not chunk:
            raise ConnectionError("redis connection closed by peer")
        self.buf += chunk

    def _read_line(self) -> bytes:
        while True:
            i = self.buf.find(_CRLF)
            if i >= 0:
                line, self.buf = self.buf[:i], self.buf[i + 2:]
                return line
            self._read_more()

    def _read_exact(self, n: int) -> bytes:
        while len(self.buf) < n + 2:
            self._read_more()
        data, self.buf = self.buf[:n], self.buf[n + 2:]  # strip CRLF
        return data

    def _read_value(self) -> Any:
        """One RESP value.  Error replies come back as RedisError VALUES
        (not raised): raising mid-array would abandon the rest of the
        reply in the buffer and desync the connection for its next
        user.  Top-level errors are raised by read_reply() after the
        parse is complete; nested errors (e.g. inside an EXEC reply)
        stay values, like mainstream clients."""
        line = self._read_line()
        if not line:
            raise RedisProtocolError("empty reply line")
        t, rest = line[:1], line[1:]
        if t == b"+":  # simple string
            return _decode(rest)
        if t == b"-":  # error
            return RedisError(rest.decode("utf-8", "replace"))
        if t == b":":  # integer
            return int(rest)
        if t == b"$":  # bulk string
            n = int(rest)
            if n < 0:
                return None
            return _decode(self._read_exact(n))
        if t == b"*" or t == b">":  # array / RESP3 push
            n = int(rest)
            if n < 0:
                return None
            return [self._read_value() for _ in range(n)]
        if t == b"%":  # RESP3 map
            n = int(rest)
            return {
                self._read_value(): self._read_value() for _ in range(n)
            }
        if t == b"_":  # RESP3 null
            return None
        if t == b"#":  # RESP3 boolean
            return rest == b"t"
        if t == b",":  # RESP3 double
            return float(rest)
        raise RedisProtocolError(f"unknown RESP type byte {t!r}")

    def read_reply(self) -> Any:
        v = self._read_value()
        if isinstance(v, RedisError):
            raise v
        return v

    def roundtrip(self, args) -> Any:
        self.sock.sendall(encode_command(args))
        return self.read_reply()


class RedisDriver(PooledDriver):
    """Pooled Redis client satisfying the emqx_tpu driver contract.

    Pool semantics come from PooledDriver (the ecpool analog): bounded
    checkout/checkin, retry-once-on-fresh-dial when a socket dies (the
    reference's eredis reconnect behavior)."""

    KIND = "redis"
    RECOVERABLE = (RedisError,)

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 6379,
        password: Optional[str] = None,
        username: Optional[str] = None,
        database: int = 0,
        pool_size: int = 4,
        timeout: float = 5.0,
        **_ignored,
    ):
        super().__init__(pool_size=pool_size, timeout=timeout)
        self.host = host
        self.port = int(port)
        self.password = password
        self.username = username
        self.database = int(database)

    def _dial(self) -> _Conn:
        conn = _Conn(self.host, self.port, self.timeout)
        try:
            if self.password is not None:
                if self.username:
                    conn.roundtrip(("AUTH", self.username, self.password))
                else:
                    conn.roundtrip(("AUTH", self.password))
            if self.database:
                conn.roundtrip(("SELECT", self.database))
        except Exception:
            conn.close()
            raise
        return conn

    # --------------------------------------------------------- contract

    # read-only commands are replayed on a fresh dial after a socket
    # death; writes (LPUSH, SET, ...) are not — they may have executed
    # server-side before the connection died
    _READ_COMMANDS = frozenset((
        "GET", "MGET", "HGET", "HGETALL", "HMGET", "EXISTS", "KEYS",
        "LRANGE", "SMEMBERS", "SISMEMBER", "ZRANGE", "ZSCORE", "TTL",
        "TYPE", "STRLEN", "LLEN", "SCARD", "ZCARD", "HLEN", "SCAN",
        "PING", "ECHO", "INFO", "TIME",
    ))

    def command(self, *args) -> Any:
        """Run one command; HGETALL replies come back as dicts."""
        retryable = bool(args) and str(args[0]).upper() in \
            self._READ_COMMANDS
        reply = self._run(lambda conn: conn.roundtrip(args),
                          retryable=retryable)
        if (
            isinstance(reply, list)
            and args
            and str(args[0]).upper() == "HGETALL"
        ):
            it = iter(reply)
            return dict(zip(it, it))
        return reply

    def health_check(self) -> bool:
        try:
            return self.command("PING") == "PONG"
        except Exception:
            return False
