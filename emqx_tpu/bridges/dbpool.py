"""Shared connection-pool base for the bundled DB drivers.

The ecpool analog (`/root/reference/apps/emqx_plugin_libs/src/
emqx_plugin_libs_pool.erl` + ecpool dep): every connector kind in the
reference checks a worker out of a bounded pool, runs one command, and
checks it back in; a dead worker is replaced by a fresh dial.  All the
bundled wire-protocol drivers (redis/pgsql/mysql/mongodb/ldap) share
that lifecycle, so it lives here once:

* up to ``pool_size`` connections, created on demand, reused LIFO;
* checkout blocks (bounded by ``timeout``) when the pool is exhausted;
* a connection that dies mid-command is dropped, the WHOLE idle pool is
  flushed (after a server restart every pooled socket is stale, not
  just the one that failed), and the command retried once on a fresh
  dial — the eredis/epgsql auto_reconnect behavior;
* a *server-reported* error (wrong password, SQL error, unknown
  command) leaves the connection in sync: it is checked back in and
  the error raised without retry.  Subclasses declare which exception
  types mean that via ``RECOVERABLE``.

Subclass contract: implement ``_dial() -> conn`` (open socket + auth;
raise loudly on failure) and give conns a ``close()``; set ``KIND`` and
``RECOVERABLE``; run commands through ``self._run(lambda conn: ...)``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional, Tuple


class PoolStopped(ConnectionError):
    pass


class PooledDriver:
    KIND = "db"
    RECOVERABLE: Tuple[type, ...] = ()

    def __init__(self, pool_size: int = 4, timeout: float = 5.0):
        self.pool_size = int(pool_size)
        self.timeout = float(timeout)
        self._idle: List[Any] = []
        self._n_open = 0
        self._lock = threading.Condition()
        self._stopped = False

    # ------------------------------------------------------------- dial

    def _dial(self) -> Any:  # pragma: no cover - abstract
        raise NotImplementedError

    @staticmethod
    def _close_conn(conn: Any) -> None:
        try:
            conn.close()
        except OSError:
            pass

    # ------------------------------------------------------------- pool

    def _checkout(self) -> Any:
        deadline = time.monotonic() + self.timeout
        with self._lock:
            while True:
                if self._stopped:
                    raise PoolStopped(f"{self.KIND} driver stopped")
                if self._idle:
                    return self._idle.pop()
                if self._n_open < self.pool_size:
                    self._n_open += 1
                    break
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(f"{self.KIND} pool exhausted")
                self._lock.wait(left)
        try:
            return self._dial()
        except Exception:
            with self._lock:
                self._n_open -= 1
                self._lock.notify()
            raise

    def _checkin(self, conn: Optional[Any]) -> None:
        with self._lock:
            if conn is None or self._stopped:
                self._n_open -= 1
                if conn is not None:
                    self._close_conn(conn)
            else:
                self._idle.append(conn)
            self._lock.notify()

    def _flush_idle(self) -> None:
        """Drop every idle connection: after one socket dies (typically
        a server restart) the rest of the pool is stale too — the retry
        must dial fresh, not pop the next dead socket."""
        with self._lock:
            for c in self._idle:
                self._close_conn(c)
            self._n_open -= len(self._idle)
            self._idle.clear()
            self._lock.notify_all()

    def _run(self, fn: Callable[[Any], Any], retryable: bool = True
             ) -> Any:
        """Checkout → fn(conn) → checkin, with the retry-once policy.

        ``retryable=False`` is for non-idempotent commands (INSERT,
        LPUSH, …): a socket that dies mid-command may have executed the
        write server-side, so re-running it could duplicate it — the
        stale pool is still flushed, but the error propagates instead
        of replaying (epgsql/eredis redial without replay either)."""
        last_err: Optional[Exception] = None
        for _attempt in range(2):
            conn = self._checkout()
            try:
                out = fn(conn)
            except self.RECOVERABLE:
                # server-reported error: the reply parse completed, the
                # connection is in sync and safe to reuse
                self._checkin(conn)
                raise
            except Exception as e:  # socket died: drop pool (+ retry)
                self._close_conn(conn)
                self._checkin(None)
                self._flush_idle()
                last_err = e
                if not retryable:
                    raise ConnectionError(
                        f"{self.KIND} command failed (not retried: "
                        f"non-idempotent): {last_err}"
                    ) from e
                continue
            self._checkin(conn)
            return out
        raise ConnectionError(
            f"{self.KIND} command failed after retry: {last_err}"
        )

    # --------------------------------------------------------- contract

    def start(self) -> None:
        """Open one connection eagerly so misconfiguration fails loudly
        at resource start, not first use.  Clears a previous stop() so
        the resource manager's stop→start restart cycle works."""
        with self._lock:
            self._stopped = False
        self._checkin(self._checkout())

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            for c in self._idle:
                self._close_conn(c)
            self._n_open -= len(self._idle)
            self._idle.clear()
            self._lock.notify_all()
