"""MongoDB client — real OP_MSG wire protocol + BSON, pooled, stdlib-only.

The analog of the reference's mongodb-erlang-backed connector
(`/root/reference/apps/emqx_connector/src/emqx_connector_mongo.erl`:
pooled clients running `find`/`find_one` selectors for authn/authz —
`emqx_authn_mongodb.erl:136-141`, `emqx_authz_mongodb.erl:55-61`),
speaking the modern wire protocol (OP_MSG, opcode 2013) over plain TCP
— no external client library, so the "mongodb" kind of the driver seam
is a real driver out of the box.

Implements:
* a minimal BSON codec (double/string/document/array/binary/objectid/
  bool/datetime/null/int32/int64) — the jiffy-for-BSON role;
* OP_MSG kind-0 command bodies: hello, ping, find (firstBatch +
  getMore for larger cursors), insert, saslStart/saslContinue;
* SCRAM-SHA-256 authentication (RFC 5802 via the shared ScramClient)
  against the configured authSource;
* the driver-seam `query(selector_template, params)` contract: ${var}
  placeholders render into a JSON selector which runs as a `find`
  against the configured collection, returning documents as dicts.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any, Dict, List, Optional, Tuple

from .dbpool import PooledDriver

OP_MSG = 2013


class MongoError(Exception):
    """Server {ok: 0} command reply; .code holds the server code."""

    def __init__(self, message: str, code: int = 0):
        self.code = code
        super().__init__(f"({code}) {message}")


class MongoProtocolError(Exception):
    """Malformed wire/BSON data."""


class Int64(int):
    """Marker for values that must encode as BSON int64 even when they
    fit in 31 bits (e.g. getMore cursor ids, which servers type-check
    as 'long')."""


def _subst_params(value: Any, params: Dict[str, str]) -> Any:
    """Replace ${var} placeholders inside a PARSED selector: a string
    value that is exactly one placeholder becomes the param verbatim;
    embedded placeholders concatenate as text.  Structure (keys,
    operators, nesting) always comes from the template alone."""
    import re

    if isinstance(value, str):
        m = re.fullmatch(r"\$\{(\w+)\}", value)
        if m:
            return params.get(m.group(1), "")
        return re.sub(r"\$\{(\w+)\}",
                      lambda m2: str(params.get(m2.group(1), "")),
                      value)
    if isinstance(value, dict):
        return {k: _subst_params(v, params) for k, v in value.items()}
    if isinstance(value, list):
        return [_subst_params(v, params) for v in value]
    return value


class ObjectId:
    """12-byte document id, held as bytes, shown as 24-hex."""

    __slots__ = ("value",)

    def __init__(self, value: bytes):
        if len(value) != 12:
            raise ValueError("ObjectId must be 12 bytes")
        self.value = value

    def __repr__(self) -> str:
        return f"ObjectId({self.value.hex()})"

    def __eq__(self, other) -> bool:
        return isinstance(other, ObjectId) and other.value == self.value

    def __hash__(self) -> int:
        return hash(self.value)


# --------------------------------------------------------------- BSON

def bson_encode(doc: Dict[str, Any]) -> bytes:
    body = b"".join(_encode_elem(k, v) for k, v in doc.items())
    return struct.pack("<i", len(body) + 5) + body + b"\x00"


def _encode_elem(key: str, v: Any) -> bytes:
    name = key.encode("utf-8") + b"\x00"
    if isinstance(v, bool):  # before int: bool is an int subclass
        return b"\x08" + name + (b"\x01" if v else b"\x00")
    if isinstance(v, float):
        return b"\x01" + name + struct.pack("<d", v)
    if isinstance(v, str):
        b = v.encode("utf-8") + b"\x00"
        return b"\x02" + name + struct.pack("<i", len(b)) + b
    if isinstance(v, dict):
        return b"\x03" + name + bson_encode(v)
    if isinstance(v, (list, tuple)):
        return b"\x04" + name + bson_encode(
            {str(i): x for i, x in enumerate(v)}
        )
    if isinstance(v, (bytes, bytearray)):
        return (b"\x05" + name + struct.pack("<i", len(v)) + b"\x00"
                + bytes(v))
    if isinstance(v, ObjectId):
        return b"\x07" + name + v.value
    if v is None:
        return b"\x0a" + name
    if isinstance(v, Int64):
        return b"\x12" + name + struct.pack("<q", v)
    if isinstance(v, int):
        if -(1 << 31) <= v < (1 << 31):
            return b"\x10" + name + struct.pack("<i", v)
        return b"\x12" + name + struct.pack("<q", v)
    raise TypeError(f"unsupported BSON value type {type(v)!r}")


def bson_decode(data: bytes) -> Dict[str, Any]:
    doc, off = _decode_doc(data, 0)
    return doc


def _decode_doc(data: bytes, off: int) -> Tuple[Dict[str, Any], int]:
    (length,) = struct.unpack_from("<i", data, off)
    end = off + length
    if data[end - 1] != 0:
        raise MongoProtocolError("document missing trailing NUL")
    off += 4
    doc: Dict[str, Any] = {}
    while off < end - 1:
        t = data[off]
        off += 1
        nul = data.index(b"\x00", off)
        key = data[off:nul].decode("utf-8")
        off = nul + 1
        doc[key], off = _decode_value(data, off, t)
    return doc, end


def _decode_value(data: bytes, off: int, t: int) -> Tuple[Any, int]:
    if t == 0x01:
        return struct.unpack_from("<d", data, off)[0], off + 8
    if t == 0x02:
        (n,) = struct.unpack_from("<i", data, off)
        s = data[off + 4:off + 4 + n - 1].decode("utf-8")
        return s, off + 4 + n
    if t == 0x03:
        return _decode_doc(data, off)
    if t == 0x04:
        sub, off = _decode_doc(data, off)
        return [sub[str(i)] for i in range(len(sub))], off
    if t == 0x05:
        (n,) = struct.unpack_from("<i", data, off)
        return data[off + 5:off + 5 + n], off + 5 + n
    if t == 0x07:
        return ObjectId(data[off:off + 12]), off + 12
    if t == 0x08:
        return data[off] == 1, off + 1
    if t == 0x09:  # UTC datetime: epoch millis
        return struct.unpack_from("<q", data, off)[0], off + 8
    if t == 0x0A:
        return None, off
    if t == 0x10:
        return struct.unpack_from("<i", data, off)[0], off + 4
    if t == 0x11 or t == 0x12:  # timestamp / int64
        return struct.unpack_from("<q", data, off)[0], off + 8
    raise MongoProtocolError(f"unsupported BSON type {t:#x}")


# ------------------------------------------------------------- OP_MSG

class _Conn:
    """One blocking socket speaking OP_MSG request/reply."""

    def __init__(self, host: str, port: int, timeout: float):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.buf = b""
        self.request_id = 0

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def _read_more(self) -> None:
        chunk = self.sock.recv(65536)
        if not chunk:
            raise ConnectionError("mongodb connection closed by peer")
        self.buf += chunk

    def run_command(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        """One OP_MSG roundtrip; raises MongoError on {ok: 0}."""
        self.request_id += 1
        body = struct.pack("<I", 0) + b"\x00" + bson_encode(doc)
        header = struct.pack("<iiii", 16 + len(body), self.request_id,
                             0, OP_MSG)
        self.sock.sendall(header + body)
        while len(self.buf) < 4:
            self._read_more()
        (length,) = struct.unpack_from("<i", self.buf, 0)
        while len(self.buf) < length:
            self._read_more()
        msg, self.buf = self.buf[:length], self.buf[length:]
        _len, _rid, _rto, opcode = struct.unpack_from("<iiii", msg, 0)
        if opcode != OP_MSG:
            raise MongoProtocolError(f"unexpected opcode {opcode}")
        # flags (4) + section kind byte (1) then the body document
        if msg[20] != 0:
            raise MongoProtocolError(
                f"unsupported reply section kind {msg[20]}"
            )
        reply = bson_decode(msg[21:])
        if not reply.get("ok"):
            raise MongoError(reply.get("errmsg", "command failed"),
                             int(reply.get("code", 0)))
        return reply


class MongoDriver(PooledDriver):
    """Pooled MongoDB client satisfying the emqx_tpu driver contract."""

    KIND = "mongodb"
    RECOVERABLE = (MongoError,)

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 27017,
        username: Optional[str] = None,
        password: Optional[str] = None,
        database: str = "mqtt",
        collection: str = "mqtt_user",
        auth_source: str = "admin",
        pool_size: int = 4,
        timeout: float = 5.0,
        **_ignored,
    ):
        super().__init__(pool_size=pool_size, timeout=timeout)
        self.host = host
        self.port = int(port)
        self.username = username
        self.password = password
        self.database = database
        self.collection = collection
        self.auth_source = auth_source

    def _dial(self) -> _Conn:
        conn = _Conn(self.host, self.port, self.timeout)
        try:
            conn.run_command({"hello": 1, "$db": "admin"})
            if self.username is not None:
                self._sasl_auth(conn)
        except Exception:
            conn.close()
            raise
        return conn

    def _sasl_auth(self, conn: _Conn) -> None:
        """SCRAM-SHA-256 against the authSource database."""
        from ..scram import ScramClient

        client = ScramClient(self.username, self.password or "")
        reply = conn.run_command({
            "saslStart": 1,
            "mechanism": "SCRAM-SHA-256",
            "payload": client.client_first(),
            "$db": self.auth_source,
        })
        cid = reply.get("conversationId", 1)
        final = client.client_final(bytes(reply["payload"]))
        reply = conn.run_command({
            "saslContinue": 1,
            "conversationId": cid,
            "payload": final,
            "$db": self.auth_source,
        })
        if not client.verify_server_final(bytes(reply["payload"])):
            raise MongoProtocolError(
                "server SCRAM signature verification failed"
            )
        while not reply.get("done"):
            reply = conn.run_command({
                "saslContinue": 1,
                "conversationId": cid,
                "payload": b"",
                "$db": self.auth_source,
            })

    # --------------------------------------------------------- queries

    def find(self, selector: Dict[str, Any],
             collection: Optional[str] = None,
             limit: int = 0) -> List[Dict[str, Any]]:
        """find → full result list (firstBatch + getMore drain)."""

        def run(conn: _Conn) -> List[Dict[str, Any]]:
            coll = collection or self.collection
            reply = conn.run_command({
                "find": coll,
                "filter": selector,
                "limit": limit,
                "$db": self.database,
            })
            cursor = reply["cursor"]
            docs = list(cursor.get("firstBatch", []))
            cid = cursor.get("id", 0)
            while cid:
                reply = conn.run_command({
                    # servers type-check getMore as int64 ('long')
                    "getMore": Int64(cid),
                    "collection": coll,
                    "$db": self.database,
                })
                cursor = reply["cursor"]
                docs.extend(cursor.get("nextBatch", []))
                cid = cursor.get("id", 0)
            return docs

        return self._run(run)

    def insert(self, documents: List[Dict[str, Any]],
               collection: Optional[str] = None) -> int:
        """insert → inserted count; never retried (non-idempotent)."""

        def run(conn: _Conn) -> int:
            reply = conn.run_command({
                "insert": collection or self.collection,
                "documents": documents,
                "$db": self.database,
            })
            return int(reply.get("n", 0))

        return self._run(run, retryable=False)

    # --------------------------------------------------------- contract

    def query(self, template: str, params: Dict[str, str]
              ) -> List[Dict[str, Any]]:
        """Run a ${var} JSON selector template as a find on the
        configured collection (`emqx_authn_mongodb` selector).

        The template (operator-controlled) is parsed FIRST; ${var}
        values (client-controlled) are substituted into the parsed
        structure as plain strings — they can never add selector
        operators or keys, and quotes/backslashes in values can't
        break the JSON (the reference pre-parses selectors the same
        way, `emqx_authn_mongodb.erl:170-177`)."""
        try:
            selector = (json.loads(template) if template.strip()
                        else {})
        except json.JSONDecodeError as e:
            raise MongoProtocolError(
                f"selector template is not valid JSON: {e}"
            ) from e
        return self.find(_subst_params(selector, params))

    def command(self, *args) -> Any:
        """("find", selector[, collection]) / ("insert", docs[, coll])
        / ("ping",) / a raw command document."""
        if args and isinstance(args[0], dict):
            return self._run(lambda conn: conn.run_command(args[0]))
        op = str(args[0]).lower() if args else ""
        if op == "find":
            return self.find(args[1], *args[2:])
        if op == "insert":
            return self.insert(args[1], *args[2:])
        if op == "ping":
            self._run(lambda conn: conn.run_command(
                {"ping": 1, "$db": "admin"}
            ))
            return True
        raise ValueError(f"unsupported mongodb command {args!r}")

    def health_check(self) -> bool:
        try:
            return self.command("ping") is True
        except Exception:
            return False
