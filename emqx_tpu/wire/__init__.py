"""Process-sharded wire plane — the esockd acceptor pool lifted to
whole OS processes.

Every in-loop plane so far (churn pool, delivery shards, prep-ahead)
still time-slices ONE Python event loop and one GIL; the reference
scales the wire side with esockd acceptor pools at schedulers x 8 per
listener (PAPER.md §1.3).  Here the pool members are full broker
processes: a parent supervisor (`supervisor.WireSupervisor`, running
inside the parent NodeRuntime) spawns `wire.workers` child processes
(`python -m emqx_tpu.wire.worker`) that each

* bind the SAME configured MQTT listeners via SO_REUSEPORT (the kernel
  load-balances accepts across processes), falling back to a single
  parent-bound listening socket inherited by FD where SO_REUSEPORT is
  unavailable;
* run the complete connection/channel/session/delivery stack of a
  normal node (a worker IS a NodeRuntime);
* cluster with the parent and each other over UNIX-domain PeerLinks
  (`cluster/transport.py` unix addressing — no TCP loopback tax), so
  the local node is just a zero-latency peer: subscriptions replicate
  through the route oplog, publishes cross processes through the
  exactly-once FORWARD/spool/dedup path, and cross-process semantics
  come for free from the existing cluster machinery.

Only transport frames cross the process boundary — the supervisor never
shares objects with a worker (enforced by the `proc-boundary` pass in
tools/analysis).  A crashed worker's clients reconnect (the kernel
rehashes them to surviving workers), its sessions park on disk and
resume after the supervisor respawns it, and QoS>=1 traffic for it
spools at the peers until the IPC link heals.
"""

from .supervisor import WireSupervisor

__all__ = ["WireSupervisor"]
