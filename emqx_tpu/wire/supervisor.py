"""Wire-worker supervisor: spawn, monitor, restart, and meter the
process pool serving the MQTT listeners.

Runs inside the parent NodeRuntime.  The parent never shares Python
state with a worker — a worker is an opaque OS process plus a cluster
PeerLink over a UNIX socket; everything the supervisor knows about a
worker it learned from `wire_stats` RPCs or the process table.  The
`proc-boundary` analysis pass enforces that discipline statically
(importing `emqx_tpu.wire.worker` anywhere in the parent is an error;
only the spawn command line below names it).

Crash handling (the esockd supervisor analog, one_for_one): a dead
worker is respawned with doubling backoff into the SAME identity —
index, node name, unix socket, data dir, listener sockets — so its
parked sessions restore from the per-worker persistence/ds planes, the
peers' forward spools drain into it after the link heals, and the
receiver-side (mid, group, filt) dedup turns the at-least-once replay
into exactly-once delivery.  While a worker is down the kernel simply
stops handing it accepts (SO_REUSEPORT) or the surviving workers win
the accept race (inherited-FD fallback), so new connections keep
landing.
"""

from __future__ import annotations

import asyncio
import copy
import json
import logging
import os
import socket
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..observe.flight import LatencyHistogram
from ..observe.tracepoints import tp

log = logging.getLogger("emqx_tpu.wire")

# listener types the shared (reuseport / inherited-FD) plane can carry;
# others would need per-worker ports and are refused at boot
SHARDABLE_LISTENERS = ("tcp", "ssl", "ws", "wss")

# parent-side knobs for the hub<->worker links: a worker boots in
# seconds, so the default 15 s reconnect ceiling would leave the hub's
# outbound link (the forward path INTO the worker) dark long after the
# worker is serving
HUB_RECONNECT_IVL = 0.25
HUB_RECONNECT_MAX = 2.0


def free_port(host: str = "127.0.0.1") -> int:
    """An OS-granted free TCP port.  SO_REUSEPORT workers must agree on
    ONE port number up front, so `port: 0` listener defs are resolved
    here once instead of per worker."""
    s = socket.socket()
    try:
        s.bind((host, 0))
        return s.getsockname()[1]
    finally:
        s.close()


@dataclass
class WorkerHandle:
    """Parent-side record of one wire worker — identity + process
    handle + last polled counters.  Never holds worker Python state."""

    idx: int
    name: str
    sock_path: str
    data_dir: str
    config_path: str
    direct_port: int  # per-worker private listener (tests/bench target
    # one specific worker; reuseport hashing is opaque)
    proc: Optional[subprocess.Popen] = None
    fails: int = 0  # consecutive crashes (backoff doubles on each)
    restart_at: float = 0.0
    healthy_since: float = 0.0  # first up+linked observation this run
    shm_region: str = ""  # this worker's shm slab (empty = plane off)
    last_stats: Dict[str, Any] = field(default_factory=dict)
    last_accepts: float = 0.0
    last_poll: float = 0.0
    # fleet observability: the worker's mergeable histograms (latest
    # scrape, deserialized) + its slowest-span sample — the inputs the
    # supervisor merges into the fleet view (fleet_histograms below)
    last_hists: Dict[str, LatencyHistogram] = field(default_factory=dict)
    last_spans: List[Dict[str, Any]] = field(default_factory=list)


class WireSupervisor:
    def __init__(self, runtime):
        self.runtime = runtime
        conf = runtime.conf
        self.node_name = runtime.node_name
        # runtime resolved "auto" (cpu_count minus the hub core, clamped
        # by wire.max_workers) at boot
        self.n = int(runtime._wire_workers)
        self.reuseport = bool(conf.get("wire.reuseport"))
        self.ipc_dir = conf.get("wire.ipc_dir") or os.path.join(
            conf.get("node.data_dir"), "wire"
        )
        self.restart_backoff = float(conf.get("wire.restart_backoff"))
        self.backoff_reset = float(conf.get("wire.backoff_reset"))
        self.stats_interval = float(conf.get("wire.stats_interval"))
        # shared-memory match plane (emqx_tpu/shm/): hub-owned slabs +
        # the drain service feeding the hub's single engine
        self.shm_enable = bool(conf.get("shm.enable"))
        self.shm_slots = int(conf.get("shm.slots"))
        self.shm_slot_bytes = int(conf.get("shm.slot_bytes"))
        # MatchService once _prepare ran; written at prepare/stop on
        # the loop, read-only elsewhere — never from worker threads
        self.service = None  # analysis: owner=loop
        self.hub_sock = os.path.join(self.ipc_dir, "hub.sock")
        self.workers: Dict[int, WorkerHandle] = {}
        self.listener_defs: List[Dict[str, Any]] = []  # resolved, shared
        self._shared_socks: List[socket.socket] = []
        self._mon_task: Optional[asyncio.Task] = None
        self._stats_task: Optional[asyncio.Task] = None
        self._hk_task: Optional[asyncio.Task] = None
        self._stopping = False
        # idle-wakeup rate sampling state (shm.hub.idle_wakeup_rate)
        self._last_idle = 0
        self._last_idle_t = 0.0

    # ------------------------------------------------------------ config

    def _prepare(self) -> None:
        """Blocking boot half (worker thread): resolve the shared
        listener set, bind fallback sockets, pick per-worker direct
        ports, build the handles."""
        os.makedirs(self.ipc_dir, exist_ok=True)
        self._resolve_listeners()
        if self.shm_enable:
            from ..shm import ShmRegistry
            from ..shm.service import MatchService

            conf = self.runtime.conf
            self.service = MatchService(
                self.runtime.broker.engine,
                ShmRegistry(self.ipc_dir),
                slots=self.shm_slots,
                slot_bytes=self.shm_slot_bytes,
                poll_interval=float(conf.get("shm.poll_interval")),
                drain=str(conf.get("shm.drain")),
                fuse_window_us=int(conf.get("shm.fuse_window_us")),
                lane_credit=int(conf.get("shm.lane_credit")),
                pin_cores=str(conf.get("shm.pin_cores")),
            )
            sem = getattr(self.runtime, "semantic", None)
            if sem is not None and sem.engine is not None:
                # the pool's ONE embedding table: workers register
                # queries and ship payload ticks through their lanes;
                # no worker process ever holds [max_queries, dim] state
                self.service.semantic = sem.engine
        for i in range(self.n):
            self.workers[i] = WorkerHandle(
                idx=i,
                name=f"{self.node_name}#w{i}",
                sock_path=os.path.join(self.ipc_dir, f"w{i}.sock"),
                data_dir=os.path.join(self.ipc_dir, f"w{i}"),
                config_path=os.path.join(self.ipc_dir, f"w{i}.json"),
                direct_port=free_port(),
            )
            if self.service is not None:
                self.workers[i].shm_region = self.service.create_lane(i)

    def _resolve_listeners(self) -> None:
        """One resolved listener set ALL workers bind: `port: 0` defs
        get a concrete port here (each worker must land on the same
        number), and in FD-fallback mode the parent binds each socket
        once and records the inheritable fd."""
        raw = self.runtime.raw.get("listeners") or [
            {"type": "tcp", "port": 1883}
        ]
        for ldef in raw:
            ldef = copy.deepcopy(ldef)
            kind = ldef.get("type", "tcp")
            if kind not in SHARDABLE_LISTENERS:
                raise ValueError(
                    f"wire plane cannot shard listener type {kind!r}"
                )
            if int(ldef.get("port", 1883)) == 0:
                ldef["port"] = free_port(ldef.get("host", "0.0.0.0"))
            if self.reuseport:
                ldef["reuseport"] = True
            else:
                ldef["sock_fd"] = self._bind_shared(
                    ldef.get("host", "0.0.0.0"), int(ldef["port"])
                )
            self.listener_defs.append(ldef)

    def _bind_shared(self, host: str, port: int) -> int:
        """Reuseport fallback: bind + listen ONCE in the parent; every
        worker inherits the fd and accepts on the shared socket (the
        classic pre-fork server shape)."""
        s = socket.create_server(
            (host, port), backlog=1024, reuse_port=False
        )
        s.set_inheritable(True)
        self._shared_socks.append(s)
        return s.fileno()

    def worker_raw(self, h: WorkerHandle) -> Dict[str, Any]:
        """Derive one worker's node config from the parent's raw dict.

        A worker is a full NodeRuntime serving the shared listeners plus
        a private direct listener, clustered over unix sockets to the
        hub and its siblings.  Node-singleton planes stay with the
        parent (REST dashboard port, gateways, bridges, rules, exhook,
        Prometheus/StatsD push); per-connection planes (authn/authz,
        rewrite, auto-subscribe, delayed, retainer, limiter) ride along
        unchanged.  Sessions park on the worker's OWN disc store so a
        kill -9 recovers through restore() on respawn."""
        conf = self.runtime.conf
        base = copy.deepcopy(self.runtime.raw)
        for parent_only in ("gateways", "bridges", "exhook", "rules"):
            base.pop(parent_only, None)
        base.setdefault("node", {})
        base["node"]["name"] = h.name
        base["node"]["data_dir"] = h.data_dir
        # ONE shared XLA compile cache: the first worker pays each
        # kernel once, the rest (and every respawn) warm-start
        base["node"]["xla_cache_dir"] = conf.get(
            "node.xla_cache_dir"
        ) or os.path.join(conf.get("node.data_dir"), "xla_cache")
        base["wire"] = {
            "workers": 0,  # a worker never forks grandchildren
            "max_conn_rate": conf.get("wire.max_conn_rate"),
        }
        base["dashboard"] = dict(
            base.get("dashboard") or {}, listen_port=0
        )
        base["prometheus"] = {"enable": False}
        base["statsd"] = {"enable": False}
        # park-on-death: sessions must survive a kill -9'd worker
        base["persistent_session_store"] = {
            "enable": True, "on_disc": True,
        }
        peers: Dict[str, List[Any]] = {
            self.runtime.node_name: ["unix", self.hub_sock]
        }
        for other in self.workers.values():
            if other.idx != h.idx:
                peers[other.name] = ["unix", other.sock_path]
        base["cluster"] = {
            "enable": True,
            "host": "127.0.0.1",
            "port": 0,
            "unix_path": h.sock_path,
            "peers": peers,
            "reconnect_ivl": HUB_RECONNECT_IVL,
            "reconnect_max": HUB_RECONNECT_MAX,
        }
        base["listeners"] = copy.deepcopy(self.listener_defs) + [
            {"type": "tcp", "host": "127.0.0.1", "port": h.direct_port}
        ]
        if h.shm_region:
            # shared-match topology: the worker attaches the hub-owned
            # slab instead of booting its own device engine, and has no
            # table state to checkpoint (the hub is registry-of-record)
            base["broker"] = dict(base.get("broker") or {},
                                  engine="shm")
            base["shm"] = {
                "enable": True,
                "region": h.shm_region,
                "slots": self.shm_slots,
                "slot_bytes": self.shm_slot_bytes,
                "timeout": conf.get("shm.timeout"),
            }
            if self.service is not None:
                if str(conf.get("shm.drain")) != "poll":
                    # the doorbell eventfd crosses exec via pass_fds
                    # (fd number preserved), so the child can open the
                    # same integer it reads from its derived config
                    base["shm"]["doorbell_fd"] = \
                        self.service.doorbell_fd(h.idx)
                core = self.service.lane_core(h.idx)
                if core is not None:
                    base["shm"]["pin_core"] = core
            base["engine"] = dict(base.get("engine") or {})
            base["engine"]["ckpt.enable"] = False
        return base

    # --------------------------------------------------------- lifecycle

    async def start(self) -> None:
        await asyncio.to_thread(self._prepare)
        # configs are written after every handle exists (peer maps name
        # all siblings), then the processes launch
        for h in self.workers.values():
            await asyncio.to_thread(self._spawn, h, self.worker_raw(h))
            tp("wire.worker.spawn", worker=h.name, respawn=False)
            self.runtime.cluster.join(h.name, ("unix", h.sock_path))
        loop = asyncio.get_running_loop()
        if self.service is not None:
            self.service.start()
        self._mon_task = loop.create_task(self._monitor())
        self._stats_task = loop.create_task(self._stats_loop())
        self._hk_task = loop.create_task(self._housekeeping())
        log.info(
            "wire plane up: %d workers on %s (%s)",
            self.n,
            ", ".join(
                f"{d.get('type', 'tcp')}:{d['port']}"
                for d in self.listener_defs
            ),
            "reuseport" if self.reuseport else "inherited fd",
        )

    def _spawn(self, h: WorkerHandle, raw: Dict[str, Any]) -> None:
        """Blocking spawn half (runs on a worker thread): write the
        derived config (built on the loop, where the parent Config is
        mutated), launch the child with the shared listening fds
        inherited, logs appended to w<i>.log."""
        os.makedirs(h.data_dir, exist_ok=True)
        with open(h.config_path, "w", encoding="utf-8") as f:
            # analysis: allow-blocking(one small config file per spawn,
            # and _spawn always runs on a to_thread worker)
            f.write(json.dumps(raw, indent=2, sort_keys=True))
        env = dict(os.environ)
        if "EMQX_TPU_JAX_PLATFORM" not in env:
            # pin children to the parent's RESOLVED backend: site hooks
            # can pre-pin a child interpreter before env JAX_PLATFORMS
            # applies, but EMQX_TPU_JAX_PLATFORM is applied in-process
            # by the worker entry (worker.py), so this is deterministic
            import jax

            env["EMQX_TPU_JAX_PLATFORM"] = jax.default_backend()
        pass_fds = tuple(s.fileno() for s in self._shared_socks)
        if self.service is not None and h.shm_region \
                and str(self.runtime.conf.get("shm.drain")) != "poll":
            # the lane's doorbell rides into the child alongside the
            # shared listener fds; same fd on every respawn
            pass_fds += (self.service.doorbell_fd(h.idx),)
        logf = open(
            os.path.join(self.ipc_dir, f"w{h.idx}.log"), "ab"
        )
        try:
            h.proc = subprocess.Popen(
                [sys.executable, "-m", "emqx_tpu.wire.worker",
                 "--config", h.config_path],
                stdout=logf,
                stderr=subprocess.STDOUT,
                env=env,
                pass_fds=pass_fds,
                start_new_session=True,
            )
        finally:
            logf.close()  # the child holds its own dup

    async def stop(self) -> None:
        self._stopping = True
        if self.service is not None:
            try:
                await self.service.stop()
            except Exception:
                log.exception("stopping shm match service")
        for t in (self._mon_task, self._stats_task, self._hk_task):
            if t is not None:
                t.cancel()
                try:
                    await t
                except (asyncio.CancelledError, Exception):
                    pass
        self._mon_task = self._stats_task = self._hk_task = None
        for h in self.workers.values():
            if h.proc is not None and h.proc.poll() is None:
                try:
                    h.proc.terminate()
                except OSError:
                    pass
        await asyncio.to_thread(self._reap_all)
        if self.service is not None:
            # segments unlink only after every worker is reaped (an
            # attached child pins the mapping; unlink-then-close is
            # still safe, but reap-first keeps the teardown ordered)
            self.service.close()
            self.service = None
        for s in self._shared_socks:
            s.close()
        self._shared_socks.clear()

    def _reap_all(self) -> None:
        deadline = time.monotonic() + 10.0
        for h in self.workers.values():
            p = h.proc
            if p is None:
                continue
            try:
                p.wait(timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                try:
                    p.kill()
                except OSError:
                    pass
                p.wait()
            h.proc = None

    # --------------------------------------------------------- monitors

    async def _monitor(self) -> None:
        """Process-table watch: reap dead workers, respawn with
        doubling backoff into the same identity.  The cluster layer
        handles everything else about a death (link down -> routes held
        for route_hold -> QoS>=1 spools -> replay + dedup on heal)."""
        while True:
            await asyncio.sleep(0.25)
            now = time.monotonic()
            for h in self.workers.values():
                p = h.proc
                if p is not None and p.poll() is not None:
                    rc = p.returncode
                    h.proc = None
                    # a worker that stayed healthy past backoff_reset
                    # ended its crash streak: the NEXT respawn pays the
                    # base delay again, not the doubled tail a flaky
                    # boot earned hours ago
                    if h.healthy_since and (
                        now - h.healthy_since >= self.backoff_reset
                    ):
                        h.fails = 0
                    h.healthy_since = 0.0
                    h.fails += 1
                    self.runtime.broker.metrics.inc("wire.worker.exits")
                    self._drop_worker_gauges(h.idx)
                    tp("wire.worker.exit", worker=h.name, rc=rc,
                       fails=h.fails)
                    log.warning(
                        "wire worker %s exited rc=%s (crash #%d)",
                        h.name, rc, h.fails,
                    )
                    h.restart_at = now + min(
                        self.restart_backoff * (2 ** (h.fails - 1)),
                        self.restart_backoff * 8,
                    )
                elif p is None and not self._stopping \
                        and now >= h.restart_at:
                    try:
                        await asyncio.to_thread(
                            self._spawn, h, self.worker_raw(h)
                        )
                    except OSError:
                        log.exception("respawning wire worker %s", h.name)
                        h.restart_at = now + self.restart_backoff * 8
                        continue
                    tp("wire.worker.spawn", worker=h.name, respawn=True)

    async def _stats_loop(self) -> None:
        """Per-worker gauges over the IPC link (`wire_stats` RPC): one
        scrape per interval lands conns / accept rate / shed counts /
        forward depth in the parent's metrics table, so $SYS metrics,
        /monitor and the Prometheus exposition all see the pool without
        any new export path."""
        cluster = self.runtime.cluster
        m = self.runtime.broker.metrics
        while True:
            await asyncio.sleep(self.stats_interval)
            alive = 0
            total_conns = 0.0
            status = cluster.status()
            for h in self.workers.values():
                up = status.get(h.name) == "up"
                running = h.proc is not None and h.proc.poll() is None
                if running and up:
                    alive += 1
                    # crash-streak reset is TIME-based (wire.backoff_
                    # reset, judged at the next death in _monitor), not
                    # instant: a worker that crash-loops slower than
                    # one stats interval must keep escalating
                    if not h.healthy_since:
                        h.healthy_since = time.monotonic()
                stats = None
                if up:
                    try:
                        stats = await cluster.call(
                            h.name, "wire_stats", {}, timeout=2.0
                        )
                    except Exception:
                        stats = None
                g = f"wire.worker.{h.idx}."
                now = time.monotonic()
                if stats:
                    h.last_stats = stats
                    # mergeable per-process histograms (wire_stats
                    # "hists" wire form): deserialize once per scrape;
                    # the fleet view merges the LATEST snapshot per
                    # worker (each is cumulative since worker boot, so
                    # re-merging every scrape would double-count)
                    try:
                        h.last_hists = {
                            name: LatencyHistogram.from_dict(d)
                            for name, d in
                            (stats.get("hists") or {}).items()
                        }
                    except (TypeError, ValueError):
                        h.last_hists = {}
                    h.last_spans = list(
                        stats.get("spans_slowest") or []
                    )
                    lh = h.last_hists.get("loop_lag")
                    if lh is not None and lh.count:
                        m.gauge_set(g + "loop_lag_p99_ms",
                                    lh.quantile(0.99) * 1e3)
                    th = h.last_hists.get("engine_tick_latency")
                    if th is not None and th.count:
                        m.gauge_set(g + "tick_p99_ms",
                                    th.quantile(0.99) * 1e3)
                    conns = float(stats.get("connections", 0))
                    total_conns += conns
                    m.gauge_set(g + "connections", conns)
                    accepts = float(stats.get("accepts", 0))
                    dt = max(now - h.last_poll, 1e-6) \
                        if h.last_poll else None
                    if dt is not None:
                        m.gauge_set(
                            g + "accept_rate",
                            max(accepts - h.last_accepts, 0.0) / dt,
                        )
                    h.last_accepts = accepts
                    h.last_poll = now
                    m.gauge_set(g + "shed", float(stats.get("shed", 0)))
                    m.gauge_set(
                        g + "rate_limited",
                        float(stats.get("rate_limited", 0)),
                    )
                    # IPC forward depth: parent->worker spool + the
                    # worker's own outbound spool backlog
                    m.gauge_set(
                        g + "forward_depth",
                        float(cluster.spool_pending(h.name))
                        + float(stats.get("spool_pending", 0)),
                    )
                else:
                    m.gauge_set(g + "connections", 0.0)
                    m.gauge_set(
                        g + "forward_depth",
                        float(cluster.spool_pending(h.name)),
                    )
            m.gauge_set("wire.workers.alive", float(alive))
            m.gauge_set("wire.connections", total_conns)
            if self.service is not None:
                # hub-side shm service counters: absolute copies, same
                # observation-point discipline as sync_engine_metrics
                st = self.service.stats()
                c = m.counters
                c["shm.hub.ticks"] = st["ticks"]
                c["shm.hub.groups"] = st["groups"]
                c["shm.hub.churn_records"] = st["churn_records"]
                c["shm.hub.reclaims"] = st["reclaims"]
                c["shm.hub.res_drops"] = st["res_drops"]
                c["shm.hub.ack_shed"] = st["ack_sheds"]
                c["shm.hub.credit_exhausted"] = st["credit_exhausted"]
                c["shm.hub.doorbell_wakeups"] = st["doorbell_wakeups"]
                c["shm.hub.sem_ticks"] = st["sem_ticks"]
                c["shm.hub.sem_texts"] = st["sem_texts"]
                c["shm.hub.sem_res_drops"] = st["sem_res_drops"]
                c["shm.hub.sem_churn"] = st["sem_churn"]
                m.gauge_set("shm.hub.sem_queries",
                            float(st["sem_queries"]))
                m.gauge_set("shm.lanes", float(st["lanes"]))
                m.gauge_set("shm.hub.fused_share",
                            float(st["fused_share"]))
                # idle-wakeup rate: loop turns that found nothing, per
                # second since the last scrape — ~1/poll_interval under
                # the legacy poll loop, ~1/s parked on doorbells
                now_m = time.monotonic()
                if self._last_idle_t:
                    dt = max(now_m - self._last_idle_t, 1e-9)
                    m.gauge_set(
                        "shm.hub.idle_wakeup_rate",
                        max(st["idle_passes"] - self._last_idle, 0) / dt,
                    )
                self._last_idle = int(st["idle_passes"])
                self._last_idle_t = now_m
                # drain/fusion telemetry: cycle-gap p99 + mean fused
                # group size (what the adaptive-fusion controller and
                # the soak gates watch), plus per-lane ring health
                hd = self.service.hist_drain
                if hd.count:
                    m.gauge_set("shm.hub.drain_cycle_p99_ms",
                                hd.quantile(0.99) * 1e3)
                gs = st.get("group_sizes") or {}
                groups = sum(gs.values())
                if groups:
                    m.gauge_set(
                        "shm.hub.group_size_mean",
                        sum(k * v for k, v in gs.items()) / groups,
                    )
                for idx, ls in self.service.lane_stats().items():
                    for key, val in ls.items():
                        m.gauge_set(f"shm.lane.{idx}.{key}",
                                    float(val))

    def _drop_worker_gauges(self, idx: int) -> None:
        """Zero-and-drop a dead worker's per-index gauges: after a
        respawn gap (or a downsized pool) the index must stop reporting
        its last scraped values through $SYS//monitor/Prometheus."""
        m = self.runtime.broker.metrics
        g = f"wire.worker.{idx}."
        for k in ("connections", "accept_rate", "shed", "rate_limited",
                  "forward_depth", "loop_lag_p99_ms", "tick_p99_ms"):
            m.gauges.pop(g + k, None)
        h = self.workers.get(idx)
        if h is not None:
            # a dead worker's histograms must leave the fleet merge
            # too, or the merged view keeps reporting its last scrape
            h.last_hists = {}
            h.last_spans = []

    async def _housekeeping(self) -> None:
        """The slice of listener housekeeping the parent still needs
        with no listener of its own running: pending-session eviction,
        persistence flush, retained GC.  (Channel timers live in the
        workers' own listener loops.)"""
        n = 0
        while True:
            await asyncio.sleep(1.0)
            n += 1
            try:
                self.runtime.broker.cm.evict_expired()
                p = self.runtime.persistence
                if p is not None:
                    p.tick()
                if n % 60 == 0:
                    self.runtime.broker.retainer.clean_expired()
            except Exception:
                log.exception("wire supervisor housekeeping")

    # -------------------------------------------------- fleet observability

    def fleet_histograms(self) -> Dict[str, LatencyHistogram]:
        """Fleet-merged histograms: each worker's latest cumulative
        snapshot added bucket-by-bucket, keyed `fleet_<name>` so the
        hub's own `span_stage_*`/`loop_lag` series stay distinct in the
        same Prometheus exposition (per-worker p99s ride the
        `wire.worker.<i>.*` gauges; this is the merged view)."""
        merged: Dict[str, LatencyHistogram] = {}
        for h in self.workers.values():
            for name, hist in h.last_hists.items():
                cur = merged.get(name)
                if cur is None:
                    merged[name] = hist.snapshot()
                else:
                    try:
                        cur.merge(hist)
                    except ValueError:  # pragma: no cover - layout drift
                        pass
        return {f"fleet_{name}": hh for name, hh in merged.items()}

    def fleet_export(self) -> Dict[str, Any]:
        """JSON-safe fleet dump (tools/fleet_dump.py input): per-worker
        stats + histograms + slowest spans, the merged fleet
        histograms, and the hub's drain/fusion + per-lane ring health."""
        workers: Dict[str, Any] = {}
        for h in self.workers.values():
            workers[str(h.idx)] = {
                "name": h.name,
                "stats": {
                    k: v for k, v in (h.last_stats or {}).items()
                    if k not in ("hists", "spans_slowest", "peers")
                },
                "hists": {n: hh.to_dict()
                          for n, hh in h.last_hists.items()},
                "spans_slowest": list(h.last_spans),
            }
        out: Dict[str, Any] = {
            "schema": "emqx-tpu/fleet-dump/v1",
            "node": self.node_name,
            "workers": workers,
            "fleet_hists": {n: hh.to_dict()
                            for n, hh in self.fleet_histograms().items()},
        }
        if self.service is not None:
            out["hub"] = {
                "stats": self.service.stats(),
                "lanes": {str(i): d for i, d in
                          self.service.lane_stats().items()},
            }
        return out

    # ------------------------------------------------------------ status

    def status(self) -> Dict[str, Any]:
        link = self.runtime.cluster.status()
        return {
            "workers": self.n,
            "reuseport": self.reuseport,
            "listeners": [
                {"type": d.get("type", "tcp"), "port": d["port"]}
                for d in self.listener_defs
            ],
            "pool": [
                {
                    "name": h.name,
                    "pid": h.proc.pid if h.proc is not None else None,
                    "link": link.get(h.name, "down"),
                    "direct_port": h.direct_port,
                    "fails": h.fails,
                    "stats": h.last_stats,
                }
                for h in self.workers.values()
            ],
        }
