"""Wire-worker process entry — `python -m emqx_tpu.wire.worker`.

PROCESS-PRIVATE MODULE: nothing in the parent process may import this
(the `proc-boundary` analysis pass errors on any such import).  The
only things that cross the supervisor/worker boundary are the spawn
command line, the derived JSON config, inherited listening fds, and
cluster-transport frames over the worker's unix socket.

A worker is a full `NodeRuntime` — the same connection/channel/session/
delivery stack a standalone node runs — whose derived config (written
by `supervisor.WireSupervisor.worker_raw`) points its listeners at the
shared ports (SO_REUSEPORT or inherited fd), parks sessions on its own
disc store, and clusters it to the hub and sibling workers over
UNIX-domain PeerLinks.  On top of that it registers the `wire_stats`
RPC the supervisor scrapes for the per-worker gauges.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys


# slowest-span sample shipped per scrape: enough for a fleet waterfall
# view without growing the RPC frame past a few KB
SLOW_SPANS_K = 8


def wire_stats(runtime):
    """The supervisor-facing stats snapshot (everything here is plain
    numbers / JSON-safe dicts — the ONLY state that ever leaves this
    process).  Besides the gauges, each scrape ships the worker's
    mergeable log2 histograms (`LatencyHistogram.to_dict` wire form:
    span stages incl. the shm ring legs, loop-lag, GC pauses, engine
    tick) plus a bounded slowest-K span sample — the supervisor merges
    them into the fleet-level view (`WireSupervisor.fleet_histograms`)
    and Prometheus/$SYS//monitor export per-worker AND merged."""
    b = runtime.broker
    m = b.metrics
    cluster = runtime.cluster
    out = {
        "connections": len(b.cm.channels),
        "sessions": len(b.cm.channels) + len(b.cm.pending),
        "subscriptions": b.subscription_count,
        "accepts": m.get("client.connect"),
        "shed": m.get("olp.new_conn.shed"),
        "rate_limited": m.get("olp.new_conn.rate_limited"),
        "spool_pending": cluster.spool_pending() if cluster else 0,
        "peers": dict(cluster.status()) if cluster else {},
        "forward_in": m.get("messages.forward.in"),
        "forward_out": m.get("messages.forward.out"),
        "messages_sent": m.get("messages.sent"),
        # shared-memory match plane (shm/client.py): zeros when this
        # worker runs its own engine (shm.enable=false derivations)
        "shm_submits": getattr(b.engine, "shm_submits", 0),
        "shm_degraded": getattr(b.engine, "shm_degraded", 0),
        "shm_local": getattr(b.engine, "shm_local", 0),
        "shm_oversize": getattr(b.engine, "shm_oversize", 0),
        "shm_reregisters": getattr(b.engine, "shm_reregisters", 0),
        "shm_hub_down": bool(getattr(b.engine, "hub_down", False)),
    }
    from ..observe import spans as _spans

    hists = {}
    for stage, h in _spans.stage_histograms().items():
        if h.count:
            hists[f"span_stage_{stage}_latency"] = h.to_dict()
    for name, h in runtime.contention.histograms().items():
        if h.count:
            hists[name] = h.to_dict()
    for name, attr in (("engine_tick_latency", "hist_tick"),
                       ("shm_ring_roundtrip", "hist_ring")):
        h = getattr(b.engine, attr, None)
        if h is not None and h.count:
            hists[name] = h.to_dict()
    out["hists"] = hists
    if _spans.enabled():
        out["spans_slowest"] = _spans.plane().slowest()[:SLOW_SPANS_K]
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="emqx_tpu.wire.worker")
    ap.add_argument("--config", "-c", required=True,
                    help="derived worker config (written by the "
                         "supervisor)")
    args = ap.parse_args(argv)

    # same post-import platform override as the node entry point: the
    # supervisor pins EMQX_TPU_JAX_PLATFORM when the site env doesn't
    _plat = os.environ.get("EMQX_TPU_JAX_PLATFORM")
    if _plat:
        import jax

        jax.config.update("jax_platforms", _plat)

    with open(args.config, "r", encoding="utf-8") as f:
        raw = json.load(f)

    from ..config.config import Config
    from ..node import NodeRuntime
    from ..observe.logfmt import setup_logging

    conf = Config(raw)
    setup_logging(level=conf.get("log.level"), fmt=conf.get("log.format"))
    runtime = NodeRuntime(raw)
    # dedicated process: same GC discipline as `python -m emqx_tpu`
    # (freeze the boot object graph out of gen-2 sweeps after start())
    runtime.gc_tune_after_boot = True
    assert runtime.cluster is not None, "worker config must cluster"
    runtime.cluster.transport.rpc_handlers["wire_stats"] = (
        lambda peer, params: wire_stats(runtime)
    )
    try:
        asyncio.run(runtime.run_forever())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
