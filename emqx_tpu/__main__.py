"""`python -m emqx_tpu` — boot one broker node (the `bin/emqx` analog).

Config file is JSON with the schema namespaces of `config.config.SCHEMA`
plus the structured `listeners` / `cluster` / `authentication` /
`authorization` / `rewrite` / `auto_subscribe` sections consumed by
`NodeRuntime`.  Environment overrides use `EMQX_TPU__<ns>__<key>`.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import sys

from .config.config import Config
from .node import NodeRuntime


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="emqx_tpu", description="TPU-native MQTT broker node"
    )
    ap.add_argument("--config", "-c", help="JSON config file path")
    ap.add_argument(
        "--print-config",
        action="store_true",
        help="print the checked effective config and exit",
    )
    ap.add_argument(
        "--log-level", default="INFO", help="root log level (default INFO)"
    )
    args = ap.parse_args(argv)

    raw = {}
    if args.config:
        with open(args.config, "r", encoding="utf-8") as f:
            raw = json.load(f)

    if args.print_config:
        print(json.dumps(Config(raw).dump(), indent=2, sort_keys=True))
        return 0

    logging.basicConfig(
        level=getattr(logging, args.log_level.upper(), logging.INFO),
        format="%(asctime)s [%(levelname)s] %(name)s: %(message)s",
    )
    node = NodeRuntime(raw)
    try:
        asyncio.run(node.run_forever())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
