"""`python -m emqx_tpu` — boot one broker node (the `bin/emqx` analog).

Config file is JSON with the schema namespaces of `config.config.SCHEMA`
plus the structured `listeners` / `cluster` / `authentication` /
`authorization` / `rewrite` / `auto_subscribe` sections consumed by
`NodeRuntime`.  Environment overrides use `EMQX_TPU__<ns>__<key>`.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

# Environments that pre-import jax (site hooks) may pin a platform
# before env vars like JAX_PLATFORMS can apply; this override works
# post-import as long as the backend hasn't initialized yet, so
# `EMQX_TPU_JAX_PLATFORM=cpu python -m emqx_tpu ...` reliably runs the
# engine on CPU (tests, CI, machines without an accelerator).
_plat = os.environ.get("EMQX_TPU_JAX_PLATFORM")
if _plat:
    import jax

    jax.config.update("jax_platforms", _plat)

from .config.config import Config
from .node import NodeRuntime


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="emqx_tpu", description="TPU-native MQTT broker node"
    )
    ap.add_argument("--config", "-c", help="JSON config file path")
    ap.add_argument(
        "--print-config",
        action="store_true",
        help="print the checked effective config and exit",
    )
    ap.add_argument(
        "--log-level", default=None,
        help="root log level (overrides the log.level config key)"
    )
    ap.add_argument(
        "--log-format", default=None, choices=("text", "json"),
        help="line format (overrides the log.format config key)"
    )
    args = ap.parse_args(argv)

    raw = {}
    if args.config:
        with open(args.config, "r", encoding="utf-8") as f:
            raw = json.load(f)

    if args.print_config:
        print(json.dumps(Config(raw).dump(), indent=2, sort_keys=True))
        return 0

    from .observe.logfmt import setup_logging

    conf = Config(raw)
    setup_logging(
        level=args.log_level or conf.get("log.level"),
        fmt=args.log_format or conf.get("log.format"),
    )
    node = NodeRuntime(raw)
    # GC tuning is process-global (freeze + thresholds), so it is opted
    # into only by this dedicated-process entry point — never by embedded
    # or multi-node-in-one-interpreter usage.  The actual freeze runs at
    # the END of start(), after boot has built/restored the route tables
    # and session stores it is meant to exempt from gen-2 sweeps.
    node.gc_tune_after_boot = True
    try:
        asyncio.run(node.run_forever())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
