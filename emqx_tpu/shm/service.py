"""Hub-side match service: event-driven drain engine over every
worker's submit ring, feeding the ONE device engine.

The service owns the slabs (created through :class:`ShmRegistry` before
the workers spawn) and runs as a single asyncio task on the hub loop,
so every engine mutation — churn application AND match dispatch — stays
on the loop thread, preserving the engines' single-mutator contract.
Only the device-sync half of a dispatch (`foreign_collect`) runs on the
default executor, mirroring how the broker's own collects block.

Wakeup (``shm.drain``): instead of the v1 fixed-cadence poll, the hub
blocks on per-lane DOORBELLS — one eventfd per lane that the worker
rings on slot commit (only when the hub armed the lane's ``C_HUB_WAIT``
ctrl word, so the busy path pays no syscall).  The block happens on a
dedicated single-thread executor so the loop sleeps for real: the
waiter calls ``etpu_drain_wait`` (native poll(2) over all lane fds,
GIL released; mode ``native``) or ``select.poll`` (mode ``thread``),
in ~100 ms slices that stamp the hub heartbeat so workers never see a
stale hub mid-wait, returning every ~1 s for housekeeping (worker-gen
reclaim, ack retries) even if no doorbell ever rings.  ``auto`` picks
native when the lib is present; ``poll`` keeps the v1 asyncio loop
(``shm.poll_interval`` cadence) as the portable fallback.  Idle hub
wakeups drop from ~1/poll_interval to ~1/s.

Fusion (``shm.fuse_window_us``): when >= 2 lanes are hot (a match
drained within the last 10 ms), a pass whose harvest did not include
every hot lane waits one fusion window and re-drains before
dispatching, so cross-worker ticks coalesce into one device call.  The
window collapses to zero with a single hot lane — p50 never pays for
fusion nobody gets.

Fairness (``shm.lane_credit``): each pass consumes at most
``lane_credit`` records per lane, lanes walked in rotating round-robin
order; a flooding worker leaves its surplus in its own ring (per-ring
order preserved — the tail never skips) and the pass immediately
re-runs, so siblings are never starved behind one hot ring
(exhaustions counted + ``shm.credit`` traced).

Drain is three-phase per pass, preserving each ring's record order:

1. walk every published record per lane; churn/hello records are
   applied to the engine inline (so a match that FOLLOWS a subscribe in
   its own ring is matched against the updated tables);
2. match records from all lanes are grouped by packed geometry (B, L)
   and handed to ``engine.foreign_submit`` in chunks of 4/2/1 — the PR
   12 coalesced-group machinery now fusing ticks from DIFFERENT
   processes into one device call (the flight recorder's `grp` column);
   ``foreign_submit`` copies the slot payloads into its own staging, so
3. every lane's tail advances immediately and the slots recycle while
   the device call is still in flight.

Reclamation: a respawned worker resets its rings and bumps its
generation cell; the service notices the stamp change, drops the dead
incarnation's filter refcounts from the engine, and resyncs cursors.
A full result ring never blocks the hub — the reply is dropped and the
worker's tick times out to its local trie.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import json
import os
import select
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..observe.flight import LatencyHistogram
from ..observe.tracepoints import tp
from ..ops import native
from .doorbell import Doorbell
from .registry import ShmRegistry
from .rings import (
    C_HUB_GEN, C_HUB_HB, C_HUB_WAIT, C_MAGIC, C_CHURN_APPLIED, C_SEM,
    K_CHURN, K_HELLO, K_MATCH, K_CHURN_ACK, K_MATCH_RES, K_SEM,
    K_SEM_RES, K_SEMQ, K_SEMQ_ACK, MAGIC, SlabView, slab_bytes,
)

GROUP_SIZES = (4, 2, 1)  # same ladder as the sharded coalescer

HOT_NS = 10_000_000      # lane hot = match drained within the last 10 ms
_HB_SLICE_S = 0.1        # mid-wait heartbeat stamp cadence
_HOUSEKEEP_S = 1.0       # max block before a housekeeping pass
_ACK_RETRY_S = 0.005     # wait cap while churn acks are queued


def parse_cores(spec: str) -> List[int]:
    """Parse a ``shm.pin_cores`` spec ("0-3", "0,2,5", mixes) into a
    core list; empty/invalid pieces are dropped (pinning is advisory)."""
    cores: List[int] = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            if "-" in part:
                lo, hi = part.split("-", 1)
                cores.extend(range(int(lo), int(hi) + 1))
            else:
                cores.append(int(part))
        except ValueError:
            continue
    return [c for c in cores if c >= 0]


def _pin_thread(core: int) -> None:
    """Pin the CURRENT thread (advisory: failures are silent — a cgroup
    mask narrower than the spec must not kill the drain engine)."""
    try:
        os.sched_setaffinity(0, {core})
    except (AttributeError, OSError, ValueError):  # pragma: no cover
        pass


class LaneState:
    """One worker's slab plus the hub's bookkeeping for it."""

    __slots__ = ("idx", "slab", "gen", "filters", "res_lk",
                 "pending_acks", "doorbell", "last_match_ns",
                 "sem_owner", "sem_l2h", "pending_sem_acks")

    def __init__(self, idx: int, slab: SlabView,
                 doorbell: Optional[Doorbell] = None):
        self.idx = idx
        self.slab = slab
        self.gen = slab.worker_gen
        # filter -> refcount added by THIS lane (drives reclamation)
        self.filters: Dict[str, int] = {}
        # semantic lane: owner key queries are registered under (the
        # worker's node name, K_SEMQ blob element 0; lane-scoped
        # fallback until it arrives), worker lqid -> hub qid (drives
        # removes + reclamation), and K_SEMQ_ACK blobs awaiting ring
        # space (same never-lose-an-ack contract as churn acks)
        self.sem_owner = f"lane{idx}"
        self.sem_l2h: Dict[int, int] = {}
        self.pending_sem_acks: List[Tuple[int, int, bytes]] = []
        self.res_lk = asyncio.Lock()
        # churn acks that found the result ring full: unlike match
        # results (worker times out to its local trie and retries the
        # next tick), a lost ack would leave the worker's fid mapping
        # un-acked FOREVER, so these retry every drain pass
        self.pending_acks: List[Tuple[int, List[int]]] = []
        # wakeup channel the worker rings on commit (hub-created; the
        # fd crosses to the worker via pass_fds + shm.doorbell_fd)
        self.doorbell = doorbell
        # when the lane last had a match drained (fusion hot-tracking)
        self.last_match_ns = 0


class _MatchReq:
    __slots__ = ("lane", "tick", "n", "B", "L", "payload", "t_drain",
                 "t_fuse")

    def __init__(self, lane: LaneState, tick: int, n: int, B: int,
                 L: int, payload: np.ndarray, t_drain: int = 0):
        self.lane = lane
        self.tick = tick
        self.n = n
        self.B = B
        self.L = L
        self.payload = payload  # [B, 2L+2] u32 COPY (slot already freed)
        # span-leg stamps (monotonic ns; 0 = the submit was unstamped,
        # i.e. the worker's span plane is disarmed — the reply then
        # ships zero timestamps and the worker records nothing)
        self.t_drain = t_drain
        self.t_fuse = 0


class _SemReq:
    """One K_SEM payload tick: texts decoded at drain time (the slot
    recycles immediately), matched off-loop, answered per lane."""

    __slots__ = ("lane", "tick", "texts")

    def __init__(self, lane: LaneState, tick: int, texts: List[str]):
        self.lane = lane
        self.tick = tick
        self.texts = texts


class MatchService:
    """Single hub-side drain loop over all worker lanes."""

    def __init__(self, engine, reg: ShmRegistry, slots: int,
                 slot_bytes: int, poll_interval: float = 0.002,
                 drain: str = "auto", fuse_window_us: int = 0,
                 lane_credit: int = 64, pin_cores: str = ""):
        self.engine = engine
        # ONE pool-wide SemanticEngine (emqx_tpu/semantic/engine.py),
        # attached by the supervisor when `semantic.enable` is on: the
        # only embedding table in the whole fleet lives behind this
        self.semantic = None
        self.reg = reg
        self.slots = slots
        self.slot_bytes = slot_bytes
        self.poll_interval = float(poll_interval)
        self.drain = drain                  # auto | native | thread | poll
        # resolved at start(); the drain thread only ever DOWNGRADES it
        # to "thread" when the native lib vanishes mid-run — a str swap
        # is atomic under the GIL and both readers tolerate either value
        self.drain_mode = ""  # analysis: owner=any
        self.fuse_window_us = int(fuse_window_us)
        self.lane_credit = int(lane_credit)
        self.pin_cores = parse_cores(pin_cores)
        self.lanes: Dict[int, LaneState] = {}
        # lifecycle state is loop-owned: mutated only here (before the
        # object is shared) and in start()/stop(), which run on the
        # loop (threads reach stop() via run_coroutine_threadsafe)
        self._task: Optional[asyncio.Task] = None  # analysis: owner=loop
        self._replies: set = set()  # in-flight _collect_reply tasks
        self._stop = False  # analysis: owner=loop
        # doorbell wait machinery (modes native/thread): the dedicated
        # drain thread + the stop doorbell that unparks it at stop().
        # Both are set once in start() BEFORE the drain thread exists
        # and cleared only after _exec.shutdown(wait=True) joins it —
        # the thread never observes a mutation
        self._exec: Optional[concurrent.futures.ThreadPoolExecutor] = None  # analysis: owner=any
        self._stop_db: Optional[Doorbell] = None  # analysis: owner=any
        # counters (supervisor mirrors these into broker metrics)
        self.match_ticks = 0
        self.match_groups = 0
        self.churn_records = 0
        self.churn_filters = 0
        self.reclaims = 0
        self.res_drops = 0
        self.ack_sheds = 0        # churn acks shed by _flush_acks
        self.sem_ticks = 0        # K_SEM ticks answered
        self.sem_texts = 0        # payload texts matched
        self.sem_res_drops = 0    # replies lost to a full result ring
        self.sem_churn = 0        # K_SEMQ records applied
        self.errors = 0
        # drain-engine telemetry: passes that found work vs not, how
        # the loop was woken, credit exhaustions, fusion-window waits
        self.drain_passes = 0
        self.idle_passes = 0
        # the wake-cause pair is bumped on the drain thread (the loop is
        # parked in run_in_executor while it runs) and read loop-side for
        # stats — int += is GIL-atomic and a torn read is just a stat
        self.doorbell_wakeups = 0  # analysis: owner=any
        self.wait_timeouts = 0  # analysis: owner=any  (housekeeping returns)
        self.credit_exhausted = 0
        self.fuse_waits = 0
        self._more = False         # credit carryover: re-pass immediately
        self._hot_count = 0        # lanes with a match in the last HOT_NS
        self._rr = 0               # round-robin lane-walk rotation
        # drain/fusion telemetry (fleet observability plane): the
        # adaptive-fusion controller (ROADMAP item 1) consumes exactly
        # these — how often the drain loop actually turns, and how much
        # cross-lane coalescing each pass achieved
        self.hist_drain = LatencyHistogram()  # drain-cycle gap (s)
        self.group_sizes: Dict[int, int] = {}  # fused group size -> count

    # ------------------------------------------------------------- lanes

    def create_lane(self, idx: int) -> str:
        """Create (or adopt) worker `idx`'s slab; returns the region
        name to hand the worker via its derived config."""
        seg = self.reg.create("lane", idx,
                              slab_bytes(self.slots, self.slot_bytes))
        slab = SlabView(seg, self.slots, self.slot_bytes)
        # fresh hub incarnation for this lane: reset both rings (we are
        # about to become submit-consumer / result-producer), bump the
        # hub generation so an adopted-slab worker re-registers
        slab.submit.reset()
        slab.result.reset()
        slab.ctrl[C_MAGIC] = MAGIC
        slab.ctrl[C_HUB_GEN] += 1
        slab.ctrl[C_CHURN_APPLIED] = 0
        slab.ctrl[C_HUB_WAIT] = 0
        slab.ctrl[C_HUB_HB] = time.monotonic_ns()
        slab.ctrl[C_SEM] = (
            self.semantic.n_queries if self.semantic is not None else 0
        )
        prev = self.lanes.get(idx)
        db = prev.doorbell if prev is not None else Doorbell()
        self.lanes[idx] = LaneState(idx, slab, db)
        return self.reg.names[f"lane{idx}"]

    def doorbell_fd(self, idx: int) -> int:
        """Worker-side (ring) fd of lane `idx`'s doorbell — the integer
        the supervisor passes through pass_fds + ``shm.doorbell_fd``."""
        return self.lanes[idx].doorbell.fd

    def lane_core(self, idx: int) -> Optional[int]:
        """The core lane `idx`'s worker should pin to under
        ``shm.pin_cores`` (first core is the drain thread's), or None."""
        if len(self.pin_cores) < 2:
            return None
        rest = self.pin_cores[1:]
        return rest[idx % len(rest)]

    def _drop_lane_filters(self, lane: LaneState, why: str) -> None:
        # queued acks address the dead incarnation's churn seqs, which
        # a respawn restarts from zero — never deliver them to the new
        # incarnation
        lane.pending_acks.clear()
        lane.pending_sem_acks.clear()
        n = sum(lane.filters.values())
        for filt, cnt in lane.filters.items():
            for _ in range(cnt):
                try:
                    self.engine.remove_filter(filt)
                except Exception:  # pragma: no cover - engine poisoned
                    self.errors += 1
        lane.filters.clear()
        # the dead incarnation's semantic queries go the same way: its
        # lqid space restarts from zero on respawn, so every mapping is
        # stale the moment the gen bumps
        if lane.sem_l2h and self.semantic is not None:
            for hub in lane.sem_l2h.values():
                try:
                    self.semantic.remove_query(hub)
                except Exception:  # pragma: no cover
                    self.errors += 1
            n += len(lane.sem_l2h)
        lane.sem_l2h.clear()
        self._sync_sem_count()
        if n:
            tp("shm.reclaim", lane=lane.idx, filters=n, why=why)

    def _check_worker_gen(self, lane: LaneState) -> None:
        gen = lane.slab.worker_gen
        if gen != lane.gen:
            # worker respawned: it already reset both rings, so every
            # in-flight slot of the dead incarnation is reclaimed here
            self.reclaims += 1
            self._drop_lane_filters(lane, "worker-gen")
            lane.gen = gen

    # ------------------------------------------------------------- churn

    def _apply_churn(self, lane: LaneState, rec) -> None:
        pay = bytes(rec.payload[: rec.a + rec.b])
        adds = pay[: rec.a].decode().split("\0") if rec.a else []
        removes = pay[rec.a:].decode().split("\0") if rec.b else []
        fids: List[int] = []
        for filt in adds:
            try:
                fids.append(int(self.engine.add_filter(filt)))
                lane.filters[filt] = lane.filters.get(filt, 0) + 1
            except Exception:  # pragma: no cover - bad filter string
                self.errors += 1
                fids.append(-1)
        for filt in removes:
            if lane.filters.get(filt, 0) <= 0:
                continue  # not this lane's (stale incarnation record)
            try:
                self.engine.remove_filter(filt)
                lane.filters[filt] -= 1
                if not lane.filters[filt]:
                    del lane.filters[filt]
            except Exception:  # pragma: no cover
                self.errors += 1
        self.churn_records += 1
        self.churn_filters += len(adds) + len(removes)
        lane.slab.ctrl[C_CHURN_APPLIED] = rec.tick
        if adds:
            self._send_ack(lane, rec.tick, fids)
        tp("shm.churn", lane=lane.idx, seq=rec.tick, adds=len(adds),
           removes=len(removes))

    def _send_ack(self, lane: LaneState, seq: int,
                  fids: List[int]) -> None:
        lane.pending_acks.append((seq, fids))
        self._flush_acks(lane)

    def _flush_acks(self, lane: LaneState) -> None:
        """Write queued churn acks in order until the result ring backs
        up; a subscribe burst (bulk add_filters) produces acks faster
        than the worker drains them, and they must all land eventually.
        Bounded: a worker that stops draining its ring entirely sheds
        the oldest acks past 4x ring depth (counted in ack_sheds —
        `shm.hub.ack_shed`, the stuck-worker tell BEFORE the eventual
        re-register) and recovers them through that re-register."""
        while lane.pending_acks:
            w = lane.slab.result.reserve()
            if w is None:
                over = len(lane.pending_acks) - 4 * self.slots
                if over > 0:
                    del lane.pending_acks[:over]
                    self.ack_sheds += over
                    tp("shm.ack_shed", lane=lane.idx, shed=over,
                       queued=len(lane.pending_acks))
                return
            seq, fids = lane.pending_acks[0]
            arr = np.asarray(fids, np.int64)
            w.payload_u8(arr.nbytes)[:] = arr.view(np.uint8)
            w.commit(K_CHURN_ACK, seq, a=len(fids), nbytes=arr.nbytes)
            lane.pending_acks.pop(0)

    # ---------------------------------------------------------- semantic

    def _sync_sem_count(self) -> None:
        """Mirror the pool-wide live query count into every lane's
        C_SEM cell: workers gate their K_SEM submits on it, so the
        no-semantic-anywhere fleet never ships a payload tick."""
        n = self.semantic.n_queries if self.semantic is not None else 0
        for lane in self.lanes.values():
            lane.slab.ctrl[C_SEM] = n

    def _apply_semq(self, lane: LaneState, rec) -> None:
        """K_SEMQ: register/deregister one worker's semantic queries
        against the hub table.  Applied inline on the drain pass (the
        churn discipline: a K_SEM that FOLLOWS the subscribe in the same
        ring matches against the updated table)."""
        blob = bytes(rec.payload[: rec.nbytes]).decode("utf-8", "replace")
        parts = blob.split("\0")
        if rec.c and parts:
            if parts[0]:
                lane.sem_owner = parts[0]
            parts = parts[1:]
        adds = parts[: rec.a]
        removes = parts[rec.a: rec.a + rec.b]
        pairs: List[Tuple[int, int]] = []
        for el in adds:
            lq, sep, text = el.partition("\x01")
            try:
                lqid = int(lq)
            except ValueError:
                self.errors += 1
                continue
            if not sep:
                continue
            hub = -1
            if self.semantic is not None:
                try:
                    hub = int(self.semantic.add_query(
                        text, owner=lane.sem_owner
                    ))
                except Exception:  # pragma: no cover - engine poisoned
                    self.errors += 1
                    hub = -1
            if hub >= 0:
                lane.sem_l2h[lqid] = hub
            pairs.append((lqid, hub))
        for el in removes:
            try:
                lqid = int(el)
            except ValueError:
                continue
            hub = lane.sem_l2h.pop(lqid, None)
            if hub is not None and self.semantic is not None:
                try:
                    self.semantic.remove_query(hub)
                except Exception:  # pragma: no cover
                    self.errors += 1
        self.sem_churn += 1
        self._sync_sem_count()
        if pairs:
            ab = "\0".join(f"{lq}\x01{hub}" for lq, hub in pairs)
            lane.pending_sem_acks.append(
                (rec.tick, len(pairs), ab.encode())
            )
            self._flush_sem_acks(lane)
        tp("shm.semq", lane=lane.idx, seq=rec.tick, adds=len(adds),
           removes=len(removes),
           live=self.semantic.n_queries if self.semantic else 0)

    def _flush_sem_acks(self, lane: LaneState) -> None:
        """K_SEMQ_ACK writer: same ordered/bounded contract as
        `_flush_acks` — a worker whose un-acked queries never map can
        never receive a cross-worker forward for them."""
        while lane.pending_sem_acks:
            w = lane.slab.result.reserve()
            if w is None:
                over = len(lane.pending_sem_acks) - 4 * self.slots
                if over > 0:
                    del lane.pending_sem_acks[:over]
                    self.ack_sheds += over
                    tp("shm.ack_shed", lane=lane.idx, shed=over,
                       queued=len(lane.pending_sem_acks))
                return
            seq, n, blob = lane.pending_sem_acks[0]
            w.payload_u8(len(blob))[:] = np.frombuffer(blob, np.uint8)
            w.commit(K_SEMQ_ACK, seq, a=n, nbytes=len(blob))
            lane.pending_sem_acks.pop(0)

    def _dispatch_sem(self, reqs: List[_SemReq]) -> None:
        """Fuse every lane's payload ticks from this pass into ONE
        engine call (the cross-worker coalescing story, semantic
        edition) and answer each lane off-loop."""
        loop = asyncio.get_running_loop()
        t = loop.create_task(self._collect_sem_reply(reqs))
        self._replies.add(t)
        t.add_done_callback(self._replies.discard)

    async def _collect_sem_reply(self, reqs: List[_SemReq]) -> None:
        texts: List[str] = []
        for r in reqs:
            texts.extend(r.texts)
        loop = asyncio.get_running_loop()
        try:
            # engine.match runs the submit/collect split under its own
            # lock (device top-k or exact host, EWMA-arbitrated) — the
            # same blocking contract as foreign_collect
            rows = await loop.run_in_executor(
                None, self.semantic.match, texts
            )
        except Exception:  # pragma: no cover - device fault
            self.errors += 1
            return
        owners = self.semantic.table.owners
        off = 0
        for req in reqs:
            n = len(req.texts)
            recs = []
            for row in rows[off: off + n]:
                own: List[int] = []
                rem: Dict[str, List[int]] = {}
                for qid, _score in row:
                    owner = owners.get(qid, "")
                    if owner == req.lane.sem_owner:
                        own.append(int(qid))
                    elif owner:
                        rem.setdefault(owner, []).append(int(qid))
                recs.append({"own": own, "rem": rem})
            off += n
            blob = json.dumps(recs, separators=(",", ":")).encode()
            lane = req.lane
            async with lane.res_lk:
                w = lane.slab.result.reserve()
                if w is None or len(blob) > lane.slab.result.payload_cap:
                    self.sem_res_drops += 1
                    continue  # worker times out to its exact fallback
                w.payload_u8(len(blob))[:] = np.frombuffer(
                    blob, np.uint8
                )
                w.commit(K_SEM_RES, req.tick, a=n, nbytes=len(blob))
            self.sem_ticks += 1
            self.sem_texts += n

    # ------------------------------------------------------------- drain

    def _drain_once(self) -> Tuple[int, List[_MatchReq], List[_SemReq]]:
        """Phase 1+3: walk every lane's published records in order,
        applying churn inline and COPYING match payloads, then advance
        the tails so the slots recycle immediately.

        Fairness: lanes are walked in rotating round-robin order and
        each lane yields at most ``lane_credit`` records per pass; the
        surplus stays IN the ring (the tail only ever advances over
        consumed records, so per-ring order holds) and ``self._more``
        flags the loop to re-pass immediately instead of sleeping —
        the flooding lane carries over, the siblings go first."""
        reqs: List[_MatchReq] = []
        semreqs: List[_SemReq] = []
        consumed = 0
        self._more = False
        now_ns = time.monotonic_ns()  # one clock read per pass: span
        #   drain stamps + fusion hot-tracking share it
        order = list(self.lanes.values())
        if len(order) > 1:
            rot = self._rr % len(order)
            self._rr += 1
            order = order[rot:] + order[:rot]
        credit = self.lane_credit if self.lane_credit > 0 else 0
        for lane in order:
            self._check_worker_gen(lane)
            if lane.pending_acks:  # ring-full leftovers from last pass
                self._flush_acks(lane)
            if lane.pending_sem_acks:
                self._flush_sem_acks(lane)
            ring = lane.slab.submit
            k = 0
            taken = 0
            while True:
                if credit and taken >= credit:
                    if ring.peek_at(k) is not None:
                        # surplus carries over; force an immediate
                        # re-pass so the flooder still drains flat out
                        self._more = True
                        self.credit_exhausted += 1
                        tp("shm.credit", lane=lane.idx,
                           left=ring.depth - k)
                    break
                rec = ring.peek_at(k)
                if rec is None:
                    break
                if rec.gen != (lane.gen & 0xFFFFFFFF):
                    k += 1  # dead incarnation's leftover: skip
                    continue
                if rec.kind == K_HELLO:
                    self._drop_lane_filters(lane, "hello")
                elif rec.kind == K_CHURN:
                    self._apply_churn(lane, rec)
                elif rec.kind == K_MATCH:
                    pay = rec.payload[: rec.nbytes].view(np.uint32)
                    buf = pay.reshape(rec.b, 2 * rec.c + 2).copy()
                    lane.last_match_ns = now_ns
                    reqs.append(_MatchReq(lane, rec.tick, rec.a,
                                          rec.b, rec.c, buf,
                                          now_ns if rec.ts[0] else 0))
                elif rec.kind == K_SEMQ:
                    self._apply_semq(lane, rec)
                elif rec.kind == K_SEM:
                    raw = bytes(rec.payload[: rec.nbytes]).decode(
                        "utf-8", "replace"
                    )
                    texts = raw.split("\0") if rec.nbytes else []
                    if len(texts) < rec.a:
                        texts += [""] * (rec.a - len(texts))
                    semreqs.append(
                        _SemReq(lane, rec.tick, texts[: rec.a])
                    )
                    lane.last_match_ns = now_ns
                k += 1
                taken += 1
            if k:
                ring.advance(k)
                consumed += k
        self._hot_count = sum(
            1 for lane in self.lanes.values()
            if now_ns - lane.last_match_ns < HOT_NS and lane.last_match_ns
        )
        return consumed, reqs, semreqs

    def _effective_window_s(self) -> float:
        """The adaptive fusion window: ``shm.fuse_window_us`` while >= 2
        lanes are hot, collapsed to zero for a lone talker (fusion can
        only ever pair ticks from DIFFERENT lanes)."""
        if self.fuse_window_us <= 0 or self._hot_count < 2:
            return 0.0
        return self.fuse_window_us / 1e6

    def _dispatch(self, reqs: List[_MatchReq]) -> None:
        """Phase 2: group by geometry and fuse cross-worker ticks into
        single engine calls via the foreign-ticket intake."""
        by_geom: Dict[Tuple[int, int], List[_MatchReq]] = {}
        for r in reqs:
            by_geom.setdefault((r.B, r.L), []).append(r)
        loop = asyncio.get_running_loop()
        for members in by_geom.values():
            i = 0
            while i < len(members):
                k = 1
                for g in GROUP_SIZES:
                    if len(members) - i >= g:
                        k = g
                        break
                chunk = members[i:i + k]
                i += k
                if any(r.t_drain for r in chunk):
                    t_fuse = time.monotonic_ns()
                    for r in chunk:
                        if r.t_drain:
                            r.t_fuse = t_fuse
                try:
                    handle = self.engine.foreign_submit(
                        [(r.payload, r.n) for r in chunk]
                    )
                except Exception:  # pragma: no cover - engine poisoned
                    self.errors += 1
                    continue
                self.match_ticks += len(chunk)
                self.match_groups += 1
                self.group_sizes[k] = self.group_sizes.get(k, 0) + 1
                if k > 1:
                    tp("shm.group", k=k,
                       lanes=sorted({r.lane.idx for r in chunk}))
                t = loop.create_task(self._collect_reply(handle, chunk))
                self._replies.add(t)
                t.add_done_callback(self._replies.discard)

    async def _collect_reply(self, handle,
                             chunk: List[_MatchReq]) -> None:
        loop = asyncio.get_running_loop()
        try:
            results = await loop.run_in_executor(
                None, self.engine.foreign_collect, handle
            )
        except Exception:  # pragma: no cover - device fault
            self.errors += 1
            return
        t_done = time.monotonic_ns() \
            if any(r.t_drain for r in chunk) else 0
        for req, (counts, fids) in zip(chunk, results):
            lane = req.lane
            async with lane.res_lk:
                w = lane.slab.result.reserve()
                need = 4 * req.n + 4 * len(fids)
                if w is None or need > lane.slab.result.payload_cap:
                    self.res_drops += 1
                    continue  # worker times out to its local trie
                pay = w.payload_u8(need)
                pay[: 4 * req.n] = np.ascontiguousarray(
                    counts, np.uint32
                ).view(np.uint8)
                if len(fids):
                    pay[4 * req.n:] = np.ascontiguousarray(
                        fids, np.int32
                    ).view(np.uint8)
                # reply stamps ride the result slot's timestamp lane
                # (zeros for an unstamped submit: the worker records
                # legs only when it stamped the submit itself)
                w.commit(K_MATCH_RES, req.tick, a=req.n, nbytes=need,
                         t0=req.t_drain, t1=req.t_fuse,
                         t2=t_done if req.t_drain else 0)

    # -------------------------------------------------------------- loop

    async def _pass(self) -> int:
        """One drain pass + fusion window + dispatch; returns records
        consumed.  Sets ``self._more`` when credit left surplus."""
        consumed, reqs, semreqs = self._drain_once()
        if reqs or semreqs:
            window = self._effective_window_s()
            if window > 0:
                hit = {r.lane.idx for r in reqs}
                hit |= {r.lane.idx for r in semreqs}
                if len(hit) < self._hot_count:
                    # some hot lane missed this harvest: hold dispatch
                    # one window so its in-flight tick fuses in
                    self.fuse_waits += 1
                    await asyncio.sleep(window)
                    c2, r2, s2 = self._drain_once()
                    consumed += c2
                    reqs += r2
                    semreqs += s2
            if reqs:
                self._dispatch(reqs)
            if semreqs and self.semantic is not None:
                self._dispatch_sem(semreqs)
        return consumed

    async def _run(self) -> None:
        last_ns = 0
        evented = self.drain_mode in ("native", "thread")
        while not self._stop:
            now = time.monotonic_ns()
            # drain-cycle gap: the cadence the submit rings are
            # actually drained at (back-to-back under load; idle gaps
            # are wakeup-bounded) — the upper bound any ring_wait pays
            if last_ns:
                self.hist_drain.observe((now - last_ns) / 1e9)
            last_ns = now
            for lane in self.lanes.values():
                lane.slab.ctrl[C_HUB_HB] = now
            self.drain_passes += 1
            try:
                consumed = await self._pass()
            except asyncio.CancelledError:
                raise
            except Exception:  # pragma: no cover - keep the hub alive
                self.errors += 1
                consumed = 0
            if consumed or self._more:
                await asyncio.sleep(0)  # busy: yield and come right back
                continue
            self.idle_passes += 1
            if evented:
                await self._block_on_doorbells()
            else:
                await asyncio.sleep(self.poll_interval)

    # ---------------------------------------------------------- doorbells

    async def _block_on_doorbells(self) -> None:
        """Idle path: arm every lane's doorbell word, recheck the rings
        (a commit racing the arm is visible now or rings the level-
        triggered fd), then park on the dedicated drain thread."""
        for lane in self.lanes.values():
            lane.slab.ctrl[C_HUB_WAIT] = 1
        try:
            for lane in self.lanes.values():
                if lane.slab.submit.depth:
                    return
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(self._exec, self._wait_block)
        finally:
            for lane in self.lanes.values():
                lane.slab.ctrl[C_HUB_WAIT] = 0

    def _wait_block(self) -> None:
        """Runs ON the drain thread: block across all lane doorbells +
        the stop doorbell in ~100 ms slices, stamping the hub heartbeat
        each slice so a blocked hub never looks dead to its workers;
        returns on any doorbell, on stop, or after ~1 s housekeeping
        (sooner when churn acks are queued for retry)."""
        lanes = list(self.lanes.values())
        fds = [ln.doorbell.wait_fd for ln in lanes]
        fds.append(self._stop_db.wait_fd)
        bound = _ACK_RETRY_S \
            if any(ln.pending_acks or ln.pending_sem_acks
                   for ln in lanes) else _HOUSEKEEP_S
        deadline = time.monotonic() + bound
        while not self._stop:
            ns = time.monotonic_ns()
            for ln in lanes:
                ln.slab.ctrl[C_HUB_HB] = ns
            remain = deadline - time.monotonic()
            if remain <= 0:
                self.wait_timeouts += 1
                return
            slice_ms = max(int(min(remain, _HB_SLICE_S) * 1000), 1)
            if self._wait_slice(fds, slice_ms):
                self.doorbell_wakeups += 1
                return

    def _wait_slice(self, fds: List[int], timeout_ms: int) -> int:
        """One bounded wait over the doorbell fds; ready fds are
        read-cleared.  Native when the lib is live, select.poll else."""
        if self.drain_mode == "native":
            out = native.drain_wait(fds, timeout_ms)
            if out is not None:
                rc, _mask = out
                return max(rc, 0)
            # lib vanished mid-run (rebuild race): degrade to poll()
            self.drain_mode = "thread"
        p = select.poll()
        for fd in fds:
            p.register(fd, select.POLLIN)
        ready = p.poll(timeout_ms)
        for fd, _ev in ready:
            try:
                os.read(fd, 8)  # eventfd read-clear
            except (BlockingIOError, OSError):
                pass
        return len(ready)

    def _resolve_drain_mode(self) -> str:
        m = self.drain
        if m == "auto":
            m = "native" if native.available() else "thread"
        if m == "native" and native.drain_wait([], 0) is None:
            m = "thread"  # requested native, lib absent: thread fallback
        return m

    # ---------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._stop = False
        self.drain_mode = self._resolve_drain_mode()
        if self.drain_mode in ("native", "thread"):
            self._stop_db = Doorbell()
            self._exec = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="shm-drain"
            )
            if self.pin_cores:
                # pin the drain thread to the first spec'd core (the
                # single worker thread serves every _wait_block call)
                self._exec.submit(_pin_thread, self.pin_cores[0])
        self._task = asyncio.get_event_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stop = True
        if self._stop_db is not None:
            self._stop_db.ring()  # unpark a blocked _wait_block
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        if self._exec is not None:
            self._exec.shutdown(wait=True)
            self._exec = None
        if self._stop_db is not None:
            self._stop_db.close()
            self._stop_db = None
        # drain in-flight reply tasks: their executor collect may still
        # be running; waiting (not just cancelling) keeps slab teardown
        # in close() from racing a result write
        for t in list(self._replies):
            t.cancel()
        if self._replies:
            await asyncio.gather(*self._replies, return_exceptions=True)
        self._replies.clear()

    def close(self, unlink: bool = True) -> None:
        # views must drop either way — a still-mapped slab pins the
        # segment and turns its eventual GC into a BufferError
        for lane in self.lanes.values():
            lane.slab.close()
            if lane.doorbell is not None:
                lane.doorbell.close()
        self.lanes.clear()
        self.reg.close_all(unlink=unlink)

    def lane_stats(self) -> Dict[int, Dict[str, int]]:
        """Per-lane ring health: occupancy of both rings, queued acks,
        and the lane's live filter refcount — the `shm.lane.<i>.*`
        gauges the supervisor exports (and fleet_dump renders)."""
        out: Dict[int, Dict[str, int]] = {}
        for idx, lane in self.lanes.items():
            out[idx] = {
                "submit_depth": lane.slab.submit.depth,
                "result_depth": lane.slab.result.depth,
                "pending_acks": len(lane.pending_acks)
                + len(lane.pending_sem_acks),
                "filters": sum(lane.filters.values()),
                "sem_queries": len(lane.sem_l2h),
            }
        return out

    def stats(self) -> Dict[str, object]:
        fused = sum(n for k, n in self.group_sizes.items() if k > 1)
        out = {
            "lanes": len(self.lanes),
            "ticks": self.match_ticks,
            "groups": self.match_groups,
            "churn_records": self.churn_records,
            "churn_filters": self.churn_filters,
            "reclaims": self.reclaims,
            "res_drops": self.res_drops,
            "ack_sheds": self.ack_sheds,
            "sem_ticks": self.sem_ticks,
            "sem_texts": self.sem_texts,
            "sem_res_drops": self.sem_res_drops,
            "sem_churn": self.sem_churn,
            "sem_queries": (self.semantic.n_queries
                            if self.semantic is not None else 0),
            "errors": self.errors,
            "group_sizes": dict(self.group_sizes),
            "drain_mode": self.drain_mode or self.drain,
            "drain_passes": self.drain_passes,
            "idle_passes": self.idle_passes,
            "doorbell_wakeups": self.doorbell_wakeups,
            "wait_timeouts": self.wait_timeouts,
            "credit_exhausted": self.credit_exhausted,
            "fuse_waits": self.fuse_waits,
            # fused share: dispatches that coalesced >1 tick — the
            # number the adaptive window exists to move
            "fused_share": (fused / self.match_groups
                            if self.match_groups else 0.0),
        }
        if self.hist_drain.count:
            out["drain_cycle_ms"] = self.hist_drain.percentiles_ms()
        return out
