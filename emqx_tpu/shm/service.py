"""Hub-side match service: drains every worker's submit ring on the
hub event loop and feeds the ONE device engine.

The service owns the slabs (created through :class:`ShmRegistry` before
the workers spawn) and runs as a single asyncio task on the hub loop,
so every engine mutation — churn application AND match dispatch — stays
on the loop thread, preserving the engines' single-mutator contract.
Only the device-sync half of a dispatch (`foreign_collect`) runs on the
default executor, mirroring how the broker's own collects block.

Drain is three-phase per pass, preserving each ring's record order:

1. walk every published record per lane; churn/hello records are
   applied to the engine inline (so a match that FOLLOWS a subscribe in
   its own ring is matched against the updated tables);
2. match records from all lanes are grouped by packed geometry (B, L)
   and handed to ``engine.foreign_submit`` in chunks of 4/2/1 — the PR
   12 coalesced-group machinery now fusing ticks from DIFFERENT
   processes into one device call (the flight recorder's `grp` column);
   ``foreign_submit`` copies the slot payloads into its own staging, so
3. every lane's tail advances immediately and the slots recycle while
   the device call is still in flight.

Reclamation: a respawned worker resets its rings and bumps its
generation cell; the service notices the stamp change, drops the dead
incarnation's filter refcounts from the engine, and resyncs cursors.
A full result ring never blocks the hub — the reply is dropped and the
worker's tick times out to its local trie.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..observe.flight import LatencyHistogram
from ..observe.tracepoints import tp
from .registry import ShmRegistry
from .rings import (
    C_HUB_GEN, C_HUB_HB, C_MAGIC, C_CHURN_APPLIED, K_CHURN, K_HELLO,
    K_MATCH, K_CHURN_ACK, K_MATCH_RES, MAGIC, SlabView, slab_bytes,
)

GROUP_SIZES = (4, 2, 1)  # same ladder as the sharded coalescer


class LaneState:
    """One worker's slab plus the hub's bookkeeping for it."""

    __slots__ = ("idx", "slab", "gen", "filters", "res_lk",
                 "pending_acks")

    def __init__(self, idx: int, slab: SlabView):
        self.idx = idx
        self.slab = slab
        self.gen = slab.worker_gen
        # filter -> refcount added by THIS lane (drives reclamation)
        self.filters: Dict[str, int] = {}
        self.res_lk = asyncio.Lock()
        # churn acks that found the result ring full: unlike match
        # results (worker times out to its local trie and retries the
        # next tick), a lost ack would leave the worker's fid mapping
        # un-acked FOREVER, so these retry every drain pass
        self.pending_acks: List[Tuple[int, List[int]]] = []


class _MatchReq:
    __slots__ = ("lane", "tick", "n", "B", "L", "payload", "t_drain",
                 "t_fuse")

    def __init__(self, lane: LaneState, tick: int, n: int, B: int,
                 L: int, payload: np.ndarray, t_drain: int = 0):
        self.lane = lane
        self.tick = tick
        self.n = n
        self.B = B
        self.L = L
        self.payload = payload  # [B, 2L+2] u32 COPY (slot already freed)
        # span-leg stamps (monotonic ns; 0 = the submit was unstamped,
        # i.e. the worker's span plane is disarmed — the reply then
        # ships zero timestamps and the worker records nothing)
        self.t_drain = t_drain
        self.t_fuse = 0


class MatchService:
    """Single hub-side drain loop over all worker lanes."""

    def __init__(self, engine, reg: ShmRegistry, slots: int,
                 slot_bytes: int, poll_interval: float = 0.002):
        self.engine = engine
        self.reg = reg
        self.slots = slots
        self.slot_bytes = slot_bytes
        self.poll_interval = float(poll_interval)
        self.lanes: Dict[int, LaneState] = {}
        # lifecycle state is loop-owned: mutated only here (before the
        # object is shared) and in start()/stop(), which run on the
        # loop (threads reach stop() via run_coroutine_threadsafe)
        self._task: Optional[asyncio.Task] = None  # analysis: owner=loop
        self._replies: set = set()  # in-flight _collect_reply tasks
        self._stop = False  # analysis: owner=loop
        # counters (supervisor mirrors these into broker metrics)
        self.match_ticks = 0
        self.match_groups = 0
        self.churn_records = 0
        self.churn_filters = 0
        self.reclaims = 0
        self.res_drops = 0
        self.errors = 0
        # drain/fusion telemetry (fleet observability plane): the
        # adaptive-fusion controller (ROADMAP item 1) consumes exactly
        # these — how often the drain loop actually turns, and how much
        # cross-lane coalescing each pass achieved
        self.hist_drain = LatencyHistogram()  # drain-cycle gap (s)
        self.group_sizes: Dict[int, int] = {}  # fused group size -> count

    # ------------------------------------------------------------- lanes

    def create_lane(self, idx: int) -> str:
        """Create (or adopt) worker `idx`'s slab; returns the region
        name to hand the worker via its derived config."""
        seg = self.reg.create("lane", idx,
                              slab_bytes(self.slots, self.slot_bytes))
        slab = SlabView(seg, self.slots, self.slot_bytes)
        # fresh hub incarnation for this lane: reset both rings (we are
        # about to become submit-consumer / result-producer), bump the
        # hub generation so an adopted-slab worker re-registers
        slab.submit.reset()
        slab.result.reset()
        slab.ctrl[C_MAGIC] = MAGIC
        slab.ctrl[C_HUB_GEN] += 1
        slab.ctrl[C_CHURN_APPLIED] = 0
        slab.ctrl[C_HUB_HB] = time.monotonic_ns()
        self.lanes[idx] = LaneState(idx, slab)
        return self.reg.names[f"lane{idx}"]

    def _drop_lane_filters(self, lane: LaneState, why: str) -> None:
        # queued acks address the dead incarnation's churn seqs, which
        # a respawn restarts from zero — never deliver them to the new
        # incarnation
        lane.pending_acks.clear()
        n = sum(lane.filters.values())
        for filt, cnt in lane.filters.items():
            for _ in range(cnt):
                try:
                    self.engine.remove_filter(filt)
                except Exception:  # pragma: no cover - engine poisoned
                    self.errors += 1
        lane.filters.clear()
        if n:
            tp("shm.reclaim", lane=lane.idx, filters=n, why=why)

    def _check_worker_gen(self, lane: LaneState) -> None:
        gen = lane.slab.worker_gen
        if gen != lane.gen:
            # worker respawned: it already reset both rings, so every
            # in-flight slot of the dead incarnation is reclaimed here
            self.reclaims += 1
            self._drop_lane_filters(lane, "worker-gen")
            lane.gen = gen

    # ------------------------------------------------------------- churn

    def _apply_churn(self, lane: LaneState, rec) -> None:
        pay = bytes(rec.payload[: rec.a + rec.b])
        adds = pay[: rec.a].decode().split("\0") if rec.a else []
        removes = pay[rec.a:].decode().split("\0") if rec.b else []
        fids: List[int] = []
        for filt in adds:
            try:
                fids.append(int(self.engine.add_filter(filt)))
                lane.filters[filt] = lane.filters.get(filt, 0) + 1
            except Exception:  # pragma: no cover - bad filter string
                self.errors += 1
                fids.append(-1)
        for filt in removes:
            if lane.filters.get(filt, 0) <= 0:
                continue  # not this lane's (stale incarnation record)
            try:
                self.engine.remove_filter(filt)
                lane.filters[filt] -= 1
                if not lane.filters[filt]:
                    del lane.filters[filt]
            except Exception:  # pragma: no cover
                self.errors += 1
        self.churn_records += 1
        self.churn_filters += len(adds) + len(removes)
        lane.slab.ctrl[C_CHURN_APPLIED] = rec.tick
        if adds:
            self._send_ack(lane, rec.tick, fids)
        tp("shm.churn", lane=lane.idx, seq=rec.tick, adds=len(adds),
           removes=len(removes))

    def _send_ack(self, lane: LaneState, seq: int,
                  fids: List[int]) -> None:
        lane.pending_acks.append((seq, fids))
        self._flush_acks(lane)

    def _flush_acks(self, lane: LaneState) -> None:
        """Write queued churn acks in order until the result ring backs
        up; a subscribe burst (bulk add_filters) produces acks faster
        than the worker drains them, and they must all land eventually.
        Bounded: a worker that stops draining its ring entirely sheds
        the oldest acks past 4x ring depth (counted in res_drops) and
        recovers them through a re-register."""
        while lane.pending_acks:
            w = lane.slab.result.reserve()
            if w is None:
                over = len(lane.pending_acks) - 4 * self.slots
                if over > 0:
                    del lane.pending_acks[:over]
                    self.res_drops += over
                return
            seq, fids = lane.pending_acks[0]
            arr = np.asarray(fids, np.int64)
            w.payload_u8(arr.nbytes)[:] = arr.view(np.uint8)
            w.commit(K_CHURN_ACK, seq, a=len(fids), nbytes=arr.nbytes)
            lane.pending_acks.pop(0)

    # ------------------------------------------------------------- drain

    def _drain_once(self) -> Tuple[int, List[_MatchReq]]:
        """Phase 1+3: walk every lane's published records in order,
        applying churn inline and COPYING match payloads, then advance
        the tails so the slots recycle immediately."""
        reqs: List[_MatchReq] = []
        consumed = 0
        # span-leg drain stamp: one clock read per pass, and only when
        # some record actually carries a submit stamp (armed workers)
        now_ns = 0
        for lane in self.lanes.values():
            self._check_worker_gen(lane)
            if lane.pending_acks:  # ring-full leftovers from last pass
                self._flush_acks(lane)
            ring = lane.slab.submit
            k = 0
            while True:
                rec = ring.peek_at(k)
                if rec is None:
                    break
                if rec.gen != (lane.gen & 0xFFFFFFFF):
                    k += 1  # dead incarnation's leftover: skip
                    continue
                if rec.kind == K_HELLO:
                    self._drop_lane_filters(lane, "hello")
                elif rec.kind == K_CHURN:
                    self._apply_churn(lane, rec)
                elif rec.kind == K_MATCH:
                    pay = rec.payload[: rec.nbytes].view(np.uint32)
                    buf = pay.reshape(rec.b, 2 * rec.c + 2).copy()
                    t_drain = 0
                    if rec.ts[0]:
                        if not now_ns:
                            now_ns = time.monotonic_ns()
                        t_drain = now_ns
                    reqs.append(_MatchReq(lane, rec.tick, rec.a,
                                          rec.b, rec.c, buf, t_drain))
                k += 1
            if k:
                ring.advance(k)
                consumed += k
        return consumed, reqs

    def _dispatch(self, reqs: List[_MatchReq]) -> None:
        """Phase 2: group by geometry and fuse cross-worker ticks into
        single engine calls via the foreign-ticket intake."""
        by_geom: Dict[Tuple[int, int], List[_MatchReq]] = {}
        for r in reqs:
            by_geom.setdefault((r.B, r.L), []).append(r)
        loop = asyncio.get_running_loop()
        for members in by_geom.values():
            i = 0
            while i < len(members):
                k = 1
                for g in GROUP_SIZES:
                    if len(members) - i >= g:
                        k = g
                        break
                chunk = members[i:i + k]
                i += k
                if any(r.t_drain for r in chunk):
                    t_fuse = time.monotonic_ns()
                    for r in chunk:
                        if r.t_drain:
                            r.t_fuse = t_fuse
                try:
                    handle = self.engine.foreign_submit(
                        [(r.payload, r.n) for r in chunk]
                    )
                except Exception:  # pragma: no cover - engine poisoned
                    self.errors += 1
                    continue
                self.match_ticks += len(chunk)
                self.match_groups += 1
                self.group_sizes[k] = self.group_sizes.get(k, 0) + 1
                if k > 1:
                    tp("shm.group", k=k,
                       lanes=sorted({r.lane.idx for r in chunk}))
                t = loop.create_task(self._collect_reply(handle, chunk))
                self._replies.add(t)
                t.add_done_callback(self._replies.discard)

    async def _collect_reply(self, handle,
                             chunk: List[_MatchReq]) -> None:
        loop = asyncio.get_running_loop()
        try:
            results = await loop.run_in_executor(
                None, self.engine.foreign_collect, handle
            )
        except Exception:  # pragma: no cover - device fault
            self.errors += 1
            return
        t_done = time.monotonic_ns() \
            if any(r.t_drain for r in chunk) else 0
        for req, (counts, fids) in zip(chunk, results):
            lane = req.lane
            async with lane.res_lk:
                w = lane.slab.result.reserve()
                need = 4 * req.n + 4 * len(fids)
                if w is None or need > lane.slab.result.payload_cap:
                    self.res_drops += 1
                    continue  # worker times out to its local trie
                pay = w.payload_u8(need)
                pay[: 4 * req.n] = np.ascontiguousarray(
                    counts, np.uint32
                ).view(np.uint8)
                if len(fids):
                    pay[4 * req.n:] = np.ascontiguousarray(
                        fids, np.int32
                    ).view(np.uint8)
                # reply stamps ride the result slot's timestamp lane
                # (zeros for an unstamped submit: the worker records
                # legs only when it stamped the submit itself)
                w.commit(K_MATCH_RES, req.tick, a=req.n, nbytes=need,
                         t0=req.t_drain, t1=req.t_fuse,
                         t2=t_done if req.t_drain else 0)

    # -------------------------------------------------------------- loop

    async def _run(self) -> None:
        last_ns = 0
        while not self._stop:
            now = time.monotonic_ns()
            # drain-cycle gap: the cadence the submit rings are
            # actually polled at (back-to-back under load, ~poll_
            # interval idle) — the upper bound any ring_wait leg pays
            if last_ns:
                self.hist_drain.observe((now - last_ns) / 1e9)
            last_ns = now
            for lane in self.lanes.values():
                lane.slab.ctrl[C_HUB_HB] = now
            try:
                consumed, reqs = self._drain_once()
                if reqs:
                    self._dispatch(reqs)
            except Exception:  # pragma: no cover - keep the hub alive
                self.errors += 1
                consumed = 0
            if consumed:
                await asyncio.sleep(0)  # busy: yield and come right back
            else:
                await asyncio.sleep(self.poll_interval)

    def start(self) -> None:
        self._stop = False
        self._task = asyncio.get_event_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stop = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        # drain in-flight reply tasks: their executor collect may still
        # be running; waiting (not just cancelling) keeps slab teardown
        # in close() from racing a result write
        for t in list(self._replies):
            t.cancel()
        if self._replies:
            await asyncio.gather(*self._replies, return_exceptions=True)
        self._replies.clear()

    def close(self, unlink: bool = True) -> None:
        # views must drop either way — a still-mapped slab pins the
        # segment and turns its eventual GC into a BufferError
        for lane in self.lanes.values():
            lane.slab.close()
        self.lanes.clear()
        self.reg.close_all(unlink=unlink)

    def lane_stats(self) -> Dict[int, Dict[str, int]]:
        """Per-lane ring health: occupancy of both rings, queued acks,
        and the lane's live filter refcount — the `shm.lane.<i>.*`
        gauges the supervisor exports (and fleet_dump renders)."""
        out: Dict[int, Dict[str, int]] = {}
        for idx, lane in self.lanes.items():
            out[idx] = {
                "submit_depth": lane.slab.submit.depth,
                "result_depth": lane.slab.result.depth,
                "pending_acks": len(lane.pending_acks),
                "filters": sum(lane.filters.values()),
            }
        return out

    def stats(self) -> Dict[str, object]:
        out = {
            "lanes": len(self.lanes),
            "ticks": self.match_ticks,
            "groups": self.match_groups,
            "churn_records": self.churn_records,
            "churn_filters": self.churn_filters,
            "reclaims": self.reclaims,
            "res_drops": self.res_drops,
            "errors": self.errors,
            "group_sizes": dict(self.group_sizes),
        }
        if self.hist_drain.count:
            out["drain_cycle_ms"] = self.hist_drain.percentiles_ms()
        return out
