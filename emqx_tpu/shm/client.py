"""Worker-side shared-memory match engine (`broker.engine: "shm"`).

Presents the match-engine API the broker/batcher stack expects
(`add_filter` / `remove_filter` / `apply_churn` / `match_submit` /
`match_collect_raw`) but owns NO device planes: a publish tick's fused
prep buffer is packed straight into the submit ring's slot (zero-copy:
`TopicPrep.pack(out_alloc=...)` writes the `[B, 2L+2]` u32 batch into
the slab), the hub's single engine matches it, and raw fid runs come
back through the result ring.  Table bytes in a worker are therefore
O(own subscriptions) — the host-trie mirror below — instead of O(all
tables), which is the whole memory story of the shared plane.

Fid spaces: the worker allocates its OWN local fids (the broker and
sub-shards in this process only ever see local fids), the hub
allocates hub fids; churn acks carry the hub fid for every add and the
client keeps the hub→local map.  A filter whose add has not been acked
yet is served from the local trie (the `pending` union below), closing
the subscribe→hub-apply race without blocking the subscribe path.

Degrade ladder (every step counted + traced):
* result not back within `shm.timeout`, submit ring full, batch too
  big for a slot, or the `shm.submit` fault site fires → THIS tick is
  served from the local trie;
* hub heartbeat stale → every tick serves locally (no per-tick timeout
  tax) until the heartbeat freshens;
* hub generation bump (hub restarted) → rings reset + HELLO + full
  re-register of the local filter set through fresh churn records.

Exact verification is worker-side: hub runs are hash matches only, the
client checks every mapped fid's filter words against the topic (the
hub never sees topic strings).  Deep filters (deeper than the device
level cap) are never device-resident for foreign ticks, so the client
serves its own deep filters from the trie on every tick.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..broker import topic as topiclib
from ..fault import plane as _fault
from ..models.reference import CpuTrieIndex
from ..observe import spans as _spans
from ..observe.flight import PATH_DEVICE, PATH_HOST, LatencyHistogram
from ..observe.tracepoints import tp
from ..ops.prep import TopicPrep
from . import registry
from .doorbell import Doorbell
from .rings import (
    C_HUB_GEN, C_HUB_WAIT, C_SEM, C_WORKER_GEN, K_CHURN, K_CHURN_ACK,
    K_HELLO, K_MATCH, K_MATCH_RES, K_SEM, K_SEM_RES, K_SEMQ, K_SEMQ_ACK,
    SlabView,
)

R_FORCED = 5  # matches models.engine R_FORCED (flight reason code)


class _ShmPending:
    """One in-flight tick: either riding the ring (`tick` set) or
    already decided local (`mode == "local"`)."""

    __slots__ = ("mode", "tick", "topics", "t0", "deadline", "extra",
                 "pipe_occ", "pipe_depth", "t_submit")

    def __init__(self, mode, tick, topics, t0, deadline, extra):
        self.mode = mode  # "shm" | "local"
        self.tick = tick
        self.topics = topics
        self.t0 = t0
        self.deadline = deadline
        self.extra = extra  # local fids to union from the trie
        self.pipe_occ = 0
        self.pipe_depth = 0
        # monotonic-ns submit stamp shipped in the slot header when the
        # span plane is armed (0 disarmed): the reply's hub stamps
        # decompose against this (observe/spans.py shm legs)
        self.t_submit = 0


class _SemPending:
    """One in-flight semantic payload tick riding the K_SEM lane."""

    __slots__ = ("tick", "n", "t0", "deadline")

    def __init__(self, tick: int, n: int, t0: float, deadline: float):
        self.tick = tick
        self.n = n
        self.t0 = t0
        self.deadline = deadline


class ShmMatchEngine:
    """Engine-API front over the per-worker submit/result rings."""

    def __init__(self, space, region: str, slots: int, slot_bytes: int,
                 timeout: float = 0.05, min_batch: int = 64,
                 use_native: bool = True, attach_retry_s: float = 5.0,
                 doorbell_fd: Optional[int] = None,
                 pin_core: Optional[int] = None):
        self.space = space
        # hub-created doorbell inherited through pass_fds: rung after a
        # submit-ring publish, but only when the hub armed C_HUB_WAIT —
        # the flat-out path never pays the write() syscall
        self._db: Optional[Doorbell] = (
            Doorbell.open(doorbell_fd)
            if doorbell_fd is not None and doorbell_fd >= 0 else None
        )
        if pin_core is not None and pin_core >= 0:
            # lane pinning (shm.pin_cores): process-wide — every thread
            # this worker spawns inherits the mask; advisory like the
            # hub's drain-thread pin
            try:
                os.sched_setaffinity(0, {int(pin_core)})
            except (AttributeError, OSError, ValueError):  # pragma: no cover
                pass
        self.verify_matches = True
        self.pipeline_depth = 4  # advisory (the hub owns the window)
        self.flight = None  # node wires a FlightRecorder (or None)
        self.hist_tick = LatencyHistogram()
        self.on_collision = None
        self.on_churn = None  # ckpt WAL hook: hub is registry-of-record
        self.collision_count = 0
        self.churn_shed = 0
        self.prep_degraded = 0
        self.timeout = float(timeout)
        self._prep = TopicPrep(space, min_batch=min_batch,
                               use_native=use_native)
        # end-to-end stamped ring round-trip (submit commit -> result
        # decode): the reconciliation target the four span legs must
        # sum to (bench.py shm-lane attribution gate)
        self.hist_ring = LatencyHistogram()
        # the supervisor creates the slab before spawning us, but a
        # respawn can race a hub restart: retry the attach briefly
        deadline = time.monotonic() + attach_retry_s
        while True:
            try:
                seg = registry.attach(region)
                break
            except FileNotFoundError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.05)  # analysis: allow-blocking(boot-time attach retry — the engine is constructed before the node serves any traffic)
        self._slab = SlabView(seg, slots, slot_bytes)
        # ---- local registry mirror (own filters ONLY) -----------------
        self._lk = threading.RLock()
        self._trie = CpuTrieIndex()
        self._fids: Dict[str, int] = {}
        self._refs: Dict[int, int] = {}
        self._words: Dict[int, List[str]] = {}
        self._filt: Dict[int, str] = {}
        self._free: List[int] = []
        self._next_fid = 0
        self._deep_loc: Set[int] = set()
        self._unacked: Set[int] = set()
        self._hub2loc: Dict[int, int] = {}
        self._loc2hub: Dict[int, int] = {}
        # churn seq -> ordered (filt, local fid) adds awaiting their ack
        self._pending_churn: Dict[int, List[Tuple[str, int]]] = {}
        # churn records the full ring deferred (FIFO, flushed by poll)
        self._unsent: List[Tuple[List[Tuple[str, int]], List[str]]] = []
        self._churn_seq = 0
        self._tick_seq = 0
        self._inflight_n = 0
        # ---- semantic lane (emqx_tpu/semantic/plane.py, shm mode) -----
        # The worker never boots an embedding table: its queries live
        # hub-side, registered through K_SEMQ churn (the filter-churn
        # discipline: unsent queue, per-seq pending adds, ack-built
        # hub<->local qid maps, full replay on hub generation bump).
        self.sem_node = ""  # cluster node name, stamped into K_SEMQ
        self._sem_local: Dict[int, str] = {}  # local qid -> query text
        self._qhub2loc: Dict[int, int] = {}
        self._qloc2hub: Dict[int, int] = {}
        self._pending_semq: Dict[int, List[Tuple[int, str]]] = {}
        self._semq_unsent: List[
            Tuple[List[Tuple[int, str]], List[int]]
        ] = []
        self._semq_seq = 0
        # tick -> raw K_SEM_RES payload bytes (JSON decoded in collect,
        # outside the leaf lock)
        self._sem_results: Dict[int, bytes] = {}
        # tick -> (counts, fids, hub reply ts, t_recv ns) — the last
        # two are zeros when the tick's submit was unstamped
        self._results: Dict[
            int, Tuple[np.ndarray, np.ndarray, Tuple[int, int, int], int]
        ] = {}
        self._res_lk = threading.Lock()  # result-ring drain (any thread)
        self._sub_lk = threading.Lock()  # submit-ring writes
        self._hub_gen = 0
        self._gen = 0
        self._hub_down = False
        # ---- counters (Broker.sync_engine_metrics picks these up) -----
        self.shm_submits = 0
        self.shm_degraded = 0   # submitted but served locally (timeout)
        self.shm_local = 0      # decided local at submit (down/full/big)
        self.shm_oversize = 0
        self.shm_reregisters = 0
        self.sem_submits = 0
        self.sem_degraded = 0   # submitted but no hub reply in time
        self.sem_local = 0      # decided degraded at submit time
        self.sem_oversize = 0
        self._attach()

    # ---------------------------------------------------------- doorbell

    def _ring_hub(self) -> None:
        """Wake the hub's drain thread if (and only if) it is parked:
        the armed word is stored by the hub just before it blocks and
        cleared when it drains, so a busy hub costs no syscall here.  A
        commit racing the arm is covered hub-side (post-arm ring
        recheck + the eventfd being level-triggered)."""
        if self._db is not None and int(self._slab.ctrl[C_HUB_WAIT]):
            self._db.ring()

    # ------------------------------------------------------------ attach

    def _attach(self) -> None:
        """Fresh incarnation handshake: reset both rings (we are the
        submit producer and the result consumer — after a kill -9 the
        hub adopts the zeroed cursors), bump our generation stamp, and
        announce with HELLO so the hub drops the dead incarnation's
        filter refcounts."""
        with self._sub_lk, self._res_lk:
            self._slab.submit.reset()
            self._slab.result.reset()
            self._slab.ctrl[C_WORKER_GEN] += 1
            self._gen = self._slab.worker_gen & 0xFFFFFFFF
            self._hub_gen = self._slab.hub_gen
            self._results.clear()
            self._sem_results.clear()
            w = self._slab.submit.reserve()
            if w is not None:  # ring just reset: cannot actually be full
                w.commit(K_HELLO, self._gen, gen=self._gen)
        self._ring_hub()

    def _reregister(self) -> None:
        """Hub restarted (generation bump): replay the whole local
        filter set — one add per refcount so the hub's counts match —
        through fresh churn records."""
        self.shm_reregisters += 1
        self._attach()
        with self._lk:
            self._hub2loc.clear()
            self._loc2hub.clear()
            self._pending_churn.clear()
            self._unsent.clear()  # the full replay supersedes them
            self._unacked = set(self._refs)
            adds = []
            for filt, fid in self._fids.items():
                adds.extend([(filt, fid)] * self._refs.get(fid, 1))
            self._send_churn(adds, [])
            # replay the semantic query set through fresh K_SEMQ records
            # (the fresh hub has no memory of our qids)
            self._qhub2loc.clear()
            self._qloc2hub.clear()
            self._pending_semq.clear()
            self._semq_unsent.clear()
            if self._sem_local:
                self._send_semq(list(self._sem_local.items()), [])
        tp("shm.reregister", n=len(self._refs))

    # ----------------------------------------------------------- liveness

    def _hub_ok(self) -> bool:
        age = self._slab.hub_heartbeat_age_s(time.monotonic_ns())
        down = age > max(self.timeout, 0.25)
        if down != self._hub_down:
            self._hub_down = down
            tp("shm.degrade", state="hub-down" if down else "hub-up",
               hb_age_s=round(age, 3))
            if down:
                # dedicated stale-transition tracepoint: the node's
                # alarm poll (`shm_hub_degraded`) keys off `hub_down`,
                # this marks the instant for trace correlation
                tp("shm.hub_stale", hb_age_s=round(age, 3))
        return not down

    @property
    def hub_down(self) -> bool:
        """Current hub-heartbeat verdict, re-evaluated on read (one
        control-page load): an IDLE worker would otherwise latch the
        last submit-time verdict and hold the `shm_hub_degraded`
        alarm raised long after the hub recovered.  Reading through
        `_hub_ok` also fires the up/down transition tracepoints at
        the poll that observed the change."""
        return not self._hub_ok()

    def _check_hub_gen(self) -> None:
        if int(self._slab.ctrl[C_HUB_GEN]) != self._hub_gen \
                and self._hub_ok():
            self._reregister()

    # -------------------------------------------------------------- churn

    def _send_churn(self, adds: List[Tuple[str, int]],
                    removes: List[str]) -> None:
        """Queue churn records (bounded chunks) and flush what the ring
        has space for; caller holds self._lk.  A full ring defers
        records in `_unsent` — flushed on the next poll()/submit, in
        order — and the affected fids stay in `_unacked` (served from
        the local trie), so no churn is ever lost, only deferred."""
        CH = 128  # filters per record (bounded payload)
        for i in range(0, max(len(adds), len(removes)), CH):
            a_chunk = adds[i:i + CH]
            r_chunk = removes[i:i + CH]
            if a_chunk or r_chunk:
                self._unsent.append((list(a_chunk), list(r_chunk)))
        self._flush_churn()

    def _flush_churn(self) -> None:
        """Push queued churn records while the submit ring has space
        (caller holds self._lk; FIFO order preserves apply order)."""
        while self._unsent:
            a_chunk, r_chunk = self._unsent[0]
            ab = "\0".join(f for f, _ in a_chunk).encode()
            rb = "\0".join(r_chunk).encode()
            need = len(ab) + len(rb)
            if need > self._slab.submit.payload_cap:
                if len(a_chunk) + len(r_chunk) > 1:  # split and retry
                    ha, hr = len(a_chunk) // 2, len(r_chunk) // 2
                    self._unsent[0:1] = [
                        (a_chunk[:ha or 1], r_chunk[:hr]),
                        (a_chunk[ha or 1:], r_chunk[hr:]),
                    ]
                    continue
                self._unsent.pop(0)  # one slot-sized filter string
                self.churn_shed += 1
                continue
            with self._sub_lk:
                w = self._slab.submit.reserve()
                if w is None:
                    self.churn_shed += 1
                    return  # ring full: retried on next poll/submit
                self._churn_seq += 1
                seq = self._churn_seq
                pay = w.payload_u8(need)
                if ab:
                    pay[:len(ab)] = np.frombuffer(ab, np.uint8)
                if rb:
                    pay[len(ab):need] = np.frombuffer(rb, np.uint8)
                w.commit(K_CHURN, seq, a=len(ab), b=len(rb),
                         nbytes=need, gen=self._gen)
            self._ring_hub()
            self._unsent.pop(0)
            if a_chunk:
                self._pending_churn[seq] = list(a_chunk)

    def add_filter(self, filt: str) -> int:
        with self._lk:
            fid = self._fids.get(filt)
            if fid is not None:
                self._refs[fid] += 1
                self._send_churn([(filt, fid)], [])
                return fid
            fid = self._free.pop() if self._free else self._alloc_fid()
            ws = topiclib.words(filt)
            self._fids[filt] = fid
            self._refs[fid] = 1
            self._words[fid] = ws
            self._filt[fid] = filt
            self._trie.insert(filt, fid)
            plen = len(ws) - (1 if ws and ws[-1] == "#" else 0)
            if plen > self.space.max_levels:
                self._deep_loc.add(fid)
            self._unacked.add(fid)
            self._send_churn([(filt, fid)], [])
            return fid

    def _alloc_fid(self) -> int:
        fid = self._next_fid
        self._next_fid += 1
        return fid

    def add_filters(self, filts: Sequence[str]) -> List[int]:
        return [self.add_filter(f) for f in filts]

    def remove_filter(self, filt: str) -> Optional[int]:
        with self._lk:
            fid = self._fids.get(filt)
            if fid is None:
                return None
            self._refs[fid] -= 1
            self._send_churn([], [filt])
            if self._refs[fid] > 0:
                return None
            del self._refs[fid]
            del self._fids[filt]
            self._trie.delete(filt, fid)
            self._words.pop(fid, None)
            self._filt.pop(fid, None)
            self._deep_loc.discard(fid)
            self._unacked.discard(fid)
            hub = self._loc2hub.pop(fid, None)
            if hub is not None:
                self._hub2loc.pop(hub, None)
            self._free.append(fid)
            return fid

    def apply_churn(self, adds: Sequence[str],
                    removes: Sequence[str]) -> List[int]:
        out = []
        for f in removes:
            self.remove_filter(f)
        for f in adds:
            out.append(self.add_filter(f))
        return out

    def fid_of(self, filt: str) -> Optional[int]:
        with self._lk:
            return self._fids.get(filt)

    def fid_map(self) -> Dict[str, int]:
        with self._lk:
            return dict(self._fids)

    def note_churn_shed(self, n: int = 1) -> None:
        self.churn_shed += n

    # ---------------------------------------------------------- semantic

    def semantic_add(self, lqid: int, text: str) -> None:
        """Register one of THIS worker's semantic queries with the hub
        (K_SEMQ churn).  Until the ack lands the query matches nothing
        hub-side; the plane's own-row exact fallback covers the gap the
        same way `_unacked` filters ride the local trie."""
        with self._lk:
            self._sem_local[lqid] = text
            self._send_semq([(lqid, text)], [])

    def semantic_remove(self, lqid: int) -> None:
        with self._lk:
            if self._sem_local.pop(lqid, None) is None:
                return
            hub = self._qloc2hub.pop(lqid, None)
            if hub is not None:
                self._qhub2loc.pop(hub, None)
            self._send_semq([], [lqid])

    def semantic_hub2loc(self, hub_qid: int) -> Optional[int]:
        with self._lk:
            return self._qhub2loc.get(int(hub_qid))

    def _send_semq(self, adds: List[Tuple[int, str]],
                   removes: List[int]) -> None:
        """Queue semantic query churn (caller holds self._lk); the
        filter-churn discipline: bounded chunks, FIFO, ring-full defers
        to `_semq_unsent` and the next poll()/submit flushes."""
        CH = 64
        for i in range(0, max(len(adds), len(removes)), CH):
            a_chunk = adds[i:i + CH]
            r_chunk = removes[i:i + CH]
            if a_chunk or r_chunk:
                self._semq_unsent.append((list(a_chunk), list(r_chunk)))
        self._flush_semq()

    def _flush_semq(self) -> None:
        """Push queued K_SEMQ records while the submit ring has space
        (caller holds self._lk).  Blob element 0 is this worker's node
        name (c=1) — the hub keys cross-worker forward sections on it."""
        while self._semq_unsent:
            a_chunk, r_chunk = self._semq_unsent[0]
            parts = [self.sem_node]
            parts.extend(f"{lq}\x01{t}" for lq, t in a_chunk)
            parts.extend(str(lq) for lq in r_chunk)
            blob = "\0".join(parts).encode("utf-8", "surrogatepass")
            if len(blob) > self._slab.submit.payload_cap:
                if len(a_chunk) + len(r_chunk) > 1:  # split and retry
                    ha, hr = len(a_chunk) // 2, len(r_chunk) // 2
                    self._semq_unsent[0:1] = [
                        (a_chunk[:ha or 1], r_chunk[:hr]),
                        (a_chunk[ha or 1:], r_chunk[hr:]),
                    ]
                    continue
                self._semq_unsent.pop(0)  # one slot-sized query text
                self.sem_oversize += 1
                continue
            with self._sub_lk:
                w = self._slab.submit.reserve()
                if w is None:
                    return  # ring full: retried on next poll/submit
                self._semq_seq += 1
                seq = self._semq_seq
                pay = w.payload_u8(len(blob))
                pay[:] = np.frombuffer(blob, np.uint8)
                w.commit(K_SEMQ, seq, a=len(a_chunk), b=len(r_chunk),
                         c=1, nbytes=len(blob), gen=self._gen)
            self._ring_hub()
            self._semq_unsent.pop(0)
            if a_chunk:
                self._pending_semq[seq] = list(a_chunk)

    def _apply_sem_ack(self, seq: int,
                       pairs: List[Tuple[int, int]]) -> None:
        with self._lk:
            if self._pending_semq.pop(seq, None) is None:
                return
            for lqid, hub in pairs:
                if lqid in self._sem_local and hub >= 0:
                    self._qhub2loc[hub] = lqid
                    self._qloc2hub[lqid] = hub

    def semantic_active(self) -> bool:
        """Pool-wide live-query count, hub-maintained (C_SEM): a worker
        whose publishes could not match ANY subscriber skips the K_SEM
        tick entirely — the common no-semantic-anywhere case costs one
        control-page load per publish batch."""
        return int(self._slab.ctrl[C_SEM]) > 0

    def semantic_submit(self, texts: Sequence[str]):
        """Ship one batch of embed prefixes to the hub (K_SEM).  None
        means THIS batch must be served by the caller's exact fallback:
        hub down, ring full, blob oversize, or a `shm.sem.submit` fault
        — the match-tick degrade ladder, one rung shorter (no local
        trie to fall to; the plane owns the own-query fallback)."""
        t0 = time.monotonic()
        self._check_hub_gen()
        self.poll()
        a = _fault.inject("shm.sem.submit", err=False) \
            if _fault.enabled() else None
        if (a is not None and a.kind in ("drop", "error", "corrupt")) \
                or not self._hub_ok():
            self.sem_local += 1
            return None
        blob = "\0".join(texts).encode("utf-8", "replace")
        if len(blob) > self._slab.submit.payload_cap:
            self.sem_oversize += 1
            self.sem_local += 1
            return None
        with self._sub_lk:
            w = self._slab.submit.reserve()
            if w is None:
                self.sem_local += 1
                return None
            self._tick_seq += 1
            tick = self._tick_seq
            if blob:
                pay = w.payload_u8(len(blob))
                pay[:] = np.frombuffer(blob, np.uint8)
            w.commit(K_SEM, tick, a=len(texts), nbytes=len(blob),
                     gen=self._gen)
        self._ring_hub()
        self.sem_submits += 1
        return _SemPending(tick, len(texts), t0, t0 + self.timeout)

    def semantic_collect(self, pending: _SemPending):
        """Await the hub's K_SEM_RES for this tick; None on timeout or
        a malformed/short reply (callers degrade to exact own-query
        scoring).  Same drain/leaf-lock contract as `_await_result`."""
        tick = pending.tick
        while True:
            with self._res_lk:
                acks, semacks = self._drain_results()
                raw = self._sem_results.pop(tick, None)
            for ack_tick, ack_fids in acks:
                self._apply_ack(ack_tick, ack_fids)
            for seq, pairs in semacks:
                self._apply_sem_ack(seq, pairs)
            if raw is not None:
                try:
                    res = json.loads(raw.decode("utf-8", "replace"))
                except ValueError:
                    res = None
                if isinstance(res, list) and len(res) == pending.n:
                    return res
                self.sem_degraded += 1
                return None
            now = time.monotonic()
            if now >= pending.deadline or not self._hub_ok():
                # sweep abandoned sem replies alongside match results
                with self._res_lk:
                    if len(self._sem_results) > 4096:
                        self._sem_results.clear()
                self.sem_degraded += 1
                tp("shm.degrade", state="sem-timeout", tick=tick)
                return None
            time.sleep(0.0002)  # analysis: allow-blocking(collect runs on the broker's executor thread — the same blocking-wait contract as match_collect)

    # ------------------------------------------------------------- match

    @property
    def inflight_ticks(self) -> int:
        return self._inflight_n

    @property
    def delta_backlog(self) -> int:
        return len(self._pending_churn)

    @property
    def memo_hits(self) -> int:
        return self._prep.hits

    @property
    def memo_misses(self) -> int:
        return self._prep.misses

    def poll(self) -> None:
        """Opportunistically drain the result ring (results + churn
        acks).  A subscribe-heavy worker that rarely publishes would
        otherwise leave acks parked until its next match, aging
        `_unacked` and risking result-ring backpressure on the hub."""
        with self._res_lk:
            acks, semacks = self._drain_results()
        for ack_tick, ack_fids in acks:
            self._apply_ack(ack_tick, ack_fids)
        for seq, pairs in semacks:
            self._apply_sem_ack(seq, pairs)
        if (self._unsent or self._semq_unsent) and self._hub_ok():
            with self._lk:
                self._flush_churn()
                self._flush_semq()

    def match_submit(self, topics: Sequence[str]) -> _ShmPending:
        t0 = time.monotonic()
        topics = list(topics)
        self._check_hub_gen()
        self.poll()
        with self._lk:
            extra = (self._deep_loc | self._unacked) \
                if (self._deep_loc or self._unacked) else None
        mode = "local"
        tick = 0
        t_sub = 0
        a = _fault.inject("shm.submit", err=False) if _fault.enabled() \
            else None
        faulted = a is not None and a.kind in ("drop", "error", "corrupt")
        if not faulted and self._hub_ok():
            with self._sub_lk:
                w = self._slab.submit.reserve()
                if w is not None:
                    cap32 = self._slab.submit.payload_cap // 4

                    def alloc(B: int, L: int) -> Optional[np.ndarray]:
                        need = B * (2 * L + 2)
                        if need > cap32:
                            return None
                        return w.payload_u32(need).reshape(B, 2 * L + 2)

                    res = self._prep.pack(topics, out_alloc=alloc)
                    if res.key is None:  # packed into the slot: submit
                        self._tick_seq += 1
                        tick = self._tick_seq
                        # span legs: one armed-test per batch; the
                        # stamp rides the slot header's timestamp lane
                        t_sub = time.monotonic_ns() if _spans.armed \
                            else 0
                        w.commit(K_MATCH, tick, a=res.n, b=res.B,
                                 c=res.L,
                                 nbytes=res.B * (2 * res.L + 2) * 4,
                                 gen=self._gen, t0=t_sub)
                        self._ring_hub()
                        mode = "shm"
                        self.shm_submits += 1
                    else:  # batch too deep/wide for a slot
                        self._prep.release(res.buf, res.key)
                        self.shm_oversize += 1
        if mode == "local":
            self.shm_local += 1
        p = _ShmPending(mode, tick, topics, t0,
                        t0 + self.timeout, extra)
        p.t_submit = t_sub
        self._inflight_n += 1
        p.pipe_occ = self._inflight_n
        p.pipe_depth = self.pipeline_depth
        return p

    def match_collect(self, pending: _ShmPending) -> List[Set[int]]:
        return [set(x) for x in self.match_collect_raw(pending)]

    def match_collect_raw(self, pending: _ShmPending) -> List[List[int]]:
        colls0 = self.collision_count
        try:
            out, path = self._collect_serve(pending)
        finally:
            self._inflight_n = max(0, self._inflight_n - 1)
        lat = max(time.monotonic() - pending.t0, 0.0)
        self.hist_tick.observe(lat)
        fl = self.flight
        if fl is not None:
            fl.record(
                n_topics=len(pending.topics),
                n_unique=len(pending.topics), path=path, reason=R_FORCED,
                rate_host=None, rate_dev=None, bytes_up=0, bytes_down=0,
                verify_fail=self.collision_count - colls0,
                churn_slots=0, lat_s=lat, churn_lag_s=0.0,
                pipe_occ=pending.pipe_occ, pipe_depth=pending.pipe_depth,
            )
        return out

    def _collect_serve(
        self, pending: _ShmPending
    ) -> Tuple[List[List[int]], int]:
        if pending.mode == "shm":
            got = self._await_result(pending)
            if got is not None:
                if pending.t_submit:
                    self._observe_legs(pending.t_submit, got[2], got[3])
                return self._serve_hub(pending, got), PATH_DEVICE
            self.shm_degraded += 1
            tp("shm.degrade", state="tick-timeout", tick=pending.tick)
        return self._serve_local(pending.topics), PATH_HOST

    def _observe_legs(self, t_submit: int, ts: Tuple[int, int, int],
                      t_recv: int) -> None:
        """Decompose one stamped ring round-trip into the four shm span
        legs (stage histograms, per tick).  Every boundary clamps at
        zero: the stamps come from one system-wide CLOCK_MONOTONIC, but
        a reply from a pre-stamp hub incarnation ships zeros and is
        skipped wholesale."""
        t_drain, t_fuse, t_done = ts
        if not (t_drain and t_fuse and t_done and t_recv):
            return
        p = _spans.plane()
        p.observe_stage("ring_wait", max(t_drain - t_submit, 0) / 1e9)
        p.observe_stage("fuse_wait", max(t_fuse - t_drain, 0) / 1e9)
        p.observe_stage("device", max(t_done - t_fuse, 0) / 1e9)
        p.observe_stage("scatter", max(t_recv - t_done, 0) / 1e9)
        self.hist_ring.observe(max(t_recv - t_submit, 0) / 1e9)

    def _await_result(self, pending: _ShmPending):
        """Drain the result ring until our tick lands or the deadline
        passes.  May run on any collect thread; the drain itself is
        serialized, the wait spins with a short sleep (the hub's drain
        cadence is sub-millisecond under load)."""
        tick = pending.tick
        while True:
            # _res_lk is a LEAF lock (lock order: _lk -> _sub_lk ->
            # _res_lk): the drain only decodes ring records to plain
            # values; churn acks are applied after release since
            # _apply_ack takes _lk
            with self._res_lk:
                acks, semacks = self._drain_results()
                got = self._results.pop(tick, None)
            for ack_tick, ack_fids in acks:
                self._apply_ack(ack_tick, ack_fids)
            for seq, pairs in semacks:
                self._apply_sem_ack(seq, pairs)
            if got is not None:
                return got
            now = time.monotonic()
            if now >= pending.deadline or not self._hub_ok():
                # sweep expired results occasionally so abandoned ticks
                # (degraded peers) cannot grow the dict without bound
                with self._res_lk:
                    if len(self._results) > 4096:
                        self._results.clear()
                return None
            time.sleep(0.0002)  # analysis: allow-blocking(collect runs on the broker's executor thread — the same blocking-wait contract as the device engines' collect)

    def _drain_results(self) -> Tuple[
        List[Tuple[int, List[int]]],
        List[Tuple[int, List[Tuple[int, int]]]],
    ]:
        """Decode everything on the result ring (caller holds _res_lk).
        Returns (churn acks, semantic query acks) as plain values so the
        caller can apply them after releasing the leaf lock."""
        acks: List[Tuple[int, List[int]]] = []
        semacks: List[Tuple[int, List[Tuple[int, int]]]] = []
        ring = self._slab.result
        while True:
            rec = ring.peek_at(0)
            if rec is None:
                return acks, semacks
            if rec.kind == K_MATCH_RES:
                n = rec.a
                counts = rec.payload[:4 * n].view(np.uint32).astype(
                    np.int64
                )
                total = int(counts.sum())
                fids = rec.payload[4 * n:4 * (n + total)].view(
                    np.int32
                ).copy()
                # t_recv closes the scatter leg; zero when the hub's
                # reply carries no stamps (submit was unstamped)
                t_recv = time.monotonic_ns() if rec.ts[0] else 0
                self._results[rec.tick] = (counts, fids, rec.ts, t_recv)
            elif rec.kind == K_CHURN_ACK:
                acks.append((
                    rec.tick,
                    rec.payload[:8 * rec.a].view(np.int64).tolist(),
                ))
            elif rec.kind == K_SEM_RES:
                # raw bytes only under the leaf lock; JSON decodes in
                # semantic_collect
                self._sem_results[rec.tick] = bytes(
                    rec.payload[:rec.nbytes]
                )
            elif rec.kind == K_SEMQ_ACK:
                blob = bytes(rec.payload[:rec.nbytes]).decode(
                    "utf-8", "replace"
                )
                pairs: List[Tuple[int, int]] = []
                for el in blob.split("\0"):
                    lq, sep, hub = el.partition("\x01")
                    if sep:
                        try:
                            pairs.append((int(lq), int(hub)))
                        except ValueError:
                            pass
                semacks.append((rec.tick, pairs))
            ring.advance()

    def _apply_ack(self, tick: int, hub_fids: List[int]) -> None:
        with self._lk:
            entry = self._pending_churn.pop(tick, None)
            if entry is None:
                return
            for (filt, loc), hub in zip(entry, hub_fids):
                if self._filt.get(loc) == filt and hub >= 0:
                    self._hub2loc[int(hub)] = loc
                    self._loc2hub[loc] = int(hub)
                    self._unacked.discard(loc)

    def _serve_hub(self, pending: _ShmPending, got) -> List[List[int]]:
        counts, fids = got[0], got[1]
        topics = pending.topics
        out: List[List[int]] = []
        off = 0
        with self._lk:
            h2l = self._hub2loc
            words = self._words
            for i, t in enumerate(topics):
                c = int(counts[i]) if i < len(counts) else 0
                row: List[int] = []
                if c:
                    nw = topiclib.words(t)
                    for f in fids[off:off + c].tolist():
                        loc = h2l.get(int(f))
                        if loc is None:
                            continue  # another worker's filter
                        ws = words.get(loc)
                        if ws is None:
                            continue
                        if not self.verify_matches or \
                                topiclib.match_words(nw, ws):
                            row.append(loc)
                        else:
                            self.collision_count += 1
                            if self.on_collision is not None:
                                self.on_collision(t, loc)
                    off += c
                if pending.extra:
                    merged = set(row)
                    merged |= self._trie.match(t) & pending.extra
                    row = list(merged)
                out.append(row)
        return out

    def _serve_local(self, topics: Sequence[str]) -> List[List[int]]:
        with self._lk:
            return [sorted(self._trie.match(t)) for t in topics]

    def match(self, topics: Sequence[str]) -> List[Set[int]]:
        return self.match_collect(self.match_submit(topics))

    def match_one(self, name: str) -> Set[int]:
        return self.match([name])[0]

    # -------------------------------------------------------------- misc

    @property
    def n_filters(self) -> int:
        with self._lk:
            return len(self._fids)

    def stats(self) -> Dict[str, int]:
        return {
            "submits": self.shm_submits,
            "degraded": self.shm_degraded,
            "local": self.shm_local,
            "oversize": self.shm_oversize,
            "reregisters": self.shm_reregisters,
            "filters": self.n_filters,
            "unacked": len(self._unacked),
            "sem_submits": self.sem_submits,
            "sem_degraded": self.sem_degraded,
            "sem_local": self.sem_local,
            "sem_oversize": self.sem_oversize,
        }

    def close(self) -> None:
        self._slab.close()
