"""SPSC rings + control page over one worker's shared-memory slab.

Layout (one slab per worker, created by the hub)::

    [0, 4096)                      control page (u64 cells, below)
    [4096, 4096 + S*slot)          submit ring   (worker writes, hub reads)
    [.., .. + S*slot)              result ring   (hub writes, worker reads)

Every ring is strictly single-producer/single-consumer, so the only
synchronization needed is publication ordering, done seqlock-style per
slot: the writer stamps the slot's seq ODD (`2*head + 1`) when it
reserves, fills header fields + payload, stamps it EVEN (`2*head + 2`)
to publish, THEN advances the shared head cursor.  The reader never
touches a slot whose seq is not exactly `2*tail + 2`, so a producer
killed -9 mid-fill leaves an unpublished slot the reader simply never
sees — reclamation happens wholesale when the respawned producer
resets the ring and bumps its generation stamp (`SlabView.ctrl`),
which is how "a worker killed mid-submit leaks no slots" holds.

All cursors (head/tail for both rings) live in the CONTROL PAGE, not
in either process's Python state: after a kill -9 of either side the
survivor reads the exact cursor state the dead peer left behind, and a
ring reset is a handful of u64 stores visible to both sides.

Aligned 8-byte stores from CPython are effectively atomic on every
platform jax runs on; the seq protocol additionally tolerates torn
header/payload writes (a torn slot is simply never published).

Payloads are numpy views STRAIGHT INTO the slab — the worker's fused
prep op packs its `[B, 2L+2]` u32 batch into the slot with zero copies
and no pickling (`TopicPrep.pack(out_alloc=...)`); the hub copies the
view once into its device staging assembly and the slot recycles as
soon as the tail advances.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

CTRL_BYTES = 4096
SLOT_HDR = 64  # one cache line: u64 seq + u32 gen/kind/tick/a/b/c/nbytes
#                + 3x u64 monotonic-ns span timestamps (offset 40)

# Slot-header timestamp lane (observe/spans.py shm legs): the header's
# spare bytes carry up to three CLOCK_MONOTONIC nanosecond stamps —
# system-wide on Linux, so hub and worker clocks compare directly.
# Submit records use ts[0] = worker submit time; result records carry
# ts[0..2] = hub drain / fuse / device-done.  Zero = unstamped (the
# span plane disarmed): commit always writes all three cells so a
# recycled slot can never leak a stale stamp into a fresh record.
SLOT_TS = 3
_TS_OFF = 40  # after u64 seq (8) + 7x u32 (28) + 4 pad for u64 align

# control-page u64 cell indices
C_MAGIC = 0
C_HUB_GEN = 1        # hub bumps on (re)attach; workers re-register on change
C_HUB_HB = 2         # hub heartbeat, monotonic ns (worker death detector)
C_WORKER_GEN = 3     # worker bumps on (re)attach; hub reclaims on change
C_SUB_HEAD = 4       # submit ring: worker-owned producer cursor
C_SUB_TAIL = 5       # submit ring: hub-owned consumer cursor
C_RES_HEAD = 6       # result ring: hub-owned producer cursor
C_RES_TAIL = 7       # result ring: worker-owned consumer cursor
C_CHURN_APPLIED = 8  # highest worker churn seq the hub has applied
C_HUB_WAIT = 9       # doorbell armed word: hub stores 1 before blocking on
#                      the lane's eventfd, 0 while actively draining — the
#                      worker only pays the wakeup write() syscall when the
#                      hub is (about to be) asleep.  The hub re-checks the
#                      rings AFTER arming, so a commit that races the store
#                      is either seen by that re-check or rings the
#                      level-triggered fd before poll() parks.
C_SEM = 10           # hub-maintained POOL-WIDE live semantic-query count,
#                      mirrored into every lane's control page: a worker
#                      skips shipping K_SEM payload ticks entirely while
#                      it reads 0 (no subscriber anywhere could match)

MAGIC = 0x45545055_00000001  # "ETPU" | layout version

# record kinds (submit ring: MATCH/CHURN/HELLO/SEM/SEMQ;
#               result ring: ACK/RES/SEM_RES/SEMQ_ACK)
K_MATCH = 1      # a=n live topics, b=B, c=L, payload=[B, 2L+2] u32
K_CHURN = 2      # tick=churn seq, a=len(adds blob), b=len(removes blob)
K_HELLO = 3      # fresh worker incarnation: hub drops its old filters
K_CHURN_ACK = 4  # tick=churn seq, a=n add fids, payload=i64 fids
K_MATCH_RES = 5  # tick=tick id, a=n, payload=u32 counts[n] + i32 fids
K_SEM = 6        # semantic payload tick: tick=tick id, a=n texts,
#                  payload=NUL-separated utf-8 embed prefixes
K_SEM_RES = 7    # tick=tick id, a=n, payload=json per-text match
#                  records ({"own": [hub qids], "rem": {node: [qids]}})
K_SEMQ = 8       # semantic query churn: tick=semq seq, a=n adds,
#                  b=n removes, payload=NUL blob ("lqid\x01text" adds
#                  first, then "lqid" removes); c=1 marks the record as
#                  carrying the worker's node name as blob element 0
K_SEMQ_ACK = 9   # tick=semq seq, a=n adds, payload=NUL blob of
#                  "lqid\x01hubqid" pairs (worker builds hub->local map)


def slab_bytes(slots: int, slot_bytes: int) -> int:
    return CTRL_BYTES + 2 * slots * slot_bytes


class Rec:
    """One published record, viewed in place (reader side).  `payload`
    aliases the slab — copy anything that outlives the tail advance."""

    __slots__ = ("gen", "kind", "tick", "a", "b", "c", "nbytes",
                 "payload", "ts")

    def __init__(self, gen, kind, tick, a, b, c, nbytes, payload,
                 ts=(0, 0, 0)):
        self.gen = gen
        self.kind = kind
        self.tick = tick
        self.a = a
        self.b = b
        self.c = c
        self.nbytes = nbytes
        self.payload = payload
        self.ts = ts  # (t0, t1, t2) monotonic ns; 0 = unstamped


class Slot:
    """A reserved (unpublished) slot, writer side.  Fill the payload
    through `payload_u8`/`payload_u32`, then `commit` publishes."""

    __slots__ = ("_ring", "_i", "_head")

    def __init__(self, ring: "RingView", i: int, head: int):
        self._ring = ring
        self._i = i
        self._head = head

    def payload_u8(self, nbytes: int) -> np.ndarray:
        return self._ring._pay[self._i][:nbytes]

    def payload_u32(self, count: int) -> np.ndarray:
        return self._ring._pay[self._i][: count * 4].view(np.uint32)

    def commit(self, kind: int, tick: int, a: int = 0, b: int = 0,
               c: int = 0, nbytes: int = 0, gen: int = 0,
               t0: int = 0, t1: int = 0, t2: int = 0) -> None:
        r = self._ring
        h = r._hdr[self._i]
        h[0] = gen & 0xFFFFFFFF
        h[1] = kind
        h[2] = tick & 0xFFFFFFFF
        h[3] = a
        h[4] = b
        h[5] = c
        h[6] = nbytes
        t = r._ts[self._i]
        t[0] = t0
        t[1] = t1
        t[2] = t2
        r._seq[self._i][0] = 2 * self._head + 2  # publish
        r._ctrl[r._hi] = self._head + 1


class RingView:
    """One SPSC ring over a slab slice; cursors live in the control
    page so they survive either side's death."""

    def __init__(self, buf, base: int, slots: int, slot_bytes: int,
                 ctrl: np.ndarray, head_idx: int, tail_idx: int):
        self.slots = slots
        self.slot_bytes = slot_bytes
        self.payload_cap = slot_bytes - SLOT_HDR
        self._ctrl = ctrl
        self._hi = head_idx
        self._ti = tail_idx
        self._seq: List[np.ndarray] = []
        self._hdr: List[np.ndarray] = []
        self._ts: List[np.ndarray] = []
        self._pay: List[np.ndarray] = []
        for i in range(slots):
            off = base + i * slot_bytes
            self._seq.append(np.frombuffer(buf, np.uint64, 1, off))
            self._hdr.append(np.frombuffer(buf, np.uint32, 7, off + 8))
            self._ts.append(
                np.frombuffer(buf, np.uint64, SLOT_TS, off + _TS_OFF)
            )
            self._pay.append(
                np.frombuffer(buf, np.uint8, self.payload_cap,
                              off + SLOT_HDR)
            )

    # ------------------------------------------------------------ cursors

    @property
    def head(self) -> int:
        return int(self._ctrl[self._hi])

    @property
    def tail(self) -> int:
        return int(self._ctrl[self._ti])

    @property
    def depth(self) -> int:
        return max(self.head - self.tail, 0)

    def reset(self) -> None:
        """Producer-side wholesale reset (fresh incarnation): zero the
        cursors and every slot seq so no stale publication survives."""
        self._ctrl[self._hi] = 0
        self._ctrl[self._ti] = 0
        for s in self._seq:
            s[0] = 0

    # ------------------------------------------------------------- writer

    def reserve(self) -> Optional[Slot]:
        head = self.head
        if head - self.tail >= self.slots:
            return None  # full: caller degrades (never blocks)
        i = head % self.slots
        self._seq[i][0] = 2 * head + 1  # mark mid-write (seqlock odd)
        return Slot(self, i, head)

    # ------------------------------------------------------------- reader

    def peek_at(self, k: int = 0) -> Optional[Rec]:
        """View the record at tail+k without consuming; None when not
        (yet) published.  k>0 lets the hub decode a whole drain batch
        before advancing the tail in order."""
        pos = self.tail + k
        if pos >= self.head:
            return None
        i = pos % self.slots
        if int(self._seq[i][0]) != 2 * pos + 2:
            return None  # mid-write or stale incarnation: not published
        h = self._hdr[i]
        t = self._ts[i]
        return Rec(int(h[0]), int(h[1]), int(h[2]), int(h[3]), int(h[4]),
                   int(h[5]), int(h[6]), self._pay[i],
                   (int(t[0]), int(t[1]), int(t[2])))

    def advance(self, k: int = 1) -> None:
        self._ctrl[self._ti] += k


class SlabView:
    """Typed views over one worker's slab: control page + both rings.

    The same class serves both sides — which ring a process writes is a
    matter of discipline (worker: submit producer / result consumer;
    hub: the mirror image), matching the SPSC contract above.
    """

    def __init__(self, seg, slots: int, slot_bytes: int):
        if slot_bytes % 64 or slot_bytes <= SLOT_HDR:
            raise ValueError(
                f"shm.slot_bytes must be a 64-byte multiple > {SLOT_HDR}"
                f" (got {slot_bytes})"
            )
        need = slab_bytes(slots, slot_bytes)
        if seg.size < need:
            raise ValueError(
                f"shm slab too small: {seg.size} < {need} "
                f"(slots={slots}, slot_bytes={slot_bytes})"
            )
        self.seg = seg
        self.slots = slots
        self.slot_bytes = slot_bytes
        buf = seg.buf
        self.ctrl = np.frombuffer(buf, np.uint64, CTRL_BYTES // 8, 0)
        self.submit = RingView(buf, CTRL_BYTES, slots, slot_bytes,
                               self.ctrl, C_SUB_HEAD, C_SUB_TAIL)
        self.result = RingView(buf, CTRL_BYTES + slots * slot_bytes,
                               slots, slot_bytes, self.ctrl,
                               C_RES_HEAD, C_RES_TAIL)

    # generation / liveness cells -------------------------------------

    @property
    def hub_gen(self) -> int:
        return int(self.ctrl[C_HUB_GEN])

    @property
    def worker_gen(self) -> int:
        return int(self.ctrl[C_WORKER_GEN])

    def hub_heartbeat_age_s(self, now_ns: int) -> float:
        hb = int(self.ctrl[C_HUB_HB])
        if hb == 0:
            return float("inf")
        return max(now_ns - hb, 0) / 1e9

    def close(self) -> None:
        # numpy views pin the exported buffer; drop them before close
        self.ctrl = None
        self.submit = None
        self.result = None
        try:
            self.seg.close()
        except BufferError:  # pragma: no cover - a view still live
            pass
