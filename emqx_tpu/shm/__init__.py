"""Shared-memory match plane (PR 14): ONE device engine serving the
wire-worker pool over zero-copy prep rings.

PR 13 gave every wire worker its own full match engine — filter tables
duplicated per process, churn bookkeeping run N times, and the single
device plane the paper is about serving exactly one process.  This
package moves matching behind the hub: each worker packs its publish
tick's `[B, 2L+2]` u32 prep buffer (the PR 12 fused prep op) DIRECTLY
into a per-worker `multiprocessing.shared_memory` slab (SPSC submit
ring, seqlock'd slot headers, no pickling), the hub's `MatchService`
drains every worker ring on its event loop and rides the coalesced
group dispatch so ticks from DIFFERENT workers fuse into one device
call, and raw fid runs scatter back through per-worker result rings.
Exact verification stays worker-side (the hub never sees topic
strings); subscribe/unsubscribe crosses the same rings as churn
records applied once by the hub engine, the registry-of-record.

Degrade story: every worker keeps a lib-less host-trie mirror of its
OWN filters (memory O(own subs), not O(all tables)) and serves from it
past `shm.timeout`, on hub death (heartbeat goes stale), or when the
`shm.submit` fault site fires.  Ring slots are generation-stamped so a
kill -9 of either side reclaims cleanly: a respawned worker resets its
rings and bumps its generation (the hub drops the dead incarnation's
filters and cursors), a restarted hub bumps its generation (workers
re-register their filters through a fresh churn stream).

The `tools/analysis` proc-boundary pass blesses THIS package as the
one allowed cross-process crossing: `multiprocessing.shared_memory`
anywhere else in the package is an error, and region names must come
from :mod:`registry` (no ad-hoc names).
"""

from .client import ShmMatchEngine  # noqa: F401
from .registry import ShmRegistry, region_name  # noqa: F401
from .rings import (  # noqa: F401
    K_CHURN, K_CHURN_ACK, K_HELLO, K_MATCH, K_MATCH_RES,
    SlabView, slab_bytes,
)
from .service import MatchService  # noqa: F401
