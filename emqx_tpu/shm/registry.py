"""Shared-memory region-name registry — the ONLY module allowed to
construct `multiprocessing.shared_memory.SharedMemory`.

Region names are a cross-process protocol surface: a typo'd or ad-hoc
name silently attaches two sides to different segments and every read
sees zeros, which is why the static-analysis gate (`tools/analysis`)
errors on any `SharedMemory(...)` constructor outside this file.  All
names derive from one scope string (the hub's wire IPC directory, a
per-node-instance path) through :func:`region_name`, so two broker
instances on one host can never collide and a respawned hub finds its
own stale segments to adopt.

Ownership: the HUB creates and unlinks segments (`ShmRegistry`);
workers only :func:`attach`.  Attachers are unregistered from the
CPython resource tracker — otherwise a worker exit would unlink the
hub's live segment out from under the pool (the 3.10 tracker treats
every opener as an owner).
"""

from __future__ import annotations

import hashlib
from multiprocessing import shared_memory
from typing import Dict, List


def region_name(scope: str, kind: str, idx: int) -> str:
    """Canonical region name: `etpu_<scope-digest>_<kind><idx>`.

    The digest keys the hub instance (scope = its wire IPC dir), the
    (kind, idx) pair keys the segment within it — short enough for any
    platform's shm name limit, unique per node instance on the host.
    """
    digest = hashlib.sha1(scope.encode("utf-8", "replace")).hexdigest()[:12]
    return f"etpu_{digest}_{kind}{idx}"


def _untrack(seg: shared_memory.SharedMemory) -> None:
    """Drop the segment from the resource tracker: the caller attaches
    to a hub-owned segment and must not unlink it at process exit."""
    try:  # pragma: no cover - tracker layout is a CPython internal
        from multiprocessing import resource_tracker

        resource_tracker.unregister(
            getattr(seg, "_name", "/" + seg.name), "shared_memory"
        )
    except Exception:
        pass


def attach(name: str) -> shared_memory.SharedMemory:
    """Open an existing hub-owned segment (worker side, non-owning)."""
    seg = shared_memory.SharedMemory(name=name)
    _untrack(seg)
    return seg


class ShmRegistry:
    """Hub-side owner of every segment for one node instance.

    `create` adopts (or recreates, on a size mismatch) a stale segment
    left by a kill -9'd previous incarnation of the same scope, so a
    hub restart reuses the names its respawned workers were given.
    """

    def __init__(self, scope: str):
        self.scope = scope
        self._owned: List[shared_memory.SharedMemory] = []
        self.names: Dict[str, str] = {}  # "<kind><idx>" -> region name

    def create(self, kind: str, idx: int,
               size: int) -> shared_memory.SharedMemory:
        name = region_name(self.scope, kind, idx)
        try:
            seg = shared_memory.SharedMemory(
                name=name, create=True, size=size
            )
        except FileExistsError:
            # stale segment from a previous incarnation of this scope:
            # adopt when the geometry still fits, else recreate
            seg = shared_memory.SharedMemory(name=name)
            if seg.size < size:
                seg.unlink()
                seg.close()
                seg = shared_memory.SharedMemory(
                    name=name, create=True, size=size
                )
        self._owned.append(seg)
        self.names[f"{kind}{idx}"] = name
        return seg

    def close_all(self, unlink: bool = True) -> None:
        for seg in self._owned:
            if unlink:
                # re-register first: when an attacher shares this
                # process (in-process tests), its _untrack already
                # removed the tracker cache entry and unlink's own
                # unregister would make the tracker daemon complain
                try:  # pragma: no cover - tracker is a CPython internal
                    from multiprocessing import resource_tracker

                    resource_tracker.register(
                        getattr(seg, "_name", "/" + seg.name),
                        "shared_memory",
                    )
                except Exception:
                    pass
                try:
                    seg.unlink()
                except FileNotFoundError:  # pragma: no cover - racing rm
                    pass
            try:
                seg.close()
            except BufferError:  # pragma: no cover - live views remain
                pass
        self._owned.clear()
        self.names.clear()
