"""Doorbell primitive for the shm match plane (hub wakeup on commit).

One eventfd per lane, created HUB-SIDE next to the lane's slab and
handed to the worker subprocess through ``pass_fds`` (fd numbers are
preserved across fork+exec, so the integer in the derived config is the
fd in the child).  The worker rings it after publishing a submit-ring
record *when the hub has armed the lane* (``C_HUB_WAIT`` ctrl word) and
the hub's drain thread blocks in one poll(2) across every lane fd —
see ``native/drain.cc`` and ``MatchService``.

eventfd is level-triggered for poll: a ring that lands between the
hub's post-arm recheck and its poll() entry still wakes it.  The
counter is read-cleared by the waiter; rings are coalesced by the
kernel (the counter just accumulates), so a flooding worker costs one
wakeup, not one per commit.

Hosts without ``os.eventfd`` (non-Linux; Python < 3.10) fall back to a
self-pipe — same poll semantics, one byte per ring, drained in bulk.

The ``tools/analysis`` shm-blessing pass pins eventfd construction to
this package, the same discipline as the SharedMemory ctor lint: a
doorbell anywhere else is a new unaudited cross-process channel.
"""

from __future__ import annotations

import os
from typing import Optional

_HAS_EVENTFD = hasattr(os, "eventfd")


class Doorbell:
    """One wakeup channel: ``ring()`` on the producer side, ``fd`` given
    to poll/``etpu_drain_wait`` and ``clear()`` on the waiter side.

    ``Doorbell()`` creates the underlying eventfd (hub side, one per
    lane); ``Doorbell.open(fd)`` wraps an inherited fd (worker side) —
    the wrap does NOT own a pipe read end, so ``close()`` on the open
    side closes only what it was given.
    """

    __slots__ = ("fd", "_rd", "_owned")

    def __init__(self, fd: Optional[int] = None, rd: Optional[int] = None,
                 _create: bool = True):
        if not _create:
            self.fd = fd  # type: ignore[assignment]
            self._rd = rd if rd is not None else fd
            self._owned = False
            return
        if _HAS_EVENTFD:
            self.fd = os.eventfd(0, os.EFD_NONBLOCK | os.EFD_CLOEXEC)
            self._rd = self.fd  # eventfd: one fd, both directions
        else:  # pragma: no cover - non-Linux fallback
            r, w = os.pipe()
            os.set_blocking(r, False)
            os.set_blocking(w, False)
            self.fd = w       # producer writes here
            self._rd = r      # waiter polls/drains here
        self._owned = True

    @classmethod
    def open(cls, fd: int) -> "Doorbell":
        """Wrap an inherited doorbell fd (worker side, from pass_fds)."""
        return cls(fd=fd, _create=False)

    @property
    def wait_fd(self) -> int:
        """The fd the waiter polls (== ``fd`` for eventfd)."""
        return self._rd

    def ring(self) -> None:
        """Producer-side wakeup; never blocks, never raises on a dead
        waiter (the degrade ladder owns that detection)."""
        try:
            if _HAS_EVENTFD:
                os.eventfd_write(self.fd, 1)
            else:  # pragma: no cover - non-Linux fallback
                os.write(self.fd, b"\x01")
        except (OSError, ValueError):
            pass  # full pipe / closed fd: the wakeup is already pending

    def clear(self) -> None:
        """Waiter-side read-clear (the native path clears inline)."""
        try:
            if _HAS_EVENTFD:
                os.eventfd_read(self._rd)
            else:  # pragma: no cover - non-Linux fallback
                while os.read(self._rd, 512):
                    pass
        except (BlockingIOError, OSError, ValueError):
            pass

    def close(self) -> None:
        if not self._owned:
            return
        try:
            os.close(self.fd)
        except OSError:
            pass
        if self._rd != self.fd:  # pragma: no cover - pipe fallback
            try:
                os.close(self._rd)
            except OSError:
                pass
        self._owned = False
