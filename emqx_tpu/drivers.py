"""Database driver registry — the ecpool/epgsql/eredis/mongodb seam.

The reference reaches MySQL/PgSQL/MongoDB/Redis/LDAP through pooled
Erlang client deps (`rebar.config` ecpool/epgsql/eredis/...;
`apps/emqx_connector/src/emqx_connector_{mysql,pgsql,redis,mongo}.erl`).

**Redis and PostgreSQL ship as REAL bundled drivers** (`bridges/redis.py`:
RESP wire protocol, the eredis analog; `bridges/pgsql.py`: protocol v3
with MD5/SCRAM auth + extended queries, the epgsql analog — both pooled
over stdlib sockets).  The other kinds have no client library in this
image, so the framework ships the *contract* and an injection point:

* a deployment registers a factory per kind —
  ``register_driver("mysql", lambda **cfg: MyAdapter(cfg))`` — wrapping
  whatever client library it has (aiomysql, asyncpg, redis-py, ...);
* authn/authz/bridge components resolve drivers by kind at create time
  and fail loudly when no driver is registered (matching the previous
  explicit-unavailable behavior);
* tests register in-memory fakes, which doubles as the contract spec.

Driver contract (duck-typed; sync because the authn/authz hook chains
run synchronously in the channel — wrap async clients accordingly):

    start() -> None              optional; open pools
    stop() -> None               optional; close pools
    health_check() -> bool       liveness probe (resource manager)
    query(statement: str, params: dict) -> List[dict]
        SQL-flavored kinds: rows as dicts keyed by column name.
        The ${var} placeholders of the reference's query templates are
        passed through in `params` (username, clientid, peerhost, ...)
        so the driver can bind them safely.
    command(*args) -> Any
        Command-flavored kinds (redis: ("HGETALL", key), mongo runs
        find filters, ldap binds) — shape is kind-specific.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

DB_KINDS = ("mysql", "pgsql", "mongodb", "redis", "ldap")

_registry: Dict[str, Callable[..., Any]] = {}


def _redis_factory(**cfg):
    from .bridges.redis import RedisDriver

    return RedisDriver(**cfg)


def _pgsql_factory(**cfg):
    from .bridges.pgsql import PgDriver

    return PgDriver(**cfg)


# Kinds with a REAL bundled implementation (stdlib wire protocol, no
# external client library).  register_driver() overrides them; the
# remaining kinds stay injection points until a client is registered.
_builtin: Dict[str, Callable[..., Any]] = {
    "redis": _redis_factory,
    "pgsql": _pgsql_factory,
}


class DriverUnavailable(NotImplementedError):
    pass


def register_driver(kind: str, factory: Callable[..., Any]) -> None:
    """Install a driver factory for `kind` (overwrites any previous)."""
    _registry[kind] = factory


def unregister_driver(kind: str) -> None:
    """Remove an injected factory (built-in drivers are restored)."""
    _registry.pop(kind, None)


def driver_available(kind: str) -> bool:
    return kind in _registry or kind in _builtin


def make_driver(kind: str, **cfg) -> Any:
    factory = _registry.get(kind) or _builtin.get(kind)
    if factory is None:
        raise DriverUnavailable(
            f"{kind} driver not registered: this environment ships no "
            f"{kind} client — register one via "
            f"emqx_tpu.drivers.register_driver({kind!r}, factory)"
        )
    return factory(**cfg)


def render_template(template: str, params: Dict[str, str]) -> str:
    """Substitute ${var} placeholders (redis keys, mongo filters)."""
    for k, v in params.items():
        template = template.replace("${" + k + "}", v)
    return template


def render_vars(clientinfo, extra: Optional[Dict[str, str]] = None
                ) -> Dict[str, str]:
    """The ${var} binding set of the reference's authn/authz templates
    (emqx_authn_mysql: ${username}/${clientid}/${peerhost}/...)."""
    out = {
        "username": clientinfo.username or "",
        "clientid": clientinfo.clientid or "",
        "peerhost": (clientinfo.peerhost or "").split(":")[0],
    }
    if extra:
        out.update(extra)
    return out
