"""Database driver registry — the ecpool/epgsql/eredis/mongodb seam.

The reference reaches MySQL/PgSQL/MongoDB/Redis/LDAP through pooled
Erlang client deps (`rebar.config` ecpool/epgsql/eredis/...;
`apps/emqx_connector/src/emqx_connector_{mysql,pgsql,redis,mongo}.erl`).

**All five kinds ship as REAL bundled wire-protocol drivers**, pooled
over stdlib sockets (`bridges/dbpool.py`, the ecpool analog):

* redis — RESP (`bridges/redis.py`, the eredis analog);
* pgsql — protocol v3, MD5/SCRAM auth, extended queries
  (`bridges/pgsql.py`, the epgsql analog);
* mysql — v10 handshake, native/caching_sha2 auth, COM_QUERY
  (`bridges/mysql.py`, the mysql-otp analog);
* mongodb — OP_MSG + BSON, SCRAM-SHA-256 (`bridges/mongo.py`);
* ldap — LDAPv3 BER bind/search (`bridges/ldap.py`, the eldap analog).

The registry stays an injection point on top of the builtins:
``register_driver(kind, factory)`` overrides a bundled driver with a
site's own client library (aiomysql, asyncpg, redis-py, ...), and
tests register in-memory fakes, which doubles as the contract spec.

Driver contract (duck-typed; sync because the authn/authz hook chains
run synchronously in the channel — wrap async clients accordingly):

    start() -> None              optional; open pools
    stop() -> None               optional; close pools
    health_check() -> bool       liveness probe (resource manager)
    query(statement: str, params: dict) -> List[dict]
        SQL-flavored kinds: rows as dicts keyed by column name.
        The ${var} placeholders of the reference's query templates are
        passed through in `params` (username, clientid, peerhost, ...)
        so the driver can bind them safely.
    command(*args) -> Any
        Command-flavored kinds (redis: ("HGETALL", key), mongo runs
        find filters, ldap binds) — shape is kind-specific.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from .utils.net import peer_host as _peer_host

DB_KINDS = ("mysql", "pgsql", "mongodb", "redis", "ldap")

_registry: Dict[str, Callable[..., Any]] = {}


def _redis_factory(**cfg):
    from .bridges.redis import RedisDriver

    return RedisDriver(**cfg)


def _pgsql_factory(**cfg):
    from .bridges.pgsql import PgDriver

    return PgDriver(**cfg)


def _mysql_factory(**cfg):
    from .bridges.mysql import MySqlDriver

    return MySqlDriver(**cfg)


def _mongodb_factory(**cfg):
    from .bridges.mongo import MongoDriver

    return MongoDriver(**cfg)


def _ldap_factory(**cfg):
    from .bridges.ldap import LdapDriver

    return LdapDriver(**cfg)


# Kinds with a REAL bundled implementation (stdlib wire protocol, no
# external client library).  register_driver() overrides them; the
# remaining kinds stay injection points until a client is registered.
_builtin: Dict[str, Callable[..., Any]] = {
    "redis": _redis_factory,
    "pgsql": _pgsql_factory,
    "mysql": _mysql_factory,
    "mongodb": _mongodb_factory,
    "ldap": _ldap_factory,
}


class DriverUnavailable(NotImplementedError):
    pass


def register_driver(kind: str, factory: Callable[..., Any]) -> None:
    """Install a driver factory for `kind` (overwrites any previous)."""
    _registry[kind] = factory


def unregister_driver(kind: str) -> None:
    """Remove an injected factory (built-in drivers are restored)."""
    _registry.pop(kind, None)


def driver_available(kind: str) -> bool:
    return kind in _registry or kind in _builtin


def make_driver(kind: str, **cfg) -> Any:
    factory = _registry.get(kind) or _builtin.get(kind)
    if factory is None:
        raise DriverUnavailable(
            f"{kind} driver not registered: this environment ships no "
            f"{kind} client — register one via "
            f"emqx_tpu.drivers.register_driver({kind!r}, factory)"
        )
    return factory(**cfg)


def render_template(template: str, params: Dict[str, str]) -> str:
    """Substitute ${var} placeholders (redis keys, mongo filters)."""
    for k, v in params.items():
        template = template.replace("${" + k + "}", v)
    return template


def render_vars(clientinfo, extra: Optional[Dict[str, str]] = None
                ) -> Dict[str, str]:
    """The ${var} binding set of the reference's authn/authz templates
    (emqx_authn_mysql: ${username}/${clientid}/${peerhost}/...)."""
    out = {
        "username": clientinfo.username or "",
        "clientid": clientinfo.clientid or "",
        "peerhost": _peer_host(clientinfo.peerhost),
    }
    if extra:
        out.update(extra)
    return out
