"""Anonymized usage telemetry — `emqx_modules/src/emqx_telemetry.erl` analog.

Builds the same report shape as the reference (uuid, version, os info,
uptime, active plugins/modules, client count, message counters
`emqx_telemetry.erl:301-314`), persists a stable node UUID, and reports
on a long interval (the reference uses 7 days).  Transport is a
pluggable callback — this environment has zero egress, so the default
reporter only logs; operators can opt out entirely (`enable=False`),
matching the reference's disable API.
"""

from __future__ import annotations

import json
import logging
import os
import platform
import time
import uuid as uuidlib
from typing import Callable, List, Optional

log = logging.getLogger("emqx_tpu.telemetry")

REPORT_INTERVAL = 7 * 86400.0  # seconds, like ?REPORT_INTERVAR

VERSION = "0.1.0"


class Telemetry:
    def __init__(self, broker=None, enable: bool = True,
                 uuid_path: Optional[str] = None,
                 reporter: Optional[Callable[[dict], None]] = None,
                 plugins=None):
        self.broker = broker
        self.enable = enable
        self.plugins = plugins
        self.reporter = reporter or (lambda rep: log.info(
            "telemetry report (not sent, no egress): %s",
            json.dumps(rep)[:512]))
        self._uuid_path = uuid_path
        self.uuid = self._load_or_create_uuid()
        self._started_at = time.time()
        self._last_report: Optional[dict] = None
        self._next_report_at = time.time() + REPORT_INTERVAL

    def _load_or_create_uuid(self) -> str:
        # one-shot boot-time IO on a <64-byte uuid file, before the node
        # serves traffic; not worth an executor hop
        if self._uuid_path and os.path.exists(self._uuid_path):
            with open(self._uuid_path, "r", encoding="utf-8") as f:
                val = f.read().strip()  # analysis: allow-blocking(boot-time uuid read)
                if val:
                    return val
        val = str(uuidlib.uuid4())
        if self._uuid_path:
            tmp = self._uuid_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(val)  # analysis: allow-blocking(boot-time uuid write)
            os.replace(tmp, self._uuid_path)
        return val

    # ------------------------------------------------------------- report

    def get_telemetry(self) -> dict:
        """Report payload (`emqx_telemetry.erl:299-314` field parity)."""
        metrics = getattr(self.broker, "metrics", None)
        get = (lambda k: metrics.get(k)) if metrics is not None else (lambda k: 0)
        active_plugins: List[str] = []
        if self.plugins is not None:
            active_plugins = [
                p["name_vsn"] for p in self.plugins.list() if p["running"]
            ]
        return {
            "emqx_version": VERSION,
            "license": {"edition": "opensource"},
            "os_name": platform.system(),
            "os_version": platform.release(),
            "otp_version": platform.python_version(),  # runtime analog
            "up_time": round(time.time() - self._started_at, 3),
            "uuid": self.uuid,
            "nodes_uuid": [],
            "active_plugins": active_plugins,
            "active_modules": [],
            "num_clients": self._num_clients(),
            "messages_received": get("messages.received"),
            "messages_sent": get("messages.sent"),
        }

    def _num_clients(self) -> int:
        cm = getattr(self.broker, "cm", None)
        if cm is None:
            return 0
        for attr in ("channel_count", "count"):
            v = getattr(cm, attr, None)
            if callable(v):
                return v()
            if isinstance(v, int):
                return v
        chans = getattr(cm, "channels", None)
        return len(chans) if chans is not None else 0

    # ------------------------------------------------------------ control

    def report_now(self) -> Optional[dict]:
        if not self.enable:
            return None
        rep = self.get_telemetry()
        self._last_report = rep
        self._next_report_at = time.time() + REPORT_INTERVAL
        try:
            self.reporter(rep)
        except Exception:
            log.exception("telemetry reporter failed")
        return rep

    def tick(self, now: Optional[float] = None) -> Optional[dict]:
        """Housekeeping-driven timer (the reference uses a 7-day timer)."""
        now = time.time() if now is None else now
        if self.enable and now >= self._next_report_at:
            return self.report_now()
        return None

    def set_enabled(self, on: bool) -> None:
        self.enable = on
        if on:
            self._next_report_at = time.time() + REPORT_INTERVAL
