"""Authentication chains + providers.

Analog of `apps/emqx_authn` + `emqx_authentication.erl` (SURVEY.md §1.11):
an ordered chain of authenticator providers runs on 'client.authenticate';
each provider returns allow / deny / ignore (continue down the chain), like
the reference's per-listener chains with provider behaviors
(`emqx_authentication.erl:126-204`).

Providers: built-in database (password_hash pbkdf2/sha256/bcrypt-compatible
iterations), JWT (HS256/none-forbidden), HTTP (pluggable transport so tests
inject a fake server), and a static allow/deny list.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from .broker.access_control import ALLOW, DENY, ClientInfo
from .broker.hooks import Hooks, STOP
from .broker.packet import ReasonCode

IGNORE = "ignore"


class Authenticator:
    """Provider behavior: authenticate -> (ALLOW|DENY|IGNORE, extras)."""

    name = "base"
    enabled = True

    def authenticate(self, ci: ClientInfo) -> Tuple[str, Dict[str, Any]]:
        raise NotImplementedError


# -------------------------------------------------------------- built-in db

def hash_password(
    password: bytes,
    salt: bytes,
    algorithm: str = "pbkdf2_sha256",
    iterations: int = 10_000,
) -> str:
    if algorithm == "pbkdf2_sha256":
        dk = hashlib.pbkdf2_hmac("sha256", password, salt, iterations)
    elif algorithm == "sha256":
        dk = hashlib.sha256(salt + password).digest()
    elif algorithm == "sha512":
        dk = hashlib.sha512(salt + password).digest()
    elif algorithm == "plain":
        dk = password
    else:
        raise ValueError(f"unsupported hash algorithm {algorithm}")
    return dk.hex()


@dataclass
class UserRecord:
    user_id: str
    password_hash: str
    salt: bytes
    algorithm: str = "pbkdf2_sha256"
    iterations: int = 10_000
    is_superuser: bool = False


class BuiltInAuthenticator(Authenticator):
    """User store keyed by username or clientid (`emqx_authn_mnesia` analog)."""

    name = "built_in_database"

    def __init__(self, user_id_type: str = "username"):
        assert user_id_type in ("username", "clientid")
        self.user_id_type = user_id_type
        self.users: Dict[str, UserRecord] = {}

    def add_user(
        self,
        user_id: str,
        password: str,
        is_superuser: bool = False,
        algorithm: str = "pbkdf2_sha256",
        bcrypt_rounds: int = 10,
    ) -> UserRecord:
        if algorithm == "bcrypt":
            # salt lives inside the $2b$ hash (reference: emqx_passwd
            # bcrypt via the C NIF; ours is native/bcrypt.cc)
            from . import bcrypt_hash

            rec = UserRecord(
                user_id=user_id,
                password_hash=bcrypt_hash.hashpw(
                    password.encode(), bcrypt_hash.gensalt(bcrypt_rounds)
                ),
                salt=b"",
                algorithm=algorithm,
                is_superuser=is_superuser,
            )
            self.users[user_id] = rec
            return rec
        salt = os.urandom(16)
        rec = UserRecord(
            user_id=user_id,
            password_hash=hash_password(password.encode(), salt, algorithm),
            salt=salt,
            algorithm=algorithm,
            is_superuser=is_superuser,
        )
        self.users[user_id] = rec
        return rec

    def delete_user(self, user_id: str) -> bool:
        return self.users.pop(user_id, None) is not None

    def authenticate(self, ci: ClientInfo) -> Tuple[str, Dict[str, Any]]:
        uid = ci.username if self.user_id_type == "username" else ci.clientid
        if not uid:
            return IGNORE, {}
        rec = self.users.get(uid)
        if rec is None:
            return IGNORE, {}
        if ci.password is None:
            return DENY, {"reason_code": ReasonCode.BAD_USERNAME_OR_PASSWORD}
        if rec.algorithm == "bcrypt":
            from . import bcrypt_hash

            if bcrypt_hash.checkpw(ci.password, rec.password_hash):
                return ALLOW, {"is_superuser": rec.is_superuser}
            return DENY, {"reason_code": ReasonCode.BAD_USERNAME_OR_PASSWORD}
        got = hash_password(ci.password, rec.salt, rec.algorithm, rec.iterations)
        if hmac.compare_digest(got, rec.password_hash):
            return ALLOW, {"is_superuser": rec.is_superuser}
        return DENY, {"reason_code": ReasonCode.BAD_USERNAME_OR_PASSWORD}


# ---------------------------------------------------------------------- db

class DbAuthenticator(Authenticator):
    """Credential lookup through an injected database driver.

    The analog of `emqx_authn_{mysql,pgsql,mongodb,redis}.erl`: a query
    template with ${var} placeholders returns the stored credential
    (password_hash / salt / is_superuser), verified host-side with the
    configured algorithm — the DB never sees the cleartext password.

    SQL-flavored kinds call driver.query(template, params); "redis"
    calls driver.command("HGETALL", rendered_key).  Drivers come from
    `emqx_tpu.drivers.register_driver` (fakes in tests).
    """

    def __init__(
        self,
        kind: str,
        query: str,
        driver=None,
        algorithm: str = "pbkdf2_sha256",
        iterations: int = 10_000,
        **driver_cfg,
    ):
        from . import drivers

        self.kind = kind
        self.name = kind
        self.query = query
        self.algorithm = algorithm
        self.iterations = iterations
        self.driver = driver if driver is not None else drivers.make_driver(
            kind, **driver_cfg
        )

    def _fetch(self, ci: ClientInfo) -> Optional[Dict[str, Any]]:
        from . import drivers

        params = drivers.render_vars(ci)
        if self.kind == "redis":
            key = drivers.render_template(self.query, params)
            row = self.driver.command("HGETALL", key)
            return dict(row) if row else None
        rows = self.driver.query(self.query, params)
        return dict(rows[0]) if rows else None

    def authenticate(self, ci: ClientInfo) -> Tuple[str, Dict[str, Any]]:
        if not (ci.username or ci.clientid):
            return IGNORE, {}
        try:
            row = self._fetch(ci)
        except Exception:
            # driver outage: fall through the chain (the reference's
            # provider returns ignore on resource errors)
            return IGNORE, {"error": "db_unavailable"}
        if row is None:
            return IGNORE, {}
        if ci.password is None:
            return DENY, {"reason_code": ReasonCode.BAD_USERNAME_OR_PASSWORD}
        try:
            stored = row.get("password_hash") or row.get("password") or ""
            is_superuser = bool(row.get("is_superuser"))
            algorithm = row.get("algorithm", self.algorithm)
            if algorithm == "bcrypt":
                from . import bcrypt_hash

                ok = bcrypt_hash.checkpw(ci.password, stored)
            else:
                salt = row.get("salt", b"")
                if isinstance(salt, str):
                    salt = bytes.fromhex(salt) if salt else b""
                got = hash_password(
                    ci.password, salt, algorithm,
                    int(row.get("iterations", self.iterations)),
                )
                ok = hmac.compare_digest(got, stored)
        except Exception:
            # malformed stored credential (bad hex salt, wrong types):
            # data problem, not an authentication verdict — fall through
            return IGNORE, {"error": "bad_credential_row"}
        if ok:
            return ALLOW, {"is_superuser": is_superuser}
        return DENY, {"reason_code": ReasonCode.BAD_USERNAME_OR_PASSWORD}


# --------------------------------------------------------------------- jwt

def b64url_decode(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


class JwtAuthenticator(Authenticator):
    """HS256 JWT verification from the password field (`emqx_authn_jwt`)."""

    name = "jwt"

    def __init__(
        self,
        secret: bytes,
        from_field: str = "password",
        verify_claims: Optional[Dict[str, str]] = None,
        acl_claim_name: str = "acl",
    ):
        self.secret = secret
        self.from_field = from_field
        self.verify_claims = verify_claims or {}
        self.acl_claim_name = acl_claim_name

    def authenticate(self, ci: ClientInfo) -> Tuple[str, Dict[str, Any]]:
        token = (
            ci.password.decode("utf-8", "replace")
            if self.from_field == "password" and ci.password
            else (ci.username or "")
        )
        if token.count(".") != 2:
            return IGNORE, {}
        head_b64, payload_b64, sig_b64 = token.split(".")
        try:
            header = json.loads(b64url_decode(head_b64))
            if header.get("alg") != "HS256":
                return DENY, {"reason_code": ReasonCode.NOT_AUTHORIZED}
            expect = hmac.new(
                self.secret, f"{head_b64}.{payload_b64}".encode(), hashlib.sha256
            ).digest()
            if not hmac.compare_digest(expect, b64url_decode(sig_b64)):
                return DENY, {"reason_code": ReasonCode.NOT_AUTHORIZED}
            claims = json.loads(b64url_decode(payload_b64))
        except Exception:
            return DENY, {"reason_code": ReasonCode.NOT_AUTHORIZED}
        if "exp" in claims and time.time() >= float(claims["exp"]):
            return DENY, {"reason_code": ReasonCode.NOT_AUTHORIZED}
        for k, want in self.verify_claims.items():
            want = want.replace("${clientid}", ci.clientid).replace(
                "${username}", ci.username or ""
            )
            if str(claims.get(k)) != want:
                return DENY, {"reason_code": ReasonCode.NOT_AUTHORIZED}
        extras: Dict[str, Any] = {"is_superuser": bool(claims.get("is_superuser"))}
        if self.acl_claim_name in claims:
            extras["acl"] = claims[self.acl_claim_name]
        if "exp" in claims:
            extras["expire_at"] = float(claims["exp"])
        return ALLOW, extras


# -------------------------------------------------------------------- http

class HttpAuthenticator(Authenticator):
    """POST {clientid, username, password...} to an HTTP endpoint.

    The transport is injectable: `request_fn(body_dict) -> (status, body)`.
    Default uses urllib in a thread-unsafe sync call — production deploys
    swap in a pooled client; tests inject a stub (matching the reference's
    `emqx_authn_http` semantics: 200 {"result": "allow"/"deny"/"ignore"}).
    """

    name = "http"

    def __init__(self, url: str, request_fn: Optional[Callable] = None, timeout: float = 5.0):
        self.url = url
        self.timeout = timeout
        self.request_fn = request_fn or self._default_request

    def _default_request(self, body: Dict[str, Any]) -> Tuple[int, bytes]:
        import urllib.request

        req = urllib.request.Request(
            self.url,
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.status, resp.read()

    def authenticate(self, ci: ClientInfo) -> Tuple[str, Dict[str, Any]]:
        body = {
            "clientid": ci.clientid,
            "username": ci.username,
            "password": ci.password.decode("utf-8", "replace") if ci.password else None,
            "peerhost": ci.peerhost,
        }
        try:
            status, raw = self.request_fn(body)
        except Exception:
            return DENY, {"reason_code": ReasonCode.SERVER_UNAVAILABLE}
        if status == 204:
            return ALLOW, {}
        if status != 200:
            return IGNORE, {}
        try:
            data = json.loads(raw)
        except Exception:
            return IGNORE, {}
        result = data.get("result", "ignore")
        if result == "allow":
            return ALLOW, {"is_superuser": bool(data.get("is_superuser"))}
        if result == "deny":
            return DENY, {"reason_code": ReasonCode.NOT_AUTHORIZED}
        return IGNORE, {}


# ------------------------------------------------------------------- chain

class AuthChain:
    """Ordered authenticator chain registered on 'client.authenticate'."""

    def __init__(self, allow_anonymous: bool = True):
        self.authenticators: List[Authenticator] = []
        self.allow_anonymous = allow_anonymous

    def add(self, a: Authenticator, front: bool = False) -> None:
        if front:
            self.authenticators.insert(0, a)
        else:
            self.authenticators.append(a)

    def remove(self, name: str) -> None:
        self.authenticators = [a for a in self.authenticators if a.name != name]

    def __call__(self, ci: ClientInfo, acc):
        ran_any = False
        for a in self.authenticators:
            if not a.enabled:
                continue
            ran_any = True
            verdict, extras = a.authenticate(ci)
            if verdict == ALLOW:
                return (STOP, {"result": ALLOW, **extras})
            if verdict == DENY:
                rc = extras.get("reason_code", ReasonCode.NOT_AUTHORIZED)
                return (STOP, {"result": DENY, "reason_code": rc})
        if ran_any and not self.allow_anonymous:
            return (
                STOP,
                {"result": DENY, "reason_code": ReasonCode.NOT_AUTHORIZED},
            )
        return None  # fall through (anonymous allowed / no authenticators)

    def install(self, hooks: Hooks, priority: int = 0) -> None:
        hooks.put("client.authenticate", self, priority)

    def uninstall(self, hooks: Hooks) -> None:
        hooks.delete("client.authenticate", self)
