"""MQTT over WebSocket — `emqx_ws_connection.erl` analog, RFC 6455 native.

No websocket library exists in this image, so the handshake (HTTP/1.1
Upgrade with Sec-WebSocket-Accept, `mqtt` subprotocol) and the frame
codec (masking, 7/16/64-bit lengths, binary/ping/pong/close opcodes,
continuation frames) are implemented here on asyncio streams.

The MQTT machinery is reused wholesale: `WsReader`/`WsWriter` adapt the
WS message stream to the byte-stream interface `Connection` expects, so
the same Channel/session/limiter paths serve TCP and WS identically —
the reference gets this by running the same emqx_channel under cowboy.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import logging
import os
import ssl
import struct
from typing import Tuple

from .listener import Connection, Listener

log = logging.getLogger("emqx_tpu.ws")

GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT = 0x0
OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA


def accept_key(key: str) -> str:
    return base64.b64encode(hashlib.sha1((key + GUID).encode()).digest()).decode()


def encode_frame(opcode: int, payload: bytes, mask: bool = False,
                 fin: bool = True) -> bytes:
    head = bytes([(0x80 if fin else 0) | opcode])
    n = len(payload)
    mask_bit = 0x80 if mask else 0
    if n < 126:
        head += bytes([mask_bit | n])
    elif n < 65536:
        head += bytes([mask_bit | 126]) + struct.pack("!H", n)
    else:
        head += bytes([mask_bit | 127]) + struct.pack("!Q", n)
    if mask:
        key = os.urandom(4)
        masked = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
        return head + key + masked
    return head + payload


MAX_MESSAGE_SIZE = 1_048_576  # match Parser(max_size) on the TCP path


class FrameTooLarge(Exception):
    pass


async def read_frame(reader: asyncio.StreamReader,
                     max_size: int = MAX_MESSAGE_SIZE) -> Tuple[int, bool, bytes]:
    """-> (opcode, fin, payload); unmasks client frames."""
    b1, b2 = await reader.readexactly(2)
    fin = bool(b1 & 0x80)
    opcode = b1 & 0x0F
    masked = bool(b2 & 0x80)
    n = b2 & 0x7F
    if n == 126:
        (n,) = struct.unpack("!H", await reader.readexactly(2))
    elif n == 127:
        (n,) = struct.unpack("!Q", await reader.readexactly(8))
    if n > max_size:
        # reject before buffering: a declared 8GB frame must not OOM us
        raise FrameTooLarge(n)
    key = await reader.readexactly(4) if masked else None
    payload = await reader.readexactly(n) if n else b""
    if key:
        payload = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
    return opcode, fin, payload


class WsReader:
    """Byte-stream view over incoming WS binary messages.

    `read()` returns the next complete (defragmented) binary payload —
    the reference likewise feeds whole WS frames into emqx_frame.
    Control frames are answered inline (ping->pong, close->echo).
    """

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 max_message_size: int = MAX_MESSAGE_SIZE):
        self._reader = reader
        self._writer = writer
        self._max_message_size = max_message_size
        self.closed = False
        # frames are pumped by a background task so a cancelled read()
        # (keepalive timeout) can never desync the frame stream
        self._q: "asyncio.Queue[bytes]" = asyncio.Queue()
        self._pump = asyncio.get_event_loop().create_task(self._pump_loop())

    async def _pump_loop(self) -> None:
        frag = b""
        try:
            while True:
                opcode, fin, payload = await read_frame(
                    self._reader, self._max_message_size)
                if opcode in (OP_BINARY, OP_TEXT, OP_CONT):
                    frag += payload
                    if len(frag) > self._max_message_size:
                        raise FrameTooLarge(len(frag))  # fragmented overrun
                    if fin:
                        if frag:  # b"" would read as the EOF sentinel
                            self._q.put_nowait(frag)
                        frag = b""
                elif opcode == OP_PING:
                    try:
                        self._writer.write(encode_frame(OP_PONG, payload))
                    except Exception:
                        pass
                elif opcode == OP_CLOSE:
                    try:
                        self._writer.write(encode_frame(OP_CLOSE, payload))
                    except Exception:
                        pass
                    break
                # pongs ignored
        except asyncio.CancelledError:
            raise  # cancellation must propagate; the finally runs either way
        except (asyncio.IncompleteReadError, ConnectionError, ssl.SSLError):
            # SSLError: close_notify teardown races on a wss transport
            pass
        except FrameTooLarge as e:
            log.warning("ws: dropping connection, frame too large (%s bytes)", e)
        finally:
            self.closed = True
            self._q.put_nowait(b"")  # EOF marker wakes a blocked read()

    async def read(self, _n: int = -1) -> bytes:
        if self.closed and self._q.empty():
            return b""
        return await self._q.get()

    def close(self) -> None:
        """Cancel the frame pump (idempotent).  A half-open socket
        otherwise keeps the pump task parked in read_frame forever —
        the transport owner closes the socket itself."""
        if self._pump is not None:
            self._pump.cancel()
            self._pump = None
        self.closed = True


class WsWriter:
    """Wraps outgoing bytes into server->client binary frames."""

    def __init__(self, writer: asyncio.StreamWriter):
        self._writer = writer
        self.transport = writer.transport

    def write(self, data: bytes) -> None:
        self._writer.write(encode_frame(OP_BINARY, data))

    def writelines(self, bufs) -> None:
        """Vectored flush parity with the TCP transport: each chunk is
        its own WS binary message, but all of them reach the socket
        writer in one call."""
        self._writer.write(
            b"".join(encode_frame(OP_BINARY, b) for b in bufs)
        )

    async def drain(self) -> None:
        await self._writer.drain()

    def close(self) -> None:
        try:
            self._writer.write(encode_frame(OP_CLOSE, b""))
        except Exception:
            pass
        self._writer.close()

    def is_closing(self) -> bool:
        return self._writer.is_closing()

    def get_extra_info(self, name, default=None):
        return self._writer.get_extra_info(name, default)


class WsListener(Listener):
    """MQTT-over-WebSocket listener; handshake on `path` (default /mqtt)."""

    def __init__(self, *a, path: str = "/mqtt", **kw):
        super().__init__(*a, **kw)
        self.path = path

    async def _on_client(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        # shed BEFORE any protocol work, same gate as the TCP listener
        # (incl. the wire.max_conn_rate accept bucket)
        if not self.accept_gate(writer):
            return
        try:
            ok = await asyncio.wait_for(self._handshake(reader, writer), 10)
        except (asyncio.TimeoutError, ConnectionError,
                asyncio.IncompleteReadError, ValueError):
            # ValueError covers LimitOverrunError from over-long header lines
            writer.close()
            return
        if not ok:
            writer.close()
            return
        # the WS message cap must track the MQTT packet cap (the v5
        # CONNACK advertises it): +16 covers the MQTT fixed header so a
        # packet exactly at the limit survives the WS framing check
        mps = (self.config.max_packet_size
               if self.config else MAX_MESSAGE_SIZE)
        ws_reader = WsReader(reader, writer, max_message_size=mps + 16)
        ws_writer = WsWriter(writer)
        conn = Connection(self.broker, ws_reader, ws_writer, self.config,
                          limiter=self.limiter)
        # wss: TLS terminated below the WS framing, cert on the raw writer
        self._attach_tls_identity(conn, writer)
        if self.batcher is not None:
            conn.channel.publish_fn = self.batcher.submit
        task = asyncio.current_task()
        self._conns.add(task)
        try:
            await conn.run()
        finally:
            ws_reader.close()
            self._conns.discard(task)

    async def _handshake(self, reader, writer) -> bool:
        req_line = await reader.readline()
        try:
            method, path, _ = req_line.decode().split(None, 2)
        except ValueError:
            return False
        headers = {}
        while True:
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.decode().partition(":")
            headers[k.strip().lower()] = v.strip()
        if (
            method != "GET"
            or path.split("?")[0] != self.path
            or headers.get("upgrade", "").lower() != "websocket"
            or "sec-websocket-key" not in headers
        ):
            writer.write(b"HTTP/1.1 400 Bad Request\r\nContent-Length: 0\r\n\r\n")
            await writer.drain()
            return False
        protos = [p.strip() for p in
                  headers.get("sec-websocket-protocol", "").split(",") if p.strip()]
        resp = (
            "HTTP/1.1 101 Switching Protocols\r\n"
            "Upgrade: websocket\r\n"
            "Connection: Upgrade\r\n"
            f"Sec-WebSocket-Accept: {accept_key(headers['sec-websocket-key'])}\r\n"
        )
        # the reference's WS listener requires the mqtt subprotocol
        if "mqtt" in protos:
            resp += "Sec-WebSocket-Protocol: mqtt\r\n"
        elif protos:
            writer.write(b"HTTP/1.1 400 Bad Request\r\nContent-Length: 0\r\n\r\n")
            await writer.drain()
            return False
        writer.write((resp + "\r\n").encode())
        await writer.drain()
        return True


async def ws_connect(host: str, port: int, path: str = "/mqtt", ssl=None,
                     server_hostname=None) -> Tuple[WsReader, "WsClientWriter"]:
    """Client-side handshake + masked-frame adapters (test harness)."""
    kw = {}
    if ssl is not None:
        kw["ssl"] = ssl
        kw["server_hostname"] = server_hostname or host
    reader, writer = await asyncio.open_connection(host, port, **kw)
    key = base64.b64encode(os.urandom(16)).decode()
    writer.write(
        (
            f"GET {path} HTTP/1.1\r\nHost: {host}:{port}\r\n"
            "Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Key: {key}\r\n"
            "Sec-WebSocket-Version: 13\r\n"
            "Sec-WebSocket-Protocol: mqtt\r\n\r\n"
        ).encode()
    )
    await writer.drain()
    status = await reader.readline()
    if b"101" not in status:
        raise ConnectionError(f"ws handshake failed: {status!r}")
    want = accept_key(key)
    got = None
    while True:
        line = await reader.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        k, _, v = line.decode().partition(":")
        if k.strip().lower() == "sec-websocket-accept":
            got = v.strip()
    if got != want:
        raise ConnectionError("bad Sec-WebSocket-Accept")
    return WsReader(reader, writer), WsClientWriter(writer)


class WsClientWriter(WsWriter):
    def write(self, data: bytes) -> None:
        self._writer.write(encode_frame(OP_BINARY, data, mask=True))

    def writelines(self, bufs) -> None:
        self._writer.write(
            b"".join(encode_frame(OP_BINARY, b, mask=True) for b in bufs)
        )
