"""TLS termination for listeners — `emqx_tls_lib.erl` / ssl_opts analog.

The reference treats `ssl` as a first-class listener type: esockd opens
the socket with an `ssl_options` proplist built by `emqx_tls_lib.erl`
from the schema's ssl_opts fields (certfile/keyfile/cacertfile/verify/
fail_if_no_peer_cert/versions/ciphers, `emqx_schema.erl` common_ssl_opts)
and TLS-PSK callbacks come from `emqx_tls_psk.erl`.  Here the same
surface maps onto `ssl.SSLContext`:

- `TlsConfig` is the typed schema for one listener's ssl options.
- `make_server_context` builds the context, including SNI-based cert
  switching (one nested TlsConfig per hostname) and ALPN.
- TLS-PSK wires `PskStore.lookup` into
  `SSLContext.set_psk_server_callback` when the runtime provides it
  (CPython 3.13+); on 3.12 the store still serves authn/gateway lookups
  and `psk_supported()` reports the gap instead of failing silently.
- `peer_cert_info` extracts the client cert CN/DN after the handshake so
  listeners can implement the reference's `peer_cert_as_username` /
  `peer_cert_as_clientid` options (`emqx_channel.erl` maybe_username).
"""

from __future__ import annotations

import ssl
from dataclasses import dataclass, field
from typing import Dict, List, Optional

#: verify modes, matching the reference's `verify` enum
VERIFY_NONE = "verify_none"
VERIFY_PEER = "verify_peer"

_TLS_VERSIONS = {
    "tlsv1.2": ssl.TLSVersion.TLSv1_2,
    "tlsv1.3": ssl.TLSVersion.TLSv1_3,
}


def psk_supported() -> bool:
    """True when the ssl runtime can terminate TLS-PSK handshakes."""
    return hasattr(ssl.SSLContext, "set_psk_server_callback")


@dataclass
class TlsConfig:
    """One listener's ssl options (`emqx_schema.erl` common_ssl_opts)."""

    certfile: Optional[str] = None
    keyfile: Optional[str] = None
    cacertfile: Optional[str] = None
    key_password: Optional[str] = None
    verify: str = VERIFY_NONE
    fail_if_no_peer_cert: bool = False
    versions: List[str] = field(default_factory=lambda: ["tlsv1.2", "tlsv1.3"])
    ciphers: Optional[str] = None  # OpenSSL cipher string (TLS<=1.2 suites)
    alpn_protocols: List[str] = field(default_factory=list)
    handshake_timeout: float = 15.0
    #: hostname -> TlsConfig carrying that vhost's cert/key (SNI)
    sni_hosts: Dict[str, "TlsConfig"] = field(default_factory=dict)
    #: enable TLS-PSK (requires runtime support; see psk_supported())
    enable_psk: bool = False
    psk_identity_hint: str = "emqx_psk_hint"
    #: derive username/clientid from the peer cert (cn or dn)
    peer_cert_as_username: Optional[str] = None  # "cn" | "dn"
    peer_cert_as_clientid: Optional[str] = None  # "cn" | "dn"


def _apply_common(ctx: ssl.SSLContext, cfg: TlsConfig) -> None:
    unknown = [v for v in cfg.versions if v not in _TLS_VERSIONS]
    if unknown:
        raise ValueError(
            f"unsupported TLS versions {unknown}; "
            f"supported: {sorted(_TLS_VERSIONS)}"
        )
    versions = [_TLS_VERSIONS[v] for v in cfg.versions] or list(
        _TLS_VERSIONS.values()
    )
    ctx.minimum_version = min(versions)
    ctx.maximum_version = max(versions)
    if cfg.ciphers:
        ctx.set_ciphers(cfg.ciphers)
    if cfg.certfile:
        ctx.load_cert_chain(
            cfg.certfile, cfg.keyfile or None, password=cfg.key_password
        )
    if cfg.cacertfile:
        ctx.load_verify_locations(cafile=cfg.cacertfile)


def make_server_context(
    cfg: TlsConfig, psk_store=None
) -> ssl.SSLContext:
    """Build the listener-side SSLContext (`emqx_tls_lib:server_ssl_opts`)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    _apply_common(ctx, cfg)
    if cfg.verify == VERIFY_PEER:
        # CERT_REQUIRED aborts the handshake when no cert is presented;
        # CERT_OPTIONAL verifies one if offered (fail_if_no_peer_cert=false)
        ctx.verify_mode = (
            ssl.CERT_REQUIRED if cfg.fail_if_no_peer_cert else ssl.CERT_OPTIONAL
        )
    elif cfg.verify == VERIFY_NONE:
        if cfg.peer_cert_as_username or cfg.peer_cert_as_clientid:
            raise ValueError(
                "peer_cert_as_username/clientid requires verify=verify_peer "
                "— with verify_none no client cert is ever requested and "
                "identity would silently fall back to the CONNECT username"
            )
        ctx.verify_mode = ssl.CERT_NONE
    else:
        raise ValueError(
            f"unknown verify mode {cfg.verify!r}; "
            f"expected {VERIFY_NONE!r} or {VERIFY_PEER!r}"
        )
    if cfg.alpn_protocols:
        ctx.set_alpn_protocols(cfg.alpn_protocols)
    if cfg.sni_hosts:
        # SSL_set_SSL_CTX (what `sock.context = ...` does mid-handshake)
        # swaps the certificate but NOT the connection's verify mode, so a
        # stricter verify on a vhost entry would be silently unenforced —
        # reject such configs instead of shipping an authentication bypass.
        for name, sub in cfg.sni_hosts.items():
            if (
                sub.verify != cfg.verify
                or sub.fail_if_no_peer_cert != cfg.fail_if_no_peer_cert
                or (sub.cacertfile or None) not in (None, cfg.cacertfile)
            ):
                raise ValueError(
                    f"sni_hosts[{name!r}]: verify/fail_if_no_peer_cert/"
                    "cacertfile must match the listener config — peer "
                    "verification is handshake-wide, only certs can vary "
                    "per SNI name"
                )
        per_host = {
            name: make_server_context(sub, psk_store)
            for name, sub in cfg.sni_hosts.items()
        }

        def _sni_cb(sock, server_name, _ctx):
            chosen = per_host.get(server_name)
            if chosen is not None:
                sock.context = chosen
            return None  # default cert serves unknown names

        ctx.sni_callback = _sni_cb
    if cfg.enable_psk:
        if psk_store is None:
            raise ValueError(
                "enable_psk=True requires a PskStore (Listener(psk_store=...))"
            )
        if not psk_supported():
            raise RuntimeError(
                "TLS-PSK requires ssl.SSLContext.set_psk_server_callback "
                "(CPython >= 3.13); gate enable_psk on tls.psk_supported()"
            )
        ctx.set_psk_server_callback(
            psk_store.ssl_callback(), cfg.psk_identity_hint
        )
        # PSK key exchange needs PSK-capable TLS1.2 suites alongside the
        # authenticated defaults.  NOT "ALL:PSK": ALL drags in anonymous
        # ADH/AECDH suites, letting a MITM handshake with no cert & no PSK.
        if not cfg.ciphers:
            ctx.set_ciphers("DEFAULT:PSK")
    return ctx


def make_client_context(
    cacertfile: Optional[str] = None,
    certfile: Optional[str] = None,
    keyfile: Optional[str] = None,
    verify: bool = True,
    alpn_protocols: Optional[List[str]] = None,
) -> ssl.SSLContext:
    """Client-side context for bridges/tests (`emqx_tls_lib:client_ssl_opts`)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    if cacertfile:
        ctx.load_verify_locations(cafile=cacertfile)
    if not verify:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    if certfile:
        ctx.load_cert_chain(certfile, keyfile or None)
    if alpn_protocols:
        ctx.set_alpn_protocols(alpn_protocols)
    return ctx


def _rdn_str(rdns) -> str:
    """Flatten getpeercert()'s RDN tuples into an RFC 4514-ish string."""
    parts = []
    for rdn in rdns:
        for key, value in rdn:
            parts.append(f"{key}={value}")
    return ",".join(parts)


def peer_cert_info(ssl_object) -> Dict[str, str]:
    """Extract cn/dn from the peer certificate after the handshake.

    Feeds `peer_cert_as_username`/`peer_cert_as_clientid`: the reference
    resolves these against the cert subject in `esockd_peercert` and
    stores them in the client's conninfo.
    """
    info: Dict[str, str] = {}
    if ssl_object is None:
        return info
    try:
        cert = ssl_object.getpeercert()
    except Exception:
        return info
    if not cert:
        return info
    subject = cert.get("subject", ())
    for rdn in subject:
        for key, value in rdn:
            if key == "commonName" and "cn" not in info:
                info["cn"] = value
    if subject:
        info["dn"] = _rdn_str(subject)
    return info
