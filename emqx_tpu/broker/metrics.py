"""Broker metrics: named counters + gauges.

Analog of `emqx_metrics.erl` (preallocated counters array,
`apps/emqx/src/emqx_metrics.erl:78,216-268`) and `emqx_stats.erl` gauges.
Python ints are atomic under the GIL, so a dict of counters plays the role
of the `counters` array; the fixed name registry is kept for API parity and
Prometheus export.
"""

from __future__ import annotations

import time
from typing import Dict

# the reference's predefined metric names (subset; extended at runtime)
PREDEFINED = [
    "bytes.received",
    "bytes.sent",
    "packets.received",
    "packets.sent",
    "packets.connect.received",
    "packets.connack.sent",
    "packets.publish.received",
    "packets.publish.sent",
    "packets.puback.received",
    "packets.puback.sent",
    "packets.subscribe.received",
    "packets.suback.sent",
    "packets.unsubscribe.received",
    "packets.unsuback.sent",
    "packets.pingreq.received",
    "packets.pingresp.sent",
    "packets.disconnect.received",
    "packets.disconnect.sent",
    "packets.auth.received",
    "packets.auth.sent",
    "messages.received",
    "messages.sent",
    "messages.qos0.received",
    "messages.qos1.received",
    "messages.qos2.received",
    "messages.delivered",
    "messages.queued",
    "messages.retained",
    "messages.dropped",
    "messages.dropped.no_subscribers",
    "messages.dropped.await_pubrel_timeout",
    "messages.acked",
    "authentication.success",
    "authentication.failure",
    "authorization.allow",
    "authorization.deny",
    "session.created",
    "session.resumed",
    "session.takenover",
    "session.discarded",
    "session.terminated",
    "client.connect",
    "client.connack",
    "client.connected",
    "client.disconnected",
    "client.subscribe",
    "client.unsubscribe",
    # engine flight-recorder counters (synced from the match engine by
    # Broker.sync_engine_metrics; exposed as Prometheus counters, e.g.
    # emqx_engine_path_flips)
    "engine.ticks",
    "engine.churn_shed",
    # fused-prep topic memo (ops/prep.py, PR 6 counters promoted out of
    # bench JSON; synced by Broker.sync_engine_metrics)
    "engine.memo_hits",
    "engine.memo_misses",
    "engine.prep_degraded",
    "engine.host_serve",
    "engine.dev_serve",
    "engine.dev_timeout",
    "engine.path_flips",
    "engine.verify_mismatch",
    "engine.probes",
    # table checkpoint & warm restart (checkpoint/manager.py)
    "engine.ckpt.saves",
    "engine.ckpt.save_failures",
    "engine.ckpt.restores",
    "engine.ckpt.wal_records",
    # durable message log (ds/manager.py; gauges ds.bytes|segments|lag
    # ride the gauge table via DsManager.sync_metrics)
    "ds.appends",
    "ds.flushes",
    "ds.replays",
    "ds.replayed_messages",
    "ds.gc_segments",
    # ds append replication (ds/repl.py leader ship / follower mirror +
    # cluster/node.py cursor-handoff takeover; gauge ds.repl.lag rides
    # the gauge table via DsManager.sync_metrics)
    "ds.repl.ranges",
    "ds.repl.records",
    "ds.repl.send_failures",
    "ds.repl.mirror_appends",
    "ds.repl.catchup_ranges",
    "ds.repl.handoffs",
    "ds.repl.mirror_gc",
    # self-healing cluster data plane (cluster/node.py forward spool)
    "messages.forward.spooled",
    "messages.forward.replayed",
    "messages.forward.spool_dropped",
    "messages.forward.dup_dropped",
    # cluster forward path (broker/broker.py + cluster/node.py): in/out
    # frames, relays, failures, shared-group redispatch
    "messages.forward.in",
    "messages.forward.out",
    "messages.forward.relayed",
    "messages.forward.shared",
    "messages.forward.dropped",
    "messages.shared.redispatched",
    "messages.dropped.no_shared_member",
    "messages.forward.semantic",
    # host match-path hash-collision catch (Broker.on_collision hook)
    "match.hash_collision",
    # delivery plane (broker/delivery.py pool + listener vectored flush
    # + frame.py shared packet-prefix cache, synced like engine.* by
    # Broker.sync_engine_metrics)
    "messages.delivered.batched",
    "deliver.flush.vectored",
    "deliver.shard.backpressure",
    "deliver.prefix.hit",
    "deliver.prefix.miss",
    # connection lifecycle + overload protection (broker/listener.py,
    # broker/ws.py)
    "channels.force_shutdown",
    "olp.new_conn.shed",
    "olp.new_conn.rate_limited",
    # process-sharded wire plane (wire/supervisor.py; the per-worker
    # wire.worker.<i>.* figures are gauges, not counters)
    "wire.worker.exits",
    # shared-memory match plane (emqx_tpu/shm/): worker-side client
    # counters (synced by Broker.sync_engine_metrics in each worker)
    # and hub-side service counters (synced by the wire supervisor's
    # stats loop)
    "shm.submits",
    "shm.degraded",
    "shm.local_serves",
    "shm.oversize",
    "shm.reregisters",
    "shm.hub.ticks",
    "shm.hub.groups",
    "shm.hub.churn_records",
    "shm.hub.reclaims",
    "shm.hub.res_drops",
    "shm.hub.ack_shed",
    "shm.hub.credit_exhausted",
    "shm.hub.doorbell_wakeups",
    "shm.hub.sem_ticks",
    "shm.hub.sem_texts",
    "shm.hub.sem_res_drops",
    "shm.hub.sem_churn",
    # exhook event dispatcher (exhook/manager.py)
    "exhook.events.dropped",
    "exhook.events.failed",
    # engine device breaker (models/engine.py; synced like the rest of
    # the engine.* counters by Broker.sync_engine_metrics)
    "engine.breaker_trips",
    # retained device index (broker/retainer.py + models/retained.py;
    # synced by Broker.sync_engine_metrics at observation points)
    "retained.lookups.index",
    "retained.lookups.trie",
    "retained.index.flips",
    "retained.index.probes",
    "retained.index.collisions",
    "retained.index.fallbacks",
    "retained.index.refetches",
    # semantic subscription plane (emqx_tpu/semantic/; synced by
    # Broker.sync_engine_metrics from SemanticPlane.counters())
    "semantic.queries.added",
    "semantic.queries.removed",
    "semantic.deliveries",
    "semantic.degraded",
    "semantic.dropped",
    "semantic.forwards",
    "semantic.matches.device",
    "semantic.matches.host",
    "semantic.flips",
    "semantic.probes",
    "semantic.refetches",
]


class Metrics:
    def __init__(self) -> None:
        self.counters: Dict[str, int] = {name: 0 for name in PREDEFINED}
        self.gauges: Dict[str, float] = {}
        self.created_at = time.time()

    def inc(self, name: str, n: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n

    def get(self, name: str) -> int:
        return self.counters.get(name, 0)

    def gauge_set(self, name: str, v: float) -> None:
        self.gauges[name] = v

    def gauge(self, name: str) -> float:
        return self.gauges.get(name, 0.0)

    def all(self) -> Dict[str, float]:
        out: Dict[str, float] = dict(self.counters)
        out.update(self.gauges)
        return out

    def reset(self) -> None:
        for k in self.counters:
            self.counters[k] = 0
