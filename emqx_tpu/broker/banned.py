"""Ban table + flapping detection.

Analog of `emqx_banned.erl` / `emqx_flapping.erl` (SURVEY.md §2.1): banned
clientids/usernames/peerhosts are rejected at CONNECT via the
'client.connect' hook; clients that connect/disconnect too fast get
auto-banned for a cooldown window.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from .access_control import DENY, ClientInfo
from .hooks import Hooks, STOP


@dataclass
class BanEntry:
    kind: str  # clientid | username | peerhost
    value: str
    reason: str = ""
    by: str = "admin"
    until: float = float("inf")


class Banned:
    def __init__(self) -> None:
        self._t: Dict[Tuple[str, str], BanEntry] = {}

    def create(self, kind: str, value: str, reason: str = "", by: str = "admin",
               duration: Optional[float] = None) -> BanEntry:
        until = time.time() + duration if duration else float("inf")
        e = BanEntry(kind, value, reason, by, until)
        self._t[(kind, value)] = e
        return e

    def delete(self, kind: str, value: str) -> bool:
        return self._t.pop((kind, value), None) is not None

    def look_up(self, kind: str, value: str) -> Optional[BanEntry]:
        e = self._t.get((kind, value))
        if e and e.until <= time.time():
            del self._t[(kind, value)]
            return None
        return e

    def check(self, ci: ClientInfo) -> bool:
        """True if the client is banned."""
        from ..utils.net import peer_host

        host = peer_host(ci.peerhost)
        return any(
            self.look_up(k, v) is not None
            for k, v in (
                ("clientid", ci.clientid),
                ("username", ci.username or ""),
                ("peerhost", host),
            )
        )

    def all(self):
        now = time.time()
        return [e for e in self._t.values() if e.until > now]

    def __call__(self, ci: ClientInfo, acc):
        if self.check(ci):
            return (STOP, DENY)
        return None

    def install(self, hooks: Hooks, priority: int = 100) -> None:
        hooks.put("client.connect", self, priority)


class Flapping:
    """Detect rapid reconnect cycles and auto-ban (`emqx_flapping.erl`)."""

    def __init__(
        self,
        banned: Banned,
        max_count: int = 15,
        window: float = 60.0,
        ban_duration: float = 300.0,
    ):
        self.banned = banned
        self.max_count = max_count
        self.window = window
        self.ban_duration = ban_duration
        self._hits: Dict[str, list] = {}

    def on_disconnect(self, ci: ClientInfo, *_args) -> None:
        now = time.time()
        hits = self._hits.setdefault(ci.clientid, [])
        hits.append(now)
        cutoff = now - self.window
        while hits and hits[0] < cutoff:
            hits.pop(0)
        if len(hits) >= self.max_count:
            self.banned.create(
                "clientid",
                ci.clientid,
                reason="flapping",
                by="flapping_detector",
                duration=self.ban_duration,
            )
            del self._hits[ci.clientid]

    def install(self, hooks: Hooks) -> None:
        hooks.put("client.disconnected", self.on_disconnect)
