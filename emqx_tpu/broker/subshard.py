"""Subscriber-shard expansion layer — the `emqx_broker_helper` analog.

The reference splits one topic's subscriber list into shard buckets once it
passes 1024 subscribers (`emqx_broker_helper.erl:54,82-91`), and dispatch
folds the main table plus the shard buckets (`emqx_broker.erl:520-524`).
Here the same layer sits host-side between the device match engine and
session delivery:

* clientids are interned to dense int32 uids (refcounted across filters);
* each fid owns a main bucket plus, past the shard threshold, hashed
  shard buckets — every bucket is an amortized-growth numpy array with
  O(1) add and swap-delete;
* expansion of matched fids to receivers is vectorized: one concatenate
  over the bucket views + one stable argsort to group clients that match
  several filters — per-receiver cost is a single delivery call, flat in
  fan-out (the `emqx_broker.erl:499-524` hot loop without per-subscriber
  dict churn).

(The sharded device engine's per-fid ``dest`` ids in
`parallel/sharded.py` are a separate, per-FID accounting dimension for
the `psum_scatter` fan-out merge; host buckets here shard per-CLIENT.
Dispatch uses the compact matched-fid return, not the device counts.)
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np

SHARD_THRESHOLD = 1024  # emqx_broker_helper.erl:54 (shard past 1024 subs)
NSHARDS = 32  # reference: schedulers x 32; fixed host-side


class _Bucket:
    """Append-friendly int32 set: amortized append + swap-delete."""

    __slots__ = ("arr", "n", "pos")

    def __init__(self) -> None:
        self.arr = np.empty(8, dtype=np.int32)
        self.n = 0
        self.pos: Dict[int, int] = {}

    def add(self, uid: int) -> None:
        if self.n == len(self.arr):
            grown = np.empty(len(self.arr) * 2, dtype=np.int32)
            grown[: self.n] = self.arr
            self.arr = grown
        self.arr[self.n] = uid
        self.pos[uid] = self.n
        self.n += 1

    def remove(self, uid: int) -> None:
        i = self.pos.pop(uid)
        last = self.n - 1
        if i != last:
            moved = self.arr[last]
            self.arr[i] = moved
            self.pos[int(moved)] = i
        self.n = last

    def view(self) -> np.ndarray:
        return self.arr[: self.n]


class SubscriberShards:
    """fid -> sharded subscriber-uid buckets + uid <-> clientid interning."""

    def __init__(
        self, threshold: int = SHARD_THRESHOLD, nshards: int = NSHARDS
    ) -> None:
        self.threshold = threshold
        self.nshards = nshards
        self._uids: Dict[str, int] = {}
        self._cids: List[str] = []
        self._uid_refs: List[int] = []
        self._free_uids: List[int] = []
        # fid -> [main bucket, shard buckets...] (shards appear lazily)
        self._fids: Dict[int, List[_Bucket]] = {}
        self._counts: Dict[int, int] = {}
        # fires when a uid's last subscription drops and the slot goes
        # back on the free list (uids are RECYCLED — any uid-keyed
        # side cache must drop the entry here)
        self.on_uid_released = None

    # ------------------------------------------------------------- intern

    def _intern(self, cid: str) -> int:
        uid = self._uids.get(cid)
        if uid is not None:
            self._uid_refs[uid] += 1
            return uid
        if self._free_uids:
            uid = self._free_uids.pop()
            self._cids[uid] = cid
            self._uid_refs[uid] = 1
        else:
            uid = len(self._cids)
            self._cids.append(cid)
            self._uid_refs.append(1)
        self._uids[cid] = uid
        return uid

    def _release(self, uid: int) -> None:
        self._uid_refs[uid] -= 1
        if self._uid_refs[uid] == 0:
            del self._uids[self._cids[uid]]
            self._cids[uid] = ""
            self._free_uids.append(uid)
            if self.on_uid_released is not None:
                self.on_uid_released(uid)

    def cid_of(self, uid: int) -> str:
        return self._cids[uid]

    # -------------------------------------------------------------- shard

    def _shard_of(self, fid: int, uid: int) -> int:
        """0 = main bucket; >0 only once the fid crossed the threshold
        (`emqx_broker_helper:get_sub_shard/2`: existing subs stay put)."""
        if self._counts.get(fid, 0) < self.threshold:
            return 0
        return 1 + (uid * 0x9E3779B1 & 0xFFFFFFFF) % self.nshards

    # ----------------------------------------------------------- mutation

    def add(self, fid: int, cid: str) -> bool:
        """Returns False (no-op) when the client already subscribes."""
        uid = self._uids.get(cid)
        buckets = self._fids.get(fid)
        if uid is not None and buckets is not None:
            for b in buckets:
                if uid in b.pos:
                    return False
        if buckets is None:
            buckets = self._fids[fid] = [_Bucket()]
        uid = self._intern(cid)
        shard = self._shard_of(fid, uid)
        while len(buckets) <= shard:
            buckets.append(_Bucket())
        buckets[shard].add(uid)
        self._counts[fid] = self._counts.get(fid, 0) + 1
        return True

    def remove(self, fid: int, cid: str) -> bool:
        uid = self._uids.get(cid)
        buckets = self._fids.get(fid)
        if uid is None or buckets is None:
            return False
        for b in buckets:
            if uid in b.pos:
                b.remove(uid)
                self._counts[fid] -= 1
                if self._counts[fid] == 0:
                    del self._fids[fid]
                    del self._counts[fid]
                self._release(uid)
                return True
        return False

    def contains(self, fid: int, cid: str) -> bool:
        uid = self._uids.get(cid)
        buckets = self._fids.get(fid)
        if uid is None or buckets is None:
            return False
        return any(uid in b.pos for b in buckets)

    def count(self, fid: int) -> int:
        return self._counts.get(fid, 0)

    def n_shards_of(self, fid: int) -> int:
        return len(self._fids.get(fid, ()))

    # ---------------------------------------------------------- expansion

    def uids(self, fid: int) -> np.ndarray:
        """All subscriber uids of one fid (view when unsharded)."""
        buckets = self._fids.get(fid)
        if buckets is None:
            return np.empty(0, dtype=np.int32)
        if len(buckets) == 1:
            return buckets[0].view()
        return np.concatenate([b.view() for b in buckets])

    def clients(self, fid: int) -> Iterable[str]:
        cids = self._cids
        for uid in self.uids(fid).tolist():
            yield cids[uid]

    def scatter(self, fid: int) -> Tuple[List[int], List[str]]:
        """One fid's receivers as parallel (uids, clientids) lists —
        the single-filter broadcast lane: no per-receiver tuple or
        filter-list allocation (expand_uids pays both to group clients
        across several matched filters; a broadcast has exactly one)."""
        uids = self.uids(fid).tolist()
        cids = self._cids
        return uids, [cids[u] for u in uids]

    def expand(
        self, fid_filts: Sequence[Tuple[int, str]]
    ) -> List[Tuple[str, List[str]]]:
        """Vectorized fan-out: matched (fid, filter) pairs -> per-receiver
        (clientid, [matched filters]) with clients grouped across fids.

        One concatenate + one stable argsort; a client subscribing to k of
        the matched filters appears once with all k (mirrors the reference
        delivering per SubPid after folding shard buckets)."""
        return [(cid, fl) for _uid, cid, fl in self.expand_uids(fid_filts)]

    def expand_uids(
        self, fid_filts: Sequence[Tuple[int, str]]
    ) -> List[Tuple[int, str, List[str]]]:
        """expand() carrying the interned uid per receiver — the
        delivery-worker pool shards connections by ``uid % workers``, so
        dispatch partitions receivers without re-hashing clientid
        strings (and per-connection packet order is preserved by
        construction: one uid always lands on one shard)."""
        views: List[np.ndarray] = []
        filts: List[str] = []
        for fid, filt in fid_filts:
            u = self.uids(fid)
            if u.size:
                views.append(u)
                filts.append(filt)
        if not views:
            return []
        cids = self._cids
        if len(views) == 1:
            f = filts[0]
            return [(uid, cids[uid], [f]) for uid in views[0].tolist()]
        all_u = np.concatenate(views)
        seg = np.repeat(
            np.arange(len(views)), [v.size for v in views]
        )
        order = np.argsort(all_u, kind="stable")
        su = all_u[order]
        ss = seg[order]
        out: List[Tuple[int, str, List[str]]] = []
        i = 0
        n = su.size
        su_l = su.tolist()
        ss_l = ss.tolist()
        while i < n:
            j = i + 1
            uid = su_l[i]
            while j < n and su_l[j] == uid:
                j += 1
            out.append((uid, cids[uid], [filts[k] for k in ss_l[i:j]]))
            i = j
        return out
