"""Authentication/authorization facade with result caching.

Analog of `emqx_access_control.erl` (`apps/emqx/src/emqx_access_control.erl:31-68`):
both checks run hook chains ('client.authenticate' / 'client.authorize') so
provider chains (emqx_tpu.authn / emqx_tpu.authz) and external bridges plug
in uniformly; authorize verdicts are cached per client like
`emqx_authz_cache`.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

from .hooks import Hooks

ALLOW, DENY = "allow", "deny"
PUB, SUB = "publish", "subscribe"


@dataclass
class ClientInfo:
    clientid: str = ""
    username: Optional[str] = None
    password: Optional[bytes] = None
    peerhost: str = ""
    protocol: str = "mqtt"
    proto_ver: int = 4
    mountpoint: Optional[str] = None
    zone: str = "default"
    is_superuser: bool = False
    attrs: Dict[str, Any] = field(default_factory=dict)


class AuthResult(Exception):
    def __init__(self, reason_code: int):
        super().__init__(hex(reason_code))
        self.reason_code = reason_code


class AccessControl:
    def __init__(self, hooks: Hooks, cache_size: int = 32,
                 cache_ttl: float = 60.0, cache_enable: bool = True,
                 deny_action: str = "ignore"):
        self.hooks = hooks
        self.cache_size = cache_size
        self.cache_ttl = cache_ttl
        self.cache_enable = cache_enable
        # authz.deny_action: "ignore" answers the op with NOT_AUTHORIZED,
        # "disconnect" drops the connection (emqx_access_control parity)
        self.deny_action = deny_action

    def make_cache(self) -> Optional["AuthzCache"]:
        """Per-channel verdict cache honoring this facade's settings
        (None when authz.cache_enable = false)."""
        if not self.cache_enable:
            return None
        return AuthzCache(self.cache_size, self.cache_ttl)

    # -- authenticate -----------------------------------------------------

    def authenticate(self, clientinfo: ClientInfo) -> Dict[str, Any]:
        """Run the authenticate chain.

        Result dict: {"result": allow|deny, "reason_code": rc, ...extras
        (is_superuser, expire_at)}. Default (no hooks) = allow, mirroring
        the reference's allow_anonymous default.
        """
        acc = {"result": ALLOW}
        out = self.hooks.run_fold("client.authenticate", (clientinfo,), acc)
        return out if isinstance(out, dict) else acc

    # -- authorize --------------------------------------------------------

    def authorize(
        self,
        clientinfo: ClientInfo,
        action: str,
        topic: str,
        cache: Optional["AuthzCache"] = None,
    ) -> str:
        if clientinfo.is_superuser:
            return ALLOW
        if cache is not None:
            hit = cache.get(action, topic)
            if hit is not None:
                return hit
        verdict = self.hooks.run_fold(
            "client.authorize", (clientinfo, action, topic), ALLOW
        )
        if verdict not in (ALLOW, DENY):
            verdict = ALLOW
        if cache is not None:
            cache.put(action, topic, verdict)
        return verdict


class AuthzCache:
    """Per-channel LRU of authorize verdicts (`emqx_authz_cache` analog)."""

    def __init__(self, max_size: int = 32, ttl: float = 60.0):
        self.max_size = max_size
        self.ttl = ttl
        self._d: Dict[Tuple[str, str], Tuple[str, float]] = {}

    def get(self, action: str, topic: str) -> Optional[str]:
        ent = self._d.get((action, topic))
        if ent is None:
            return None
        verdict, ts = ent
        if time.monotonic() - ts > self.ttl:
            del self._d[(action, topic)]
            return None
        return verdict

    def put(self, action: str, topic: str, verdict: str) -> None:
        if len(self._d) >= self.max_size:
            self._d.pop(next(iter(self._d)))
        self._d[(action, topic)] = (verdict, time.monotonic())

    def drain(self) -> None:
        self._d.clear()
