"""Persistent sessions: checkpoint/resume across broker restarts.

Analog of `emqx_persistent_session.erl` + its mnesia ram/disc backends
(SURVEY.md §5.4): disconnected sessions with a nonzero expiry interval
are checkpointed (subscriptions, inflight window, message queue,
QoS2 dedup set) and restored on boot — routes re-enter the match
engine, pending messages replay to the resuming client.

Redesign notes:
  * the engine's HBM tables are a cache over host truth; host truth is
    rebuilt from this store on restart (`restore()`), so the device
    state needs no checkpoint of its own — the failure model the
    reference applies to mnesia-vs-trie applies to host-vs-HBM here;
  * instead of per-message mnesia tables + marker-based replay, each
    parked session snapshots atomically to one JSON file (temp+rename);
    offline enqueues mark the session dirty and `tick()` (driven by the
    listener housekeeping loop) re-snapshots — crash loses at most one
    tick of offline messages, the same at-most-once window the
    reference's async rlog persistence has;
  * GC of expired stored sessions mirrors `emqx_persistent_session_gc`.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import os
import tempfile
import time
from typing import Dict, List, Optional

from .inflight import InflightEntry
from .message import Message
from .packet import SubOpts
from .session import Session


# ------------------------------------------------------- serialization

def message_to_dict(msg: Message) -> dict:
    return {
        "topic": msg.topic,
        "payload": base64.b64encode(msg.payload).decode(),
        "qos": msg.qos,
        "retain": msg.retain,
        "dup": msg.dup,
        "from": msg.from_client,
        "username": msg.from_username,
        "mid": msg.mid.hex(),
        "ts": msg.timestamp,
        "props": {
            str(k): v
            for k, v in msg.properties.items()
            if isinstance(v, (int, float, str, bool))
        },
        # headers carry routing tags (e.g. "shared" -> (group, filter)
        # for redispatch-on-death); keep the JSON-safe ones
        "headers": {
            k: list(v) if isinstance(v, tuple) else v
            for k, v in msg.headers.items()
            if isinstance(v, (int, float, str, bool, list, tuple))
        },
    }


def message_from_dict(d: dict) -> Message:
    props = {}
    for k, v in (d.get("props") or {}).items():
        try:
            props[int(k)] = v
        except ValueError:
            props[k] = v
    return Message(
        topic=d["topic"],
        payload=base64.b64decode(d.get("payload", "")),
        qos=d.get("qos", 0),
        retain=d.get("retain", False),
        dup=d.get("dup", False),
        from_client=d.get("from", ""),
        from_username=d.get("username"),
        mid=bytes.fromhex(d["mid"]) if d.get("mid") else b"",
        timestamp=d.get("ts", 0),
        properties=props,
        headers=dict(d.get("headers") or {}),
    )


def session_to_dict(
    s: Session, expire_at: float, cursor: Optional[dict] = None
) -> dict:
    """Session snapshot dict.

    Legacy form (cursor=None) embeds the whole mqueue — the
    O(queue depth) rewrite the durable log replaces.  Cursor form
    (`ds.enable`) persists (subscriptions, inflight, dedup, cursor)
    plus the RESIDUAL mqueue — the messages the log never owns (QoS0
    and shared-group QoS>=1 copies, which stay on the in-memory path);
    everything else is reconstructed by replaying the shared log from
    the per-shard cursor on resume (ds/manager.py).  The residual is
    omitted when empty, the common case."""
    d = {
        "clientid": s.clientid,
        "expiry_interval": s.expiry_interval,
        "expire_at": None if expire_at == float("inf") else expire_at,
        "upgrade_qos": s.upgrade_qos,
        "retry_interval": s.retry_interval,
        "max_awaiting_rel": s.max_awaiting_rel,
        "await_rel_timeout": s.await_rel_timeout,
        "created_at": s.created_at,
        "next_pid": s._next_pid,
        "max_inflight": s.inflight.max_size,
        "max_mqueue": s.mqueue.max_len,
        "store_qos0": s.mqueue.store_qos0,
        "subscriptions": {
            f: dataclasses.asdict(o) for f, o in s.subscriptions.items()
        },
        "mqueue": [message_to_dict(m) for m in s.mqueue.peek_all()],
        "inflight": [
            {
                "pid": pid,
                "phase": e.phase,
                "message": message_to_dict(e.message) if e.message else None,
            }
            for pid, e in s.inflight.items()
        ],
        "awaiting_rel": list(s.awaiting_rel.keys()),
    }
    if cursor is not None:
        if not d["mqueue"]:
            del d["mqueue"]
        d["cursor"] = {str(k): list(v) for k, v in cursor.items()}
        # cursor-handoff takeover (ds/repl.py): a cursor pointing into
        # ANOTHER node's log names its origin; replay resolves it
        # against the local mirror
        node = getattr(s, "ds_cursor_node", None)
        if node:
            d["cursor_node"] = node
    return d


def session_from_dict(d: dict) -> Session:
    s = Session(
        clientid=d["clientid"],
        clean_start=False,
        expiry_interval=d.get("expiry_interval", 0),
        max_inflight=d.get("max_inflight", 32),
        max_mqueue=d.get("max_mqueue", 1000),
        store_qos0=d.get("store_qos0", True),
        upgrade_qos=d.get("upgrade_qos", False),
        retry_interval=d.get("retry_interval", 30.0),
        max_awaiting_rel=d.get("max_awaiting_rel", 100),
        await_rel_timeout=d.get("await_rel_timeout", 300.0),
        created_at=d.get("created_at"),
    )
    s._next_pid = d.get("next_pid", 1)
    for f, o in (d.get("subscriptions") or {}).items():
        s.subscriptions[f] = SubOpts(**o)
    for m in d.get("mqueue") or []:
        s.mqueue.insert(message_from_dict(m))
    now = time.monotonic()
    for e in d.get("inflight") or []:
        s.inflight.insert(
            e["pid"],
            InflightEntry(
                phase=e["phase"],
                message=message_from_dict(e["message"]) if e["message"] else None,
                ts=now,
            ),
        )
    for pid in d.get("awaiting_rel") or []:
        s.awaiting_rel[pid] = now
    if d.get("cursor") is not None:
        s.ds_cursor = {
            int(k): (int(v[0]), int(v[1]))
            for k, v in d["cursor"].items()
        }
        if d.get("cursor_node"):
            s.ds_cursor_node = d["cursor_node"]
    return s


# ------------------------------------------------------------- backends

class RamBackend:
    """In-memory store (`emqx_persistent_session_mnesia_ram_backend`)."""

    def __init__(self) -> None:
        self._d: Dict[str, dict] = {}

    def save(self, clientid: str, data: dict) -> None:
        self._d[clientid] = data

    def delete(self, clientid: str) -> None:
        self._d.pop(clientid, None)

    def load_all(self) -> List[dict]:
        return list(self._d.values())

    def clear(self) -> None:
        self._d.clear()


class DiscBackend:
    """One JSON file per session, atomic temp+rename writes."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, clientid: str) -> str:
        name = base64.urlsafe_b64encode(clientid.encode()).decode().rstrip("=")
        return os.path.join(self.dir, name + ".session.json")

    def save(self, clientid: str, data: dict) -> None:
        path = self._path(clientid)
        fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(data, f, separators=(",", ":"))
                # fsync BEFORE the atomic rename: without it a power
                # loss right after the rename can surface an empty or
                # partial file as the session snapshot (the same
                # temp+fsync+rename discipline as checkpoint/store.py)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def delete(self, clientid: str) -> None:
        try:
            os.unlink(self._path(clientid))
        except FileNotFoundError:
            pass

    def load_all(self) -> List[dict]:
        out = []
        for name in os.listdir(self.dir):
            if not name.endswith(".session.json"):
                continue
            try:
                with open(os.path.join(self.dir, name)) as f:
                    out.append(json.load(f))
            except (OSError, json.JSONDecodeError):
                continue
        return out

    def clear(self) -> None:
        for name in os.listdir(self.dir):
            if name.endswith(".session.json"):
                try:
                    os.unlink(os.path.join(self.dir, name))
                except OSError:
                    pass


# -------------------------------------------------------------- manager

class SessionPersistence:
    def __init__(self, broker, backend=None):
        self.broker = broker
        self.backend = backend if backend is not None else RamBackend()
        self._dirty: set = set()
        self._orig_on_discard = broker.cm.on_discard
        broker.cm.on_park = self._on_park
        broker.cm.on_discard = self._on_discard
        broker.cm.on_resume = self.on_resume
        broker.persistence = self

    # ------------------------------------------------------- write points

    @property
    def ds(self):
        """The broker's durable message log, when enabled (ds/)."""
        return getattr(self.broker, "ds", None)

    def _on_park(self, clientid: str, session: Session, expire_at: float) -> None:
        ds = self.ds
        if ds is not None:
            # cursor form: the log owns the message bytes from here —
            # park_session spills QoS>=1 mqueue overflow into the log
            # (past the cursor) and the record carries no mqueue at all
            cursor = ds.park_session(session)
            self.backend.save(
                clientid, session_to_dict(session, expire_at, cursor=cursor)
            )
        else:
            self.backend.save(clientid, session_to_dict(session, expire_at))
        self._dirty.discard(clientid)

    def _on_discard(self, session: Session) -> None:
        self.backend.delete(session.clientid)
        self._dirty.discard(session.clientid)
        if self._orig_on_discard is not None:
            self._orig_on_discard(session)

    def mark_dirty(self, clientid: str) -> None:
        ent = self.broker.cm.pending.get(clientid)
        if ent is None:
            return
        if self.ds is not None and not len(ent[0].mqueue):
            # cursor-form records are static while parked as long as
            # every offline enqueue lands in the shared log; only a
            # residual in-memory enqueue (a shared-group QoS>=1 copy,
            # or QoS0) changes the record and needs a re-snapshot
            return
        self._dirty.add(clientid)

    def on_resume(
        self, clientid: str, session: Optional[Session] = None
    ) -> None:
        """Client reconnected: the live channel owns the session now.
        With the durable log enabled, the mqueue is rebuilt here by
        replaying from the session's park cursor."""
        ds = self.ds
        if ds is not None and session is not None:
            ds.replay_into(session)
        self.backend.delete(clientid)
        self._dirty.discard(clientid)

    def on_handoff(self, clientid: str) -> None:
        """Session shipped to another node in cursor-handoff form
        (ds/repl.py): drop the on-disc copy — the taker owns the state
        now — WITHOUT the replay half of `on_resume`.  Not replaying
        the queue here is the whole point of the handoff."""
        self.backend.delete(clientid)
        self._dirty.discard(clientid)

    def tick(self, now: Optional[float] = None) -> int:
        """Flush dirty parked sessions + GC expired store entries."""
        n = 0
        for cid in list(self._dirty):
            ent = self.broker.cm.pending.get(cid)
            if ent is None:
                self._dirty.discard(cid)
                continue
            session, expire_at = ent
            # a ds session re-snapshots in cursor form (its cursor
            # must survive the rewrite — dropping it would migrate the
            # session afresh on restore and orphan the log window
            # between its old cursor and the migration-time end)
            self.backend.save(cid, session_to_dict(
                session, expire_at,
                cursor=getattr(session, "ds_cursor", None)))
            self._dirty.discard(cid)
            n += 1
        return n

    # ------------------------------------------------------------ restore

    def restore(self, now: Optional[float] = None) -> int:
        """Rebuild cm.pending + engine routes from the store (boot path).

        One-shot migration: on the first boot with `ds.enable`, a
        legacy snapshot (embedded mqueue, no cursor) has its queued
        messages appended to the durable log and its file rewritten in
        cursor form — the cursor is taken BEFORE the appends, so the
        session's own resume replays them back.  N legacy sessions
        holding copies of the same broadcast message append N records
        (the spill path must not mid-dedup; see DsManager.append), but
        replay's receiver-side mid dedup still delivers each exactly
        once per session."""
        now = now if now is not None else time.time()
        ds = self.ds
        restored = 0
        for data in self.backend.load_all():
            expire_at = data.get("expire_at")
            if expire_at is not None and expire_at <= now:
                self.backend.delete(data["clientid"])
                continue
            session = session_from_dict(data)
            cid = session.clientid
            if ds is not None and session.ds_cursor is None:
                cursor = ds.park_session(session)  # migrate: queue -> log
                self.backend.save(
                    cid, session_to_dict(session, _expire(expire_at),
                                         cursor=cursor)
                )
            self.broker.cm.pending[cid] = (
                session,
                expire_at if expire_at is not None else float("inf"),
            )
            for filt, opts in session.subscriptions.items():
                self.broker.subscribe(cid, filt, opts)
            restored += 1
        if ds is not None:
            ds.flush_all()  # migrated messages are durable before serving
        return restored


def _expire(expire_at: Optional[float]) -> float:
    return expire_at if expire_at is not None else float("inf")
