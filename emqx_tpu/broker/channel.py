"""Per-client MQTT protocol state machine.

Analog of `emqx_channel.erl` (1,837 LoC pure-functional FSM, SURVEY.md §1.5):
drives CONNECT/auth/session-open, the publish/subscribe pipelines with authz
and topic-alias handling, QoS ack flows, will messages, and disconnect.
Transport-agnostic: `handle_in(packet)` returns a list of actions the
connection executes (('send', pkt) / ('close', reason) / ...), mirroring the
reference's `{ok, Replies, Channel}` returns.
"""

from __future__ import annotations

import itertools
import time
import uuid
from collections import deque
from dataclasses import dataclass, replace
from typing import Any, Dict, List, Optional, Tuple

from . import packet as pkt
from . import topic as topiclib
from .access_control import ALLOW, AccessControl, ClientInfo, DENY, PUB, SUB
from .broker import Broker
from .message import Message, now_ms
from ..observe import spans as _spans
from .packet import PacketType, Property, ReasonCode, SubOpts
from .delivery import scatter_template
from .session import Session, SessionError

Action = Tuple[str, Any]  # ('send', Packet) | ('close', rc|None) | ('connected',)

IDLE, CONNECTED, DISCONNECTED = "idle", "connected", "disconnected"
AUTHENTICATING = "authenticating"  # mid enhanced-auth handshake (v5 AUTH)


@dataclass
class ChannelConfig:
    max_inflight: int = 32
    max_mqueue: int = 1000
    max_awaiting_rel: int = 100
    await_rel_timeout: float = 300.0
    retry_interval: float = 30.0
    upgrade_qos: bool = False
    max_qos_allowed: int = 2
    retain_available: bool = True
    wildcard_sub_available: bool = True
    shared_sub_available: bool = True
    max_topic_levels: int = 128
    max_session_expiry: int = 7200
    max_topic_alias: int = 65535
    server_keepalive: Optional[int] = None
    max_clientid_len: int = 65535
    max_packet_size: int = 1_048_576
    mqueue_store_qos0: bool = True
    keepalive_multiplier: float = 1.5
    idle_timeout: float = 15.0
    mountpoint: Optional[str] = None
    # retained re-delivery flow control (emqx_retainer.erl:85-150)
    retained_batch: int = 1000
    retained_interval: float = 0.05


class Channel:
    def __init__(
        self,
        broker: Broker,
        access: Optional[AccessControl] = None,
        config: Optional[ChannelConfig] = None,
        peername: str = "",
        conn_mod: str = "tcp",
    ):
        self.broker = broker
        self.access = access or getattr(
            broker, "access_control", None
        ) or AccessControl(broker.hooks)
        self.cfg = config or ChannelConfig()
        self.state = IDLE
        self.peername = peername
        self.conn_mod = conn_mod
        # peer TLS cert subject (cn/dn) set by a TLS listener before CONNECT;
        # cert_as_* mirror the listener's peer_cert_as_username/clientid opts
        self.peer_cert: Dict[str, str] = {}
        self.cert_as_username: Optional[str] = None
        self.cert_as_clientid: Optional[str] = None

        self.clientinfo = ClientInfo(peerhost=peername)
        self.session: Optional[Session] = None
        self.clientid: str = ""
        self.proto_ver = pkt.MQTT_V4
        self.keepalive = 0
        self.clean_start = True
        self.expiry_interval = 0
        self.client_receive_max = 65535  # CONNECT Receive Maximum
        self.client_max_packet: Optional[int] = None
        self.client_alias_max = 0  # CONNECT Topic Alias Maximum
        self.will_msg: Optional[Message] = None
        self.will_delay = 0
        self.authz_cache = self.access.make_cache()
        self.alias_in: Dict[int, str] = {}  # inbound topic aliases (v5)
        self.alias_out: Dict[str, int] = {}
        self.connected_at: Optional[float] = None
        self.disconnect_reason: Optional[int] = None
        # connect-time enhanced auth: stashed CONNECT while AUTH rounds run
        self._pending_connect: Optional[tuple] = None
        self._auth_method: Optional[str] = None
        # cross-node session sync: phase2 args stashed while the async
        # cluster takeover/discard runs (post-auth, pre-open_session)
        self._pending_phase2: Optional[tuple] = None
        self._cluster_synced = False
        self._takeover = False
        # connection layer integration: out_cb receives actions produced
        # outside handle_in (broker deliveries, kicks); tests collect them.
        self.out_cb = lambda actions: None
        self.on_kick = None
        self._will_on_normal = False
        # Optional async publish path (PublishBatcher.submit). When set,
        # publish acks are deferred via ('ack_async', future, builder)
        # actions so a whole tick of publishes shares one device match.
        self.publish_fn = None
        # broadcast scatter lane eligibility (broker._scatter_one_filter):
        # True once the connection's statics allow receiver-invariant
        # delivery (no mountpoint/alias/max-packet/upgrade-qos); the
        # broker then serves this channel's plain QoS0 subscriptions
        # from a shared action list.  scatter_plain aliases the
        # session's per-filter map for one-hop access.
        self.scatter_fast = False
        self.scatter_plain: Dict[str, bool] = {}

    # ------------------------------------------------------------- helpers

    @property
    def v5(self) -> bool:
        return self.proto_ver == pkt.MQTT_V5

    def _m(self, name: str, n: int = 1) -> None:
        self.broker.metrics.inc(name, n)

    def _close(self, rc: Optional[int], send_disconnect: bool = False) -> List[Action]:
        acts: List[Action] = []
        if send_disconnect and self.v5 and self.state == CONNECTED and rc is not None:
            acts.append(("send", pkt.Disconnect(reason_code=rc)))
            self._m("packets.disconnect.sent")
        acts.append(("close", rc))
        return acts

    # ------------------------------------------------------------ inbound

    def handle_in(self, p: pkt.Packet) -> List[Action]:
        self._m("packets.received")
        t = p.type
        if self.state == IDLE and t != PacketType.CONNECT:
            return self._close(ReasonCode.PROTOCOL_ERROR)
        if self.state == AUTHENTICATING and t not in (
            PacketType.AUTH,
            PacketType.DISCONNECT,
        ):
            # MQTT-3.15: only AUTH/DISCONNECT may flow mid-handshake
            return self._close(ReasonCode.PROTOCOL_ERROR)
        if self.state == CONNECTED and t == PacketType.CONNECT:
            return self._close(ReasonCode.PROTOCOL_ERROR, send_disconnect=True)
        handler = {
            PacketType.CONNECT: self._in_connect,
            PacketType.PUBLISH: self._in_publish,
            PacketType.PUBACK: self._in_puback,
            PacketType.PUBREC: self._in_pubrec,
            PacketType.PUBREL: self._in_pubrel,
            PacketType.PUBCOMP: self._in_pubcomp,
            PacketType.SUBSCRIBE: self._in_subscribe,
            PacketType.UNSUBSCRIBE: self._in_unsubscribe,
            PacketType.PINGREQ: self._in_pingreq,
            PacketType.DISCONNECT: self._in_disconnect,
            PacketType.AUTH: self._in_auth,
        }.get(t)
        if handler is None:
            return self._close(ReasonCode.PROTOCOL_ERROR)
        return handler(p)

    # -- CONNECT ----------------------------------------------------------

    def _connack_fail(self, rc: int) -> List[Action]:
        self._m("packets.connack.sent")
        self._m("client.connack")
        ack = pkt.Connack(session_present=False, reason_code=rc)
        return [("send", ack)] + self._close(rc)

    def _in_connect(self, p: pkt.Connect) -> List[Action]:
        self._m("packets.connect.received")
        self._m("client.connect")
        self.proto_ver = p.proto_ver
        self.clean_start = p.clean_start
        self.keepalive = p.keepalive

        clientid = p.clientid
        # TLS listeners may mint identity from the verified peer cert
        # (reference: peer_cert_as_clientid/username, esockd_peercert)
        if self.cert_as_clientid and self.peer_cert.get(self.cert_as_clientid):
            clientid = self.peer_cert[self.cert_as_clientid]
        if len(clientid) > self.cfg.max_clientid_len:
            return self._connack_fail(ReasonCode.CLIENT_IDENTIFIER_NOT_VALID)
        assigned = False
        if not clientid:
            if self.proto_ver == pkt.MQTT_V5 or p.clean_start:
                clientid = "auto-" + uuid.uuid4().hex[:16]
                assigned = True
            else:
                return self._connack_fail(ReasonCode.CLIENT_IDENTIFIER_NOT_VALID)

        if self.v5:
            self.expiry_interval = int(
                min(
                    p.properties.get(Property.SESSION_EXPIRY_INTERVAL, 0),
                    self.cfg.max_session_expiry,
                )
            )
            # MQTT-3.3.4-9: never exceed the client's Receive Maximum
            # of concurrent unacked QoS1/2 deliveries
            rm = p.properties.get(Property.RECEIVE_MAXIMUM)
            if rm is not None:
                if not isinstance(rm, int) or rm < 1:
                    return self._connack_fail(ReasonCode.PROTOCOL_ERROR)
                self.client_receive_max = rm
            # MQTT-3.1.2-24/25: never send a packet larger than the
            # client's Maximum Packet Size (0 is a protocol error)
            mp = p.properties.get(Property.MAXIMUM_PACKET_SIZE)
            if mp is not None:
                if not isinstance(mp, int) or mp < 1:
                    return self._connack_fail(ReasonCode.PROTOCOL_ERROR)
                self.client_max_packet = mp
            # the client's advertised inbound topic-alias window: the
            # server may substitute aliases for long topics outbound
            self.client_alias_max = int(
                p.properties.get(Property.TOPIC_ALIAS_MAXIMUM, 0) or 0
            )
        else:
            self.expiry_interval = 0 if p.clean_start else self.cfg.max_session_expiry

        username = p.username
        if self.cert_as_username and self.peer_cert.get(self.cert_as_username):
            username = self.peer_cert[self.cert_as_username]
        self.clientinfo = ClientInfo(
            clientid=clientid,
            username=username,
            password=p.password,
            peerhost=self.peername,
            proto_ver=p.proto_ver,
            mountpoint=self.cfg.mountpoint,
        )
        if self.peer_cert:
            self.clientinfo.attrs["peer_cert"] = dict(self.peer_cert)

        # enhanced (SASL-style) auth at CONNECT (MQTT-4.12): the v5
        # AUTHENTICATION_METHOD property opens an AUTH-packet handshake
        # instead of the password check (reference: emqx_channel
        # enhanced_auth / emqx_authn SCRAM providers)
        method = (
            p.properties.get(Property.AUTHENTICATION_METHOD)
            if self.v5
            else None
        )
        extra_props: pkt.Properties = {}
        if method:
            data = p.properties.get(Property.AUTHENTICATION_DATA, b"")
            out = self.broker.hooks.run_fold(
                "client.enhanced_auth_start",
                (self.clientinfo, method, data),
                None,
            )
            if out is None:
                self._m("authentication.failure")
                return self._connack_fail(ReasonCode.BAD_AUTHENTICATION_METHOD)
            action, payload = out
            if action == "continue":
                self._pending_connect = (p, clientid, username, assigned)
                self._auth_method = method
                self.state = AUTHENTICATING
                self._m("packets.auth.sent")
                return [
                    (
                        "send",
                        pkt.Auth(
                            reason_code=ReasonCode.CONTINUE_AUTHENTICATION,
                            properties={
                                Property.AUTHENTICATION_METHOD: method,
                                Property.AUTHENTICATION_DATA: payload or b"",
                            },
                        ),
                    )
                ]
            if action != "ok":
                self._m("authentication.failure")
                return self._connack_fail(ReasonCode.NOT_AUTHORIZED)
            auth = {"result": ALLOW}
            if isinstance(payload, dict):
                auth.update(payload)
            elif isinstance(payload, (bytes, bytearray)):
                extra_props[Property.AUTHENTICATION_METHOD] = method
                extra_props[Property.AUTHENTICATION_DATA] = bytes(payload)
        else:
            auth = self.access.authenticate(self.clientinfo)
        if auth.get("result") != ALLOW:
            self._m("authentication.failure")
            return self._connack_fail(
                auth.get("reason_code", ReasonCode.NOT_AUTHORIZED)
            )
        return self._connect_phase2(p, clientid, username, assigned, auth,
                                    extra_props)

    def _connect_phase2(
        self,
        p: pkt.Connect,
        clientid: str,
        username,
        assigned: bool,
        auth: dict,
        extra_props: Optional[pkt.Properties] = None,
    ) -> List[Action]:
        """Post-authentication half of CONNECT processing: hooks, will,
        session open, CONNACK.  Split out so the enhanced-auth handshake
        can resume here after its AUTH rounds."""
        # cross-node session sync runs ONLY after authentication (an
        # unauthenticated CONNECT must never be able to kick or pull
        # another node's session); the connection awaits the RPCs and
        # re-enters via finish_cluster_sync
        cluster = getattr(self.broker, "cluster", None)
        if cluster is not None and not self._cluster_synced and not assigned:
            self._pending_phase2 = (
                p, clientid, username, assigned, auth, extra_props
            )
            self.state = AUTHENTICATING  # gate other packets meanwhile
            return [("cluster_sync", clientid, p.clean_start)]
        self._m("authentication.success")
        self.clientinfo.is_superuser = bool(auth.get("is_superuser"))
        for k in ("acl", "expire_at"):
            if k in auth:
                self.clientinfo.attrs[k] = auth[k]

        if self.broker.hooks.run_fold("client.connect", (self.clientinfo,), ALLOW) == DENY:
            return self._connack_fail(ReasonCode.BANNED)
        username = self.clientinfo.username

        # will message
        if p.will_flag:
            if p.will_qos > self.cfg.max_qos_allowed:
                return self._connack_fail(ReasonCode.QOS_NOT_SUPPORTED)
            if not topiclib.validate_name(p.will_topic or ""):
                return self._connack_fail(ReasonCode.TOPIC_NAME_INVALID)
            if p.will_retain and not self.cfg.retain_available:
                return self._connack_fail(ReasonCode.RETAIN_NOT_SUPPORTED)
            self.will_delay = int(p.will_props.get(Property.WILL_DELAY_INTERVAL, 0))
            self.will_msg = Message(
                topic=topiclib.prepend_mountpoint(self.cfg.mountpoint, p.will_topic or ""),
                payload=p.will_payload or b"",
                qos=p.will_qos,
                retain=p.will_retain,
                from_client=clientid,
                from_username=username,
                properties=dict(p.will_props),
            )

        self.clientid = clientid
        session, present = self.broker.cm.open_session(
            p.clean_start, clientid, self._make_session
        )
        if present:
            # MQTT-3.3.4-9 applies per CONNECTION: a resumed session
            # must honor THIS connection's Receive Maximum, not the
            # previous one's
            session.inflight.max_size = min(self.cfg.max_inflight,
                                            self.client_receive_max)
            # and carries the LATEST connection's username for
            # offline-session queries
            session.username = getattr(self.clientinfo, "username",
                                       None)
        self.session = session
        if present and not session.scatter_plain and session.subscriptions:
            # disk-restored sessions write `subscriptions` directly and
            # skip Session.subscribe — rebuild the plain map here so
            # resumed receivers rejoin the broadcast fast lane
            for f, o in session.subscriptions.items():
                session.scatter_plain[f] = (
                    not o.no_local
                    and not o.retain_as_published
                    and o.sub_id is None
                )
        self.scatter_fast = (
            self.cfg.mountpoint is None
            and self.client_max_packet is None
            and not (self.v5 and self.client_alias_max)
            and not session.upgrade_qos
        )
        self.scatter_plain = session.scatter_plain
        self._m("session.resumed" if present else "session.created")
        self.state = CONNECTED
        self.connected_at = time.time()
        self.broker.cm.register_channel(self)

        props: pkt.Properties = dict(extra_props or {})
        if self.v5:
            if assigned:
                props[Property.ASSIGNED_CLIENT_IDENTIFIER] = clientid
            if self.cfg.server_keepalive is not None:
                props[Property.SERVER_KEEP_ALIVE] = self.cfg.server_keepalive
                self.keepalive = self.cfg.server_keepalive
            if self.cfg.max_qos_allowed < 2:
                props[Property.MAXIMUM_QOS] = self.cfg.max_qos_allowed
            if not self.cfg.retain_available:
                props[Property.RETAIN_AVAILABLE] = 0
            if not self.cfg.wildcard_sub_available:
                props[Property.WILDCARD_SUBSCRIPTION_AVAILABLE] = 0
            if not self.cfg.shared_sub_available:
                props[Property.SHARED_SUBSCRIPTION_AVAILABLE] = 0
            props[Property.TOPIC_ALIAS_MAXIMUM] = self.cfg.max_topic_alias
            if self.cfg.max_packet_size < 268_435_455:
                # advertise the server's inbound limit (a bigger inbound
                # packet is rejected at the frame scan with 0x95)
                props[Property.MAXIMUM_PACKET_SIZE] = \
                    self.cfg.max_packet_size
            # the broker's inbound QoS2 window IS its Receive Maximum
            # (QoS1 publishes are acked synchronously, so only
            # unreleased QoS2 flows count against it) — advertised so a
            # conformant client throttles; violators are disconnected
            # with 0x93 (MQTT-3.3.4-7/9).  0 (= unlimited here) must be
            # OMITTED: Receive Maximum 0 is a protocol error
            # (MQTT-3.2.2.3.3), and the u16 property caps at 65535
            if 0 < self.cfg.max_awaiting_rel <= 0xFFFF:
                props[Property.RECEIVE_MAXIMUM] = self.cfg.max_awaiting_rel
            if self.expiry_interval != int(
                p.properties.get(Property.SESSION_EXPIRY_INTERVAL, 0)
            ):
                props[Property.SESSION_EXPIRY_INTERVAL] = self.expiry_interval

        self._m("packets.connack.sent")
        self._m("client.connack")
        self._m("client.connected")
        self.broker.hooks.run("client.connected", (self.clientinfo,))
        acts: List[Action] = [
            ("send", pkt.Connack(session_present=present, reason_code=0, properties=props)),
            ("connected",),
        ]
        if present:
            for d in session.replay():
                acts.extend(self._deliveries_out([d]))
        return acts

    def finish_cluster_sync(self) -> List[Action]:
        """Resume CONNECT processing after the async cluster session
        sync completed (or failed best-effort)."""
        if self._pending_phase2 is None:
            return []
        p, clientid, username, assigned, auth, extra_props = (
            self._pending_phase2
        )
        self._pending_phase2 = None
        self._cluster_synced = True
        return self._connect_phase2(
            p, clientid, username, assigned, auth, extra_props
        )

    def _make_session(self) -> Session:
        return Session(
            clientid=self.clientid,
            username=getattr(self.clientinfo, "username", None),
            clean_start=self.clean_start,
            expiry_interval=self.expiry_interval,
            max_inflight=min(self.cfg.max_inflight,
                             self.client_receive_max),
            max_mqueue=self.cfg.max_mqueue,
            upgrade_qos=self.cfg.upgrade_qos,
            retry_interval=self.cfg.retry_interval,
            max_awaiting_rel=self.cfg.max_awaiting_rel,
            await_rel_timeout=self.cfg.await_rel_timeout,
            store_qos0=self.cfg.mqueue_store_qos0,
        )

    # -- PUBLISH ----------------------------------------------------------

    def _resolve_alias(self, p: pkt.Publish) -> Optional[str]:
        if not self.v5:
            return p.topic
        alias = p.properties.get(Property.TOPIC_ALIAS)
        if alias is not None:
            if alias == 0 or alias > self.cfg.max_topic_alias:
                return None
            if p.topic:
                self.alias_in[alias] = p.topic
                return p.topic
            return self.alias_in.get(alias)
        return p.topic

    def _in_publish(self, p: pkt.Publish) -> List[Action]:
        self._m("packets.publish.received")
        self._m(f"messages.qos{p.qos}.received")
        topic = self._resolve_alias(p)
        if topic is None:
            return self._close(ReasonCode.TOPIC_ALIAS_INVALID, send_disconnect=True)
        if not topiclib.validate_name(topic):
            return self._puberr(p, ReasonCode.TOPIC_NAME_INVALID)
        if p.qos > self.cfg.max_qos_allowed:
            return self._close(ReasonCode.QOS_NOT_SUPPORTED, send_disconnect=True)
        if p.retain and not self.cfg.retain_available:
            return self._close(ReasonCode.RETAIN_NOT_SUPPORTED, send_disconnect=True)
        if topiclib.levels(topic) > self.cfg.max_topic_levels:
            return self._puberr(p, ReasonCode.TOPIC_NAME_INVALID)

        if self.access.authorize(self.clientinfo, PUB, topic, self.authz_cache) == DENY:
            self._m("authorization.deny")
            if self.access.deny_action == "disconnect":
                return self._close(ReasonCode.NOT_AUTHORIZED,
                                   send_disconnect=True)
            return self._puberr(p, ReasonCode.NOT_AUTHORIZED)
        self._m("authorization.allow")

        full_topic = topiclib.prepend_mountpoint(self.cfg.mountpoint, topic)
        msg = Message(
            topic=full_topic,
            payload=p.payload,
            qos=p.qos,
            retain=p.retain,
            from_client=self.clientid,
            from_username=self.clientinfo.username,
            properties={
                k: v for k, v in p.properties.items() if k != Property.TOPIC_ALIAS
            },
        )

        if p.qos == 0:
            if self.publish_fn is not None:
                self.publish_fn(msg)  # batched; no ack to produce
            else:
                self.broker.publish(msg)
            return []
        if p.qos == 1:
            return self._pub_ack(msg, p.packet_id, pkt.PubAck, "packets.puback.sent")
        # qos 2
        try:
            self.session.publish_qos2(p.packet_id)
        except SessionError as e:
            if (
                self.v5
                and e.reason_code == ReasonCode.RECEIVE_MAXIMUM_EXCEEDED
            ):
                # client ignored the advertised Receive Maximum: this is
                # a protocol violation, not flow control — DISCONNECT
                # 0x93 (MQTT-3.3.4-9; the reference does the same,
                # emqx_channel handle_in publish error path)
                self._m("packets.publish.quota_exceeded")
                return self._close(
                    ReasonCode.RECEIVE_MAXIMUM_EXCEEDED,
                    send_disconnect=True,
                )
            return [("send", pkt.PubRec(packet_id=p.packet_id, reason_code=e.reason_code))]
        return self._pub_ack(msg, p.packet_id, pkt.PubRec, "packets.pubrec.sent")

    def _pub_ack(self, msg: Message, packet_id: int, cls, metric: str) -> List[Action]:
        """Ack a qos>0 publish; deferred when the batched path is active."""

        def mk(n: int):
            self._m(metric)
            rc = 0 if n else (ReasonCode.NO_MATCHING_SUBSCRIBERS if self.v5 else 0)
            return cls(packet_id=packet_id, reason_code=rc)

        if self.publish_fn is not None:
            return [("ack_async", self.publish_fn(msg), mk)]
        return [("send", mk(self.broker.publish(msg)))]

    def _puberr(self, p: pkt.Publish, rc: int) -> List[Action]:
        """Error response appropriate to the publish qos/version."""
        if p.qos == 0:
            if rc in (ReasonCode.TOPIC_NAME_INVALID,):
                return self._close(rc, send_disconnect=True)
            return []  # silently drop (authz deny on qos0)
        cls = pkt.PubAck if p.qos == 1 else pkt.PubRec
        if self.v5:
            return [("send", cls(packet_id=p.packet_id, reason_code=rc))]
        # v3: no way to signal; disconnect on protocol violations
        if rc == ReasonCode.TOPIC_NAME_INVALID:
            return self._close(rc)
        return []

    # -- acks -------------------------------------------------------------

    def _in_puback(self, p: pkt.PubAck) -> List[Action]:
        self._m("packets.puback.received")
        try:
            msg, more = self.session.puback(p.packet_id)
            self._m("messages.acked")
            self.broker.hooks.run("message.acked", (self.clientid, msg))
            return self._deliveries_out(more)
        except SessionError:
            self._m("packets.puback.missed")
            return []

    def _in_pubrec(self, p: pkt.PubRec) -> List[Action]:
        self._m("packets.pubrec.received")
        try:
            msg = self.session.pubrec(p.packet_id)
            self._m("messages.acked")
            self.broker.hooks.run("message.acked", (self.clientid, msg))
            self._m("packets.pubrel.sent")
            return [("send", pkt.PubRel(packet_id=p.packet_id))]
        except SessionError as e:
            self._m("packets.pubrec.missed")
            if self.v5:
                return [("send", pkt.PubRel(packet_id=p.packet_id, reason_code=e.reason_code))]
            return [("send", pkt.PubRel(packet_id=p.packet_id))]

    def _in_pubrel(self, p: pkt.PubRel) -> List[Action]:
        self._m("packets.pubrel.received")
        found = self.session.pubrel(p.packet_id)
        rc = 0 if found else ReasonCode.PACKET_IDENTIFIER_NOT_FOUND
        if not found:
            self._m("packets.pubrel.missed")
        self._m("packets.pubcomp.sent")
        return [("send", pkt.PubComp(packet_id=p.packet_id, reason_code=rc if self.v5 else 0))]

    def _in_pubcomp(self, p: pkt.PubComp) -> List[Action]:
        self._m("packets.pubcomp.received")
        try:
            more = self.session.pubcomp(p.packet_id)
            return self._deliveries_out(more)
        except SessionError:
            self._m("packets.pubcomp.missed")
            return []

    # -- SUBSCRIBE / UNSUBSCRIBE ------------------------------------------

    def _check_sub(self, tf: str, opts: SubOpts) -> int:
        group, real = topiclib.parse_share(tf)
        if group is not None and not self.cfg.shared_sub_available:
            return ReasonCode.SHARED_SUBSCRIPTIONS_NOT_SUPPORTED
        if not topiclib.validate_filter(real):
            return ReasonCode.TOPIC_FILTER_INVALID
        if topiclib.levels(real) > self.cfg.max_topic_levels:
            return ReasonCode.TOPIC_FILTER_INVALID
        if topiclib.wildcard(real) and not self.cfg.wildcard_sub_available:
            return ReasonCode.WILDCARD_SUBSCRIPTIONS_NOT_SUPPORTED
        if group is not None and opts.no_local:
            # v5 spec: no_local on a shared subscription is a protocol error
            return ReasonCode.PROTOCOL_ERROR
        if self.access.authorize(self.clientinfo, SUB, real, self.authz_cache) == DENY:
            self._m("authorization.deny")
            return ReasonCode.NOT_AUTHORIZED
        return min(opts.qos, self.cfg.max_qos_allowed)

    def _in_subscribe(self, p: pkt.Subscribe) -> List[Action]:
        self._m("packets.subscribe.received")
        self._m("client.subscribe")
        filters = self.broker.hooks.run_fold(
            "client.subscribe", (self.clientinfo, p.properties), p.topic_filters
        )
        codes: List[int] = []
        acts: List[Action] = []
        sub_id = None
        if self.v5:
            sids = p.properties.get(Property.SUBSCRIPTION_IDENTIFIER)
            if sids:
                sub_id = sids[0] if isinstance(sids, list) else sids
        # pass 1: grant + subscribe + CREATE every retained iterator
        # before consuming any — with the device retained index the
        # lookups queue up and the first consumption below flushes the
        # whole packet's filters as ONE batched index dispatch
        # (broker/retainer.py), the way publish ticks batch matching
        rits = []
        for tf, opts in filters:
            rc = self._check_sub(tf, opts)
            codes.append(rc)
            if rc > 2:
                continue
            granted = replace(opts, qos=rc, sub_id=sub_id)
            mounted = topiclib.mount_filter(self.cfg.mountpoint, tf)
            is_new = self.session.subscribe(mounted, granted)
            if is_new:
                # re-subscribes only update session opts; the engine
                # refcount must stay one per live subscription
                self.broker.subscribe(self.clientid, mounted, granted)
            else:
                self.broker.hooks.run(
                    "session.subscribed", (self.clientid, mounted, granted)
                )
            rh = granted.retain_handling if self.v5 else 0
            _g, real = topiclib.parse_share(mounted)
            rits.append((real, self.broker.retained_iter(mounted, rh, is_new)))
        # pass 2: retained messages (v5 retain-handling; v3 always
        # sends).  Deliveries beyond one batch are paced by the
        # connection (flow control, `emqx_retainer.erl:85-150`) so a
        # huge retained set cannot starve the event loop or flood the
        # socket in one burst.
        for real, rit in rits:
            for rmsg in itertools.islice(rit, self.cfg.retained_batch):
                rmsg = replace(rmsg, headers=dict(rmsg.headers, retained=True))
                for d in self.session.deliver([(real, rmsg)]):
                    acts.extend(self._delivery_to_send(d))
            nxt = next(rit, None)
            if nxt is not None:  # more than one batch: pace the rest
                acts.append(
                    ("retained_paced", real, itertools.chain([nxt], rit))
                )
        if (
            ReasonCode.NOT_AUTHORIZED in codes
            and self.access.deny_action == "disconnect"
        ):
            # authz.deny_action = disconnect applies to SUBSCRIBE too
            # (emqx_channel check_sub_authzs parity): SUBACK, then drop
            self._m("packets.suback.sent")
            return [
                ("send", pkt.SubAck(packet_id=p.packet_id,
                                    reason_codes=codes))
            ] + self._close(ReasonCode.NOT_AUTHORIZED, send_disconnect=True)
        self._m("packets.suback.sent")
        return [("send", pkt.SubAck(packet_id=p.packet_id, reason_codes=codes))] + acts

    def _in_unsubscribe(self, p: pkt.Unsubscribe) -> List[Action]:
        self._m("packets.unsubscribe.received")
        self._m("client.unsubscribe")
        codes: List[int] = []
        acts: List[Action] = []
        for tf in p.topic_filters:
            mounted = topiclib.mount_filter(self.cfg.mountpoint, tf)
            if self.session.unsubscribe(mounted) is not None:
                self.broker.unsubscribe(self.clientid, mounted)
                _g, real = topiclib.parse_share(mounted)
                acts.append(("retained_stop", real))  # halt paced tail
                codes.append(0)
            else:
                codes.append(ReasonCode.NO_SUBSCRIPTION_EXISTED)
        self._m("packets.unsuback.sent")
        return [("send", pkt.UnsubAck(packet_id=p.packet_id, reason_codes=codes))] + acts

    # -- PING / DISCONNECT / AUTH -----------------------------------------

    def _in_pingreq(self, p: pkt.PingReq) -> List[Action]:
        self._m("packets.pingreq.received")
        self._m("packets.pingresp.sent")
        return [("send", pkt.PingResp())]

    def _in_disconnect(self, p: pkt.Disconnect) -> List[Action]:
        self._m("packets.disconnect.received")
        if self.v5:
            exp = p.properties.get(Property.SESSION_EXPIRY_INTERVAL)
            if exp is not None:
                if self.expiry_interval == 0 and exp > 0:
                    return self._close(ReasonCode.PROTOCOL_ERROR, send_disconnect=True)
                self.expiry_interval = min(exp, self.cfg.max_session_expiry)
                if self.session:
                    self.session.expiry_interval = self.expiry_interval
        if p.reason_code == ReasonCode.DISCONNECT_WITH_WILL:
            self._will_on_normal = True  # MQTT-3.14.2-10: publish the will
        else:
            self.will_msg = None  # normal disconnect discards the will
        self.disconnect_reason = p.reason_code
        return [("close", None)]

    def _in_auth(self, p: pkt.Auth) -> List[Action]:
        self._m("packets.auth.received")
        # Enhanced (SASL-style) auth continuation: delegated to the
        # 'client.enhanced_auth' chain; without a registered provider it
        # is a protocol error, like a reference broker with no matching
        # authenticator.  Handlers get (clientinfo, method, data, acc).
        method = p.properties.get(Property.AUTHENTICATION_METHOD)
        data = p.properties.get(Property.AUTHENTICATION_DATA, b"")
        if method is not None and self._auth_method is not None and (
            method != self._auth_method
        ):
            # MQTT-4.12.0-5: the method must not change mid-handshake
            return self._auth_fail(ReasonCode.PROTOCOL_ERROR)
        out = self.broker.hooks.run_fold(
            "client.enhanced_auth", (self.clientinfo, method, data), None
        )
        if out is None:
            return self._auth_fail(ReasonCode.BAD_AUTHENTICATION_METHOD)
        action, payload = out
        if action == "continue":
            self._m("packets.auth.sent")
            return [
                (
                    "send",
                    pkt.Auth(
                        reason_code=ReasonCode.CONTINUE_AUTHENTICATION,
                        properties={
                            Property.AUTHENTICATION_METHOD: method or "",
                            Property.AUTHENTICATION_DATA: payload or b"",
                        },
                    ),
                )
            ]
        if action != "ok":
            self._m("authentication.failure")
            return self._auth_fail(ReasonCode.NOT_AUTHORIZED)
        final: pkt.Properties = {}
        if method:
            final[Property.AUTHENTICATION_METHOD] = method
        if isinstance(payload, (bytes, bytearray)) and payload:
            final[Property.AUTHENTICATION_DATA] = bytes(payload)
        if self._pending_connect is not None:
            # connect-time handshake finished: the server's final SCRAM
            # data rides in CONNACK (MQTT-4.12.0-7)
            pc, clientid, username, assigned = self._pending_connect
            self._pending_connect = None
            # the provider may have set identity fields on clientinfo
            # (SCRAM authenticated username, superuser) — carry them over
            auth = {
                "result": ALLOW,
                "is_superuser": self.clientinfo.is_superuser,
            }
            return self._connect_phase2(
                pc, clientid, username, assigned, auth, final
            )
        # post-connect re-authentication: success AUTH closes the round
        return [("send", pkt.Auth(reason_code=0, properties=final))]

    def _auth_fail(self, rc: int) -> List[Action]:
        """Abort an enhanced-auth handshake: CONNACK-fail pre-connect,
        DISCONNECT post-connect."""
        if self.state == AUTHENTICATING or self._pending_connect is not None:
            self._pending_connect = None
            return self._connack_fail(rc)
        return self._close(rc, send_disconnect=True)

    # ----------------------------------------------------------- outbound

    def deliver(self, delivers: List[Tuple[str, Message]]) -> None:
        """Called by the broker dispatch; pushes actions to the connection."""
        acts = self._scatter_deliver(delivers)
        if acts is None:
            acts = self._deliveries_out(self.session.deliver(delivers))
        if acts:
            self.out_cb(acts)
        if _spans.armed:
            # wire boundary: out_cb flushed this batch to the transport
            # synchronously; the first receiver closes a sampled span's
            # wire stage (observe/spans.py — one attribute-load bool
            # test per flush batch when disarmed)
            _spans.wire(delivers)

    def _scatter_deliver(
        self, delivers: List[Tuple[str, Message]]
    ) -> Optional[List[Action]]:
        """QoS0 broadcast scatter: reuse ONE prebuilt PUBLISH packet
        (carrying the shared wire prefix) per (proto version, retain,
        sub-id) wire form across every receiver of a message — the
        per-receiver cost of the delivery hot loop collapses to two
        dict lookups and a list append.  Returns None (fall back to the
        full per-receiver path) whenever any item needs session state
        or per-receiver bytes: effective QoS > 0 (inflight/packet-id),
        outbound topic aliasing, a mountpoint strip, or an expiry-
        interval rewrite.  The fast path is side-effect-free until it
        commits, so a mid-batch fallback reprocesses the whole batch
        exactly once."""
        session = self.session
        v5 = self.proto_ver == pkt.MQTT_V5
        if (
            session is None
            or self.cfg.mountpoint is not None
            or (v5 and self.client_alias_max)
        ):
            return None
        subs = session.subscriptions
        upgrade = session.upgrade_qos
        acts: Optional[List[Action]] = None
        n = 0
        for filt, msg in delivers:
            opts = subs.get(filt)
            if opts is None:
                return None
            if (msg.qos or opts.qos) if upgrade else \
                    (msg.qos and opts.qos):
                return None  # effective qos > 0
            if Property.MESSAGE_EXPIRY_INTERVAL in msg.properties:
                return None
            if opts.no_local and msg.from_client == self.clientid:
                continue
            retain = msg.retain if (
                opts.retain_as_published or msg.headers.get("retained")
            ) else False
            key = (self.proto_ver, retain, opts.sub_id if v5 else None)
            headers = msg.headers
            cache = headers.get("__scatter")
            if cache is None:
                cache = headers["__scatter"] = {}
            ent = cache.get(key)
            if ent is None:
                ent = cache[key] = scatter_template(msg, key)
            tmpl, act = ent
            if self.client_max_packet is not None:
                from . import frame as framelib

                if framelib.exact_publish_size(tmpl, self.proto_ver) > \
                        self.client_max_packet:
                    return None  # slow path owns the drop accounting
            n += 1
            if acts is None:
                # the common single-delivery broadcast reuses the
                # template's cached one-action list outright (borrowed:
                # materialized below before any mutation)
                acts = act
            else:
                if n == 2:
                    acts = [acts[0]]  # materialize the borrowed list
                acts.append(act[0])
        if n:
            self._m("packets.publish.sent", n)
            self._m("messages.sent", n)
        return acts if acts is not None else []

    def _deliveries_out(self, ds) -> List[Action]:
        """Iterative drain: a dropped too-large delivery frees its
        window slot and APPENDS the refill to this queue instead of
        recursing (a long run of queued oversized messages would
        otherwise blow the recursion limit)."""
        acts: List[Action] = []
        queue = deque(ds)
        while queue:
            acts.extend(self._delivery_to_send(queue.popleft(), queue))
        return acts

    def _delivery_to_send(self, d, _followups=None) -> List[Action]:
        if d.message is None:  # pubrel resend
            self._m("packets.pubrel.sent")
            return [("send", pkt.PubRel(packet_id=d.packet_id))]
        msg = d.message
        props = dict(msg.properties)
        if Property.MESSAGE_EXPIRY_INTERVAL in props:
            # MQTT-3.3.2-6: forward the expiry MINUS the time spent
            # waiting in the server (expired messages were already
            # dropped by Session.deliver/dequeue/replay)
            waited = max(0, (now_ms() - msg.timestamp) // 1000)
            props[Property.MESSAGE_EXPIRY_INTERVAL] = max(
                1, int(props[Property.MESSAGE_EXPIRY_INTERVAL]) - int(waited)
            )
        if self.v5 and d.sub_ids:
            props[Property.SUBSCRIPTION_IDENTIFIER] = list(d.sub_ids)
        topic = topiclib.strip_mountpoint(self.cfg.mountpoint, msg.topic)
        # outbound topic aliasing within the client's window
        # (MQTT-3.3.2-8): decide now, COMMIT only after the size check
        # passes — a dropped establishing publish must not leave an
        # alias the client never learned
        new_alias_topic = None
        if self.v5 and self.client_alias_max and not d.dup:
            alias = self.alias_out.get(topic)
            if alias is not None:
                props[Property.TOPIC_ALIAS] = alias
                topic = ""
            elif len(self.alias_out) < self.client_alias_max:
                alias = len(self.alias_out) + 1
                new_alias_topic = topic
                props[Property.TOPIC_ALIAS] = alias
        out = pkt.Publish(
            topic=topic,
            payload=msg.payload,
            qos=d.qos,
            retain=d.retain,
            dup=d.dup,
            packet_id=d.packet_id,
            properties=props,
        )
        if not d.dup and topic == msg.topic and props == msg.properties:
            # identical wire form (up to version/qos/retain and the
            # 2-byte packet-id slot) for every such receiver of this
            # message: share one serialization across the fan-out and
            # splice only the packet id per receiver (build-once/
            # scatter-many, frame.publish_prefix).  Attached BEFORE the
            # size gate so the exact-measure slow path below memoizes
            # on the same entry.
            out._wire_prefix = msg.headers.setdefault("__wire_prefix", {})
        if self.client_max_packet is not None and \
                not self._fits_client_packet(out):
            # MQTT-3.1.2-25: drop, don't send; free the QoS window
            # slot so the flow doesn't wedge
            self._m("delivery.dropped.too_large")
            if d.qos > 0 and d.packet_id is not None:
                self.session.inflight.delete(d.packet_id)
                refill = self.session.dequeue()
                if _followups is not None:
                    _followups.extend(refill)
                    return []
                return self._deliveries_out(refill)
            return []
        if new_alias_topic is not None:
            self.alias_out[new_alias_topic] = \
                props[Property.TOPIC_ALIAS]
        self._m("packets.publish.sent")
        self._m("messages.sent")
        return [("send", out)]

    @staticmethod
    def _prop_bound(v) -> int:
        """Upper bound on one property value's serialized size."""
        if isinstance(v, (bytes, bytearray)):
            return len(v) + 8
        if isinstance(v, str):
            return 4 * len(v) + 8  # worst-case utf-8 expansion
        if isinstance(v, (list, tuple)):
            return sum(Channel._prop_bound(x) for x in v) + 8
        return 16  # ints / varints

    def _fits_client_packet(self, out: "pkt.Publish") -> bool:
        """Size gate against the client's Maximum Packet Size.  Fast
        path: an UPPER-bound estimate skips the exact serialize when
        the packet is clearly small enough; near-limit packets pay one
        measuring serialization, memoized on the shared prefix entry
        when the scatter path is active — identical payloads measure
        once per wire form, not once per receiver."""
        rough = len(out.payload) + 4 * len(out.topic) + 16
        for v in out.properties.values():
            rough += self._prop_bound(v)
        if rough <= self.client_max_packet:
            return True
        from . import frame as framelib

        return framelib.exact_publish_size(out, self.proto_ver) <= \
            self.client_max_packet

    # ------------------------------------------------------------- timers

    def handle_retry(self) -> List[Action]:
        if self.session is None:
            return []
        return self._deliveries_out(self.session.retry())

    def handle_expire_awaiting_rel(self) -> List[Action]:
        if self.session:
            dead = self.session.expire_awaiting_rel()
            if dead:
                self._m("messages.dropped.await_pubrel_timeout", len(dead))
        return []

    # ---------------------------------------------------------- lifecycle

    def kick(self, reason_code: int) -> None:
        """Forced close (takeover/admin). Connection observes via callback."""
        self.state = DISCONNECTED
        self._takeover = reason_code == ReasonCode.SESSION_TAKEN_OVER
        if self.on_kick:
            self.on_kick(reason_code)

    def terminate(self, normal: bool) -> None:
        """Connection gone: unregister, maybe publish will, park session."""
        if self.state == DISCONNECTED and self._takeover:
            # session stolen by a new connection: nothing to clean
            self._m("session.takenover")
            return
        was_connected = self.state == CONNECTED
        self.state = DISCONNECTED
        if self.session is not None:
            if (not normal or self._will_on_normal) and self.will_msg is not None:
                # the will passes the same authz gate as a live PUBLISH
                if (
                    self.access.authorize(
                        self.clientinfo, PUB, self.will_msg.topic, self.authz_cache
                    )
                    == ALLOW
                ):
                    if self.will_delay > 0 and self.session.expiry_interval > 0:
                        # v5 Will Delay Interval: publish when the delay
                        # passes OR the session ends, whichever first
                        # (MQTT-3.1.3.2.2); a resume cancels (the CM owns
                        # the timer — this channel object dies now)
                        expiry = self.session.expiry_interval
                        delay = (
                            self.will_delay
                            if expiry == 0xFFFFFFFF
                            else min(self.will_delay, expiry)
                        )
                        msg = self.will_msg
                        broker = self.broker
                        broker.cm.schedule_will(
                            self.clientid,
                            lambda: broker.publish(msg),
                            time.time() + delay,
                        )
                    else:
                        self.broker.publish(self.will_msg)
                self.will_msg = None
            if self.session.expiry_interval == 0:
                # session dies with the connection: clean routes; pending
                # shared-group deliveries fail over to surviving members
                self.broker.client_down(
                    self.clientid,
                    list(self.session.subscriptions),
                    session=self.session,
                )
                self._m("session.terminated")
            self.broker.cm.disconnect_channel(self)
        if was_connected:
            self._m("client.disconnected")
            self.broker.hooks.run("client.disconnected", (self.clientinfo, normal))
