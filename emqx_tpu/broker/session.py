"""MQTT session: subscriptions, QoS 0/1/2 delivery state machine.

Analog of `emqx_session.erl` (SURVEY.md §2.1): inflight window for unacked
QoS1/2 deliveries, bounded mqueue for overflow/offline buffering,
awaiting_rel for inbound QoS2 exactly-once, packet-id allocation, retry and
resume replay.  Pure data structure — no I/O, no clocks of its own (callers
pass `now` where relevant), so it is trivially testable and serializable
(checkpoint/resume, takeover).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .inflight import Inflight, InflightEntry
from .message import Message
from .mqueue import MQueue
from .packet import ReasonCode, SubOpts


class SessionError(Exception):
    def __init__(self, reason_code: int, msg: str = ""):
        super().__init__(msg or hex(reason_code))
        self.reason_code = reason_code


@dataclass
class Delivery:
    """An outbound publish decided by the session (wire-ready fields)."""

    packet_id: Optional[int]
    message: Message
    qos: int
    dup: bool = False
    retain: bool = False
    sub_ids: List[int] = field(default_factory=list)


class Session:
    def __init__(
        self,
        clientid: str,
        clean_start: bool = True,
        expiry_interval: int = 0,  # seconds; 0 = ends with connection
        max_inflight: int = 32,
        max_mqueue: int = 1000,
        store_qos0: bool = True,
        upgrade_qos: bool = False,
        retry_interval: float = 30.0,
        max_awaiting_rel: int = 100,
        await_rel_timeout: float = 300.0,
        created_at: Optional[float] = None,
        username: Optional[str] = None,
    ):
        self.clientid = clientid
        self.username = username  # last connection's; offline queries
        self.clean_start = clean_start
        self.expiry_interval = expiry_interval
        self.upgrade_qos = upgrade_qos
        self.retry_interval = retry_interval
        self.max_awaiting_rel = max_awaiting_rel
        self.await_rel_timeout = await_rel_timeout
        self.created_at = created_at if created_at is not None else time.time()

        self.subscriptions: Dict[str, SubOpts] = {}
        # filt -> True when the subscription has no per-receiver
        # delivery state (no no_local, no retain-as-published, no
        # sub-id): the broker's broadcast scatter lane delivers these
        # receivers from ONE shared action list without consulting the
        # SubOpts at all.  Maintained by subscribe/unsubscribe; restore
        # paths that write `subscriptions` directly leave entries
        # absent, which just means the (correct) general path serves
        # them.
        self.scatter_plain: Dict[str, bool] = {}
        self.inflight = Inflight(max_inflight)
        self.mqueue = MQueue(max_len=max_mqueue, store_qos0=store_qos0)
        self.awaiting_rel: Dict[int, float] = {}  # inbound qos2 packet ids
        self._next_pid = 1
        # durable-message-log replay cursor (ds/): per-shard
        # (generation, offset) taken at park time; None until the
        # session first parks under an enabled log.  While a cursor is
        # held, QoS>=1 offline traffic lives in the SHARED log and the
        # mqueue is rebuilt from it on resume (ds/manager.py).
        self.ds_cursor: Optional[Dict[int, Tuple[int, int]]] = None
        # cursor-handoff takeover (ds/repl.py): when the cursor points
        # into ANOTHER node's log, ds_cursor_node names that origin and
        # replay resolves it against the local mirror; ds_handoff_tail
        # holds the shipped unreplicated ranges the mirror could not
        # absorb (RAM-only, never persisted — its loss is reported as a
        # replay gap, not silence)
        self.ds_cursor_node: Optional[str] = None
        self.ds_handoff_tail: Optional[Dict[int, dict]] = None

    # ------------------------------------------------------ subscriptions

    def subscribe(self, filt: str, opts: SubOpts) -> bool:
        """Returns True if this is a new subscription (vs an update)."""
        is_new = filt not in self.subscriptions
        self.subscriptions[filt] = opts
        self.scatter_plain[filt] = (
            not opts.no_local
            and not opts.retain_as_published
            and opts.sub_id is None
        )
        return is_new

    def unsubscribe(self, filt: str) -> Optional[SubOpts]:
        self.scatter_plain.pop(filt, None)
        return self.subscriptions.pop(filt, None)

    # ------------------------------------------------- inbound QoS2 dedup

    def publish_qos2(self, packet_id: int) -> None:
        """Register an inbound QoS2 publish awaiting PUBREL."""
        if packet_id in self.awaiting_rel:
            raise SessionError(ReasonCode.PACKET_IDENTIFIER_IN_USE)
        if 0 < self.max_awaiting_rel <= len(self.awaiting_rel):
            raise SessionError(ReasonCode.RECEIVE_MAXIMUM_EXCEEDED)
        self.awaiting_rel[packet_id] = time.monotonic()

    def pubrel(self, packet_id: int) -> bool:
        return self.awaiting_rel.pop(packet_id, None) is not None

    def expire_awaiting_rel(self, now: Optional[float] = None) -> List[int]:
        now = now if now is not None else time.monotonic()
        dead = [
            pid
            for pid, ts in self.awaiting_rel.items()
            if now - ts >= self.await_rel_timeout
        ]
        for pid in dead:
            del self.awaiting_rel[pid]
        return dead

    # ------------------------------------------------------ outbound path

    def _alloc_pid(self) -> int:
        for _ in range(65535):
            pid = self._next_pid
            self._next_pid = pid % 65535 + 1
            if not self.inflight.contain(pid):
                return pid
        raise SessionError(ReasonCode.QUOTA_EXCEEDED, "no free packet id")

    def _alloc_pids(self, n: int) -> List[int]:
        """Allocate n distinct free packet ids in ONE scan of the id
        space (batched fan-out deliveries pay one cursor walk, not one
        _alloc_pid call per message).  Ids are handed out in the same
        order the per-id allocator would."""
        if n == 1:
            return [self._alloc_pid()]
        out: List[int] = []
        contain = self.inflight.contain
        for _ in range(65535):
            pid = self._next_pid
            self._next_pid = pid % 65535 + 1
            if not contain(pid):
                out.append(pid)
                if len(out) == n:
                    return out
        raise SessionError(ReasonCode.QUOTA_EXCEEDED, "no free packet id")

    def _effective_qos(self, msg: Message, opts: SubOpts) -> int:
        if self.upgrade_qos:
            return max(msg.qos, opts.qos)
        return min(msg.qos, opts.qos)

    def deliver(
        self, delivers: List[Tuple[str, Message]]
    ) -> List[Delivery]:
        """Route matched messages through QoS logic.

        `delivers` pairs the matched subscription filter with the message
        (mirrors the reference's `{deliver, Topic, Msg}`,
        `emqx_session:deliver` `apps/emqx/src/emqx_session.erl:485`).
        Returns wire-ready deliveries; overflow goes to the mqueue.
        """
        out: List[Delivery] = []
        # two-pass so a batch of QoS>0 admissions allocates its packet
        # ids in ONE id-space scan (_alloc_pids); `free` mirrors the
        # inflight window so admission decisions match the one-at-a-time
        # ordering exactly
        free = self.inflight.free_slots()
        pend: List[Tuple[int, Message, int, bool, List[int]]] = []
        for filt, msg in delivers:
            opts = self.subscriptions.get(filt)
            if opts is None:
                # $queue/$share deliveries pass the real filter; direct
                # matches always exist. Unknown filter -> best effort qos0.
                opts = SubOpts(qos=0)
            if opts.no_local and msg.from_client == self.clientid:
                continue
            qos = self._effective_qos(msg, opts)
            retain = msg.retain if (opts.retain_as_published or msg.headers.get("retained")) else False
            sub_ids = [opts.sub_id] if opts.sub_id is not None else []
            if qos == 0:
                out.append(Delivery(None, msg, 0, retain=retain, sub_ids=sub_ids))
            elif free <= 0:
                self.mqueue.insert(self._with_qos(msg, qos))
            else:
                free -= 1
                pend.append((len(out), msg, qos, retain, sub_ids))
                out.append(None)  # placeholder filled below
        if pend:
            pids = self._alloc_pids(len(pend))
            for (i, msg, qos, retain, sub_ids), pid in zip(pend, pids):
                phase = "wait_ack" if qos == 1 else "wait_rec"
                self.inflight.insert(
                    pid, InflightEntry(phase=phase, message=self._with_qos(msg, qos))
                )
                out[i] = Delivery(pid, msg, qos, retain=retain, sub_ids=sub_ids)
        return out

    @staticmethod
    def _with_qos(msg: Message, qos: int) -> Message:
        if msg.qos == qos:
            return msg
        from dataclasses import replace

        return replace(msg, qos=qos)

    def enqueue(self, msg: Message) -> Optional[Message]:
        return self.mqueue.insert(msg)

    def pending_mids(self) -> set:
        """mids already held by this session (mqueue + unacked
        inflight) — the receiver-side dedup key the durable-log replay
        uses so an at-least-once replay converges to exactly-once."""
        mids = {m.mid for m in self.mqueue.peek_all()}
        for _pid, e in self.inflight.items():
            if e.message is not None:
                mids.add(e.message.mid)
        return mids

    # acks ----------------------------------------------------------------

    def puback(self, packet_id: int) -> Tuple[Optional[Message], List[Delivery]]:
        e = self.inflight.get(packet_id)
        if e is None or e.phase != "wait_ack":
            raise SessionError(ReasonCode.PACKET_IDENTIFIER_NOT_FOUND)
        self.inflight.delete(packet_id)
        return e.message, self.dequeue()

    def pubrec(self, packet_id: int) -> Optional[Message]:
        e = self.inflight.get(packet_id)
        if e is None:
            raise SessionError(ReasonCode.PACKET_IDENTIFIER_NOT_FOUND)
        if e.phase == "wait_comp":
            raise SessionError(ReasonCode.PACKET_IDENTIFIER_IN_USE)
        msg = e.message
        self.inflight.update(
            packet_id, InflightEntry(phase="wait_comp", message=None, ts=e.ts)
        )
        return msg

    def pubcomp(self, packet_id: int) -> List[Delivery]:
        e = self.inflight.get(packet_id)
        if e is None or e.phase != "wait_comp":
            raise SessionError(ReasonCode.PACKET_IDENTIFIER_NOT_FOUND)
        self.inflight.delete(packet_id)
        return self.dequeue()

    def dequeue(self) -> List[Delivery]:
        """Move queued messages into the freed inflight window."""
        out: List[Delivery] = []
        while not self.inflight.is_full():
            msg = self.mqueue.pop()
            if msg is None:
                break
            if msg.expired():
                continue
            if msg.qos == 0:
                out.append(Delivery(None, msg, 0))
            else:
                pid = self._alloc_pid()
                phase = "wait_ack" if msg.qos == 1 else "wait_rec"
                self.inflight.insert(pid, InflightEntry(phase=phase, message=msg))
                out.append(Delivery(pid, msg, msg.qos))
        return out

    # retry / replay ------------------------------------------------------

    def retry(self, now: Optional[float] = None) -> List[Delivery]:
        """Re-deliver unacked inflight entries past the retry interval."""
        if self.retry_interval <= 0:
            return []
        now = now if now is not None else time.monotonic()
        out: List[Delivery] = []
        for pid, e in self.inflight.items():
            if now - e.ts < self.retry_interval:
                continue
            e.ts = now
            e.retries += 1
            if e.phase == "wait_comp":
                out.append(Delivery(pid, None, 2, dup=False))  # resend PUBREL
            elif e.message is not None and e.message.expired():
                self.inflight.delete(pid)
            else:
                out.append(Delivery(pid, e.message, e.message.qos, dup=True))
        return out

    def replay(self) -> List[Delivery]:
        """On resume: re-send all pending inflight (dup) then drain queue.

        Messages whose MESSAGE_EXPIRY_INTERVAL lapsed while the client
        was away are dropped, not re-sent (MQTT-3.3.2-5); a started QoS2
        release (wait_comp) still completes — the receiver already holds
        the message."""
        out: List[Delivery] = []
        for pid, e in list(self.inflight.items()):
            if e.phase == "wait_comp":
                out.append(Delivery(pid, None, 2))
            elif e.message is not None:
                if e.message.expired():
                    self.inflight.delete(pid)
                    continue
                out.append(Delivery(pid, e.message, e.message.qos, dup=True))
        out.extend(self.dequeue())
        return out

    # info ----------------------------------------------------------------

    def info(self) -> Dict:
        return {
            "clientid": self.clientid,
            "username": self.username,
            "clean_start": self.clean_start,
            "subscriptions_cnt": len(self.subscriptions),
            "inflight_cnt": len(self.inflight),
            "mqueue_len": len(self.mqueue),
            "mqueue_dropped": self.mqueue.dropped,
            "awaiting_rel_cnt": len(self.awaiting_rel),
            "created_at": self.created_at,
        }
