"""Priority-ordered hook chains with stop/fold semantics.

Analog of `emqx_hooks.erl` (`run/2`, `run_fold/3`,
`apps/emqx/src/emqx_hooks.erl:162-231`): callbacks registered per hookpoint
with a priority (higher runs first); a callback may stop the chain and/or
transform an accumulator.  This is the extension boundary every subsystem
(authn, authz, rule engine, exhook bridge, retainer, ...) plugs into.

Callback protocol (pythonized):
  run(point, args):        cb(*args) -> None to continue, hooks.STOP to halt
  run_fold(point, args, acc): cb(*args, acc) -> None (keep acc), (CONTINUE, new_acc),
                              STOP, or (STOP, new_acc)
"""

from __future__ import annotations

import bisect
from typing import Any, Callable, Dict, List, Tuple

STOP = "stop"
CONTINUE = "ok"


class Hooks:
    def __init__(self) -> None:
        # point -> list of (-priority, seq, callback); kept sorted
        self._chains: Dict[str, List[Tuple[int, int, Callable]]] = {}
        self._seq = 0

    def put(self, point: str, cb: Callable, priority: int = 0) -> None:
        chain = self._chains.setdefault(point, [])
        self._seq += 1
        bisect.insort(chain, (-priority, self._seq, cb))

    def delete(self, point: str, cb: Callable) -> None:
        # equality, not identity: `self.m` builds a fresh bound-method
        # object on every access, so delete(point, self.m) with an `is`
        # check would never match the one put() stored
        chain = self._chains.get(point, [])
        self._chains[point] = [e for e in chain if e[2] != cb]

    def callbacks(self, point: str) -> List[Callable]:
        return [cb for _, _, cb in self._chains.get(point, [])]

    def has(self, point: str) -> bool:
        """Cheap hot-path gate: lets a fan-out loop skip the per-receiver
        run() machinery entirely when nothing subscribes to the point."""
        return bool(self._chains.get(point))

    def run(self, point: str, args: Tuple = ()) -> None:
        chain = self._chains.get(point)
        if not chain:  # no subscribers: zero-alloc early out (hot path)
            return
        for _, _, cb in list(chain):
            if cb(*args) == STOP:
                return

    def run_fold(self, point: str, args: Tuple, acc: Any) -> Any:
        for cb in self.callbacks(point):
            r = cb(*args, acc)
            if r is None:
                continue
            if r == STOP:
                return acc
            if isinstance(r, tuple) and len(r) == 2:
                action, acc = r
                if action == STOP:
                    return acc
            # any other value: treat as new acc (convenience)
            elif r != CONTINUE:
                acc = r
        return acc
