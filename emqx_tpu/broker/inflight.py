"""Unacked-message window, insertion-ordered.

Analog of `emqx_inflight.erl` (gb_tree keyed by packet id): bounded window of
QoS1/2 deliveries awaiting PUBACK/PUBREC/PUBCOMP; iteration order is insertion
(= retry/replay order).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, Optional, Tuple


@dataclass
class InflightEntry:
    phase: str  # 'wait_ack' (qos1), 'wait_rec' (qos2 publish), 'wait_comp' (pubrel sent)
    message: Any = None
    ts: float = field(default_factory=time.monotonic)
    retries: int = 0


class Inflight:
    def __init__(self, max_size: int = 32):
        self.max_size = max_size
        self._d: Dict[int, InflightEntry] = {}  # python dict preserves order

    def __len__(self) -> int:
        return len(self._d)

    def is_full(self) -> bool:
        return self.max_size > 0 and len(self._d) >= self.max_size

    def free_slots(self) -> int:
        """Open window slots; unbounded windows report 65535 (the
        packet-id space is the true ceiling).  Lets batch deliver
        pre-count its QoS>0 admissions and allocate packet ids in one
        pass instead of re-checking is_full per message."""
        if self.max_size <= 0:
            return 65535
        return max(0, self.max_size - len(self._d))

    def contain(self, pid: int) -> bool:
        return pid in self._d

    def insert(self, pid: int, entry: InflightEntry) -> None:
        if pid in self._d:
            raise KeyError(f"packet id {pid} already inflight")
        self._d[pid] = entry

    def get(self, pid: int) -> Optional[InflightEntry]:
        return self._d.get(pid)

    def update(self, pid: int, entry: InflightEntry) -> None:
        if pid not in self._d:
            raise KeyError(pid)
        self._d[pid] = entry  # keeps original position

    def delete(self, pid: int) -> Optional[InflightEntry]:
        return self._d.pop(pid, None)

    def items(self) -> Iterator[Tuple[int, InflightEntry]]:
        return iter(list(self._d.items()))
