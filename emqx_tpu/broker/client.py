"""Asyncio MQTT client — the in-repo `emqtt` analog.

Used by integration tests to drive real listeners (the role emqtt plays in
the reference's CT suites, e.g. `emqx_client_SUITE`), by the MQTT data
bridge, and by gateway tests.  Supports v3.1.1/v5, QoS 0/1/2 both
directions, wills, and properties.
"""

from __future__ import annotations

import asyncio
import ssl
from typing import Dict, List, Optional, Tuple

from . import packet as pkt
from .frame import FrameError, Parser, serialize
from .packet import MQTT_V5, PacketType, SubOpts


class MqttError(Exception):
    pass


class MqttClient:
    def __init__(
        self,
        clientid: str = "",
        proto_ver: int = MQTT_V5,
        clean_start: bool = True,
        keepalive: int = 60,
        username: Optional[str] = None,
        password: Optional[bytes] = None,
        properties: Optional[dict] = None,
        will: Optional[pkt.Connect] = None,
        auto_ack: bool = True,
        scram=None,  # ScramClient: enhanced auth over AUTH packets
    ):
        self.clientid = clientid
        self.proto_ver = proto_ver
        self.clean_start = clean_start
        self.keepalive = keepalive
        self.username = username
        self.password = password
        self.properties = properties or {}
        self.auto_ack = auto_ack
        self.scram = scram
        self.scram_server_verified: Optional[bool] = None
        self.will: Optional[Tuple[str, bytes, int, bool]] = None

        self.messages: asyncio.Queue = asyncio.Queue()
        self.connack: Optional[pkt.Connack] = None
        self.disconnect_packet: Optional[pkt.Disconnect] = None

        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._parser = Parser()
        self._read_task: Optional[asyncio.Task] = None
        self._pending: Dict[Tuple[int, int], asyncio.Future] = {}
        self._next_pid = 1
        self._connected = asyncio.Event()
        self.closed = asyncio.Event()

    # ------------------------------------------------------------ connect

    async def connect(self, host: str = "127.0.0.1", port: int = 1883,
                      streams=None, ssl=None, server_hostname=None) -> pkt.Connack:
        """`streams=(reader, writer)` runs MQTT over a pre-established
        transport (e.g. a WebSocket adapter) instead of dialing TCP.
        `ssl` takes an SSLContext (see tls.make_client_context) for mqtts."""
        if streams is not None:
            self._reader, self._writer = streams
        else:
            kw = {}
            if ssl is not None:
                kw["ssl"] = ssl
                kw["server_hostname"] = server_hostname or host
            self._reader, self._writer = await asyncio.open_connection(
                host, port, **kw
            )
        self._parser = Parser(version=self.proto_ver)
        c = pkt.Connect(
            proto_name="MQIsdp" if self.proto_ver == 3 else "MQTT",
            proto_ver=self.proto_ver,
            clientid=self.clientid,
            clean_start=self.clean_start,
            keepalive=self.keepalive,
            username=self.username,
            password=self.password,
            properties=dict(self.properties),
        )
        if self.scram is not None:
            if self.proto_ver != MQTT_V5:
                raise MqttError("SCRAM enhanced auth requires MQTT 5")
            from ..scram import METHOD as SCRAM_METHOD

            c.properties[pkt.Property.AUTHENTICATION_METHOD] = SCRAM_METHOD
            c.properties[pkt.Property.AUTHENTICATION_DATA] = (
                self.scram.client_first()
            )
        if self.will:
            topic, payload, qos, retain = self.will
            c.will_flag = True
            c.will_topic = topic
            c.will_payload = payload
            c.will_qos = qos
            c.will_retain = retain
            if getattr(self, "will_props", None):
                c.will_props = dict(self.will_props)
        self._send(c)
        self._read_task = asyncio.create_task(self._read_loop())
        await asyncio.wait_for(self._connected.wait(), 10)
        assert self.connack is not None
        if self.connack.reason_code != 0:
            raise MqttError(f"connack rc={self.connack.reason_code:#x}")
        return self.connack

    def _send(self, p) -> None:
        assert self._writer is not None
        self._writer.write(serialize(p, self.proto_ver))

    def _alloc_pid(self) -> int:
        pid = self._next_pid
        self._next_pid = pid % 65535 + 1
        return pid

    # ---------------------------------------------------------- read loop

    async def _read_loop(self) -> None:
        try:
            while True:
                data = await self._reader.read(65536)
                if not data:
                    break
                for p in self._parser.feed(data):
                    await self._handle(p)
        except asyncio.CancelledError:
            raise  # cancellation must propagate; cleanup runs in finally
        except (FrameError, ConnectionResetError, ssl.SSLError):
            # SSLError: server dropped a TLS transport without close_notify
            pass
        finally:
            self._connected.set()  # unblock connect() on immediate close
            self.closed.set()
            for f in self._pending.values():
                if not f.done():
                    f.set_exception(MqttError("connection closed"))
            self._pending.clear()

    async def _handle(self, p) -> None:
        t = p.type
        if t == PacketType.CONNACK:
            self.connack = p
            if (
                self.scram is not None
                and p.reason_code == 0
                and self.scram._salted is not None  # rounds actually ran
            ):
                data = p.properties.get(pkt.Property.AUTHENTICATION_DATA, b"")
                self.scram_server_verified = self.scram.verify_server_final(
                    data
                )
            self._connected.set()
        elif t == PacketType.AUTH:
            if self.scram is not None and p.reason_code == 0x18:
                data = p.properties.get(pkt.Property.AUTHENTICATION_DATA, b"")
                from ..scram import METHOD as SCRAM_METHOD

                self._send(
                    pkt.Auth(
                        reason_code=0x18,
                        properties={
                            pkt.Property.AUTHENTICATION_METHOD: SCRAM_METHOD,
                            pkt.Property.AUTHENTICATION_DATA: (
                                self.scram.client_final(data)
                            ),
                        },
                    )
                )
        elif t == PacketType.PUBLISH:
            if p.qos == 0:
                await self.messages.put(p)
            elif p.qos == 1:
                await self.messages.put(p)
                if self.auto_ack:
                    self._send(pkt.PubAck(packet_id=p.packet_id))
            else:
                if self.auto_ack:
                    self._send(pkt.PubRec(packet_id=p.packet_id))
                await self.messages.put(p)
        elif t == PacketType.PUBREL:
            if self.auto_ack:
                self._send(pkt.PubComp(packet_id=p.packet_id))
        elif t in (PacketType.PUBACK, PacketType.PUBCOMP, PacketType.SUBACK,
                   PacketType.UNSUBACK, PacketType.PUBREC):
            if t == PacketType.PUBREC:
                self._send(pkt.PubRel(packet_id=p.packet_id))
                return  # wait for PUBCOMP to resolve the future
            f = self._pending.pop((int(t), p.packet_id), None) or self._pending.pop(
                (int(PacketType.PUBACK), p.packet_id), None
            )
            if f is None and t == PacketType.PUBCOMP:
                f = self._pending.pop((int(PacketType.PUBREC), p.packet_id), None)
            if f and not f.done():
                f.set_result(p)
        elif t == PacketType.DISCONNECT:
            self.disconnect_packet = p
        elif t == PacketType.PINGRESP:
            pass

    def _expect(self, ptype: PacketType, pid: int) -> asyncio.Future:
        f = asyncio.get_event_loop().create_future()
        self._pending[(int(ptype), pid)] = f
        return f

    # ------------------------------------------------------------ actions

    async def subscribe(
        self, filters, qos: int = 0, properties: Optional[dict] = None,
        retain_handling: int = 0, no_local: bool = False,
        retain_as_published: bool = False,
    ) -> List[int]:
        opts = SubOpts(qos=qos, retain_handling=retain_handling,
                       no_local=no_local,
                       retain_as_published=retain_as_published)
        if isinstance(filters, str):
            filters = [filters]
        filters = [
            (f, opts) if isinstance(f, str) else (f[0], f[1])
            for f in filters
        ]
        pid = self._alloc_pid()
        f = self._expect(PacketType.SUBACK, pid)
        self._send(pkt.Subscribe(packet_id=pid, topic_filters=filters,
                                 properties=properties or {}))
        ack = await asyncio.wait_for(f, 10)
        return ack.reason_codes

    async def unsubscribe(self, filters) -> List[int]:
        if isinstance(filters, str):
            filters = [filters]
        pid = self._alloc_pid()
        f = self._expect(PacketType.UNSUBACK, pid)
        self._send(pkt.Unsubscribe(packet_id=pid, topic_filters=filters))
        ack = await asyncio.wait_for(f, 10)
        return ack.reason_codes

    async def publish(
        self,
        topic: str,
        payload: bytes = b"",
        qos: int = 0,
        retain: bool = False,
        properties: Optional[dict] = None,
    ) -> Optional[int]:
        """Returns the terminal reason code for qos>0 (None for qos0)."""
        if qos == 0:
            self._send(pkt.Publish(topic=topic, payload=payload, qos=0,
                                   retain=retain, properties=properties or {}))
            await self._writer.drain()
            return None
        pid = self._alloc_pid()
        wait_t = PacketType.PUBACK if qos == 1 else PacketType.PUBREC
        f = self._expect(wait_t, pid)
        self._send(pkt.Publish(topic=topic, payload=payload, qos=qos,
                               retain=retain, packet_id=pid,
                               properties=properties or {}))
        ack = await asyncio.wait_for(f, 10)
        return ack.reason_code

    async def ping(self) -> None:
        self._send(pkt.PingReq())
        await self._writer.drain()

    async def recv(self, timeout: float = 5.0) -> pkt.Publish:
        return await asyncio.wait_for(self.messages.get(), timeout)

    async def disconnect(self, reason_code: int = 0, properties: Optional[dict] = None) -> None:
        try:
            self._send(pkt.Disconnect(reason_code=reason_code,
                                      properties=properties or {}))
            await self._writer.drain()
        except Exception:
            pass
        await self.close()

    async def close(self) -> None:
        """Hard close (no DISCONNECT — triggers the will on the broker)."""
        if self._read_task:
            self._read_task.cancel()
        if self._writer:
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except Exception:
                pass
        self.closed.set()
