"""Host control plane: MQTT codec, topics, sessions, channels, dispatch."""
