"""Broker-internal message representation + GUID generation.

Analog of the reference's `#message{}` record (`apps/emqx/include/emqx.hrl`)
and `emqx_guid.erl` (time-ordered unique ids).
"""

from __future__ import annotations

import itertools
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

_seq = itertools.count()
_node_salt = os.urandom(6)


def guid() -> bytes:
    """16-byte time-ordered unique id (ts_us | node salt | seq)."""
    ts = time.time_ns() // 1000
    return ts.to_bytes(8, "big") + _node_salt + (next(_seq) & 0xFFFF).to_bytes(2, "big")


def now_ms() -> int:
    return time.time_ns() // 1_000_000


@dataclass
class Message:
    topic: str
    payload: bytes = b""
    qos: int = 0
    retain: bool = False
    dup: bool = False
    from_client: str = ""
    from_username: Optional[str] = None
    mid: bytes = field(default_factory=guid)
    timestamp: int = field(default_factory=now_ms)
    properties: Dict = field(default_factory=dict)
    headers: Dict[str, Any] = field(default_factory=dict)  # peername, proto, allow_publish...

    def expired(self, now: Optional[int] = None) -> bool:
        from .packet import Property

        exp = self.properties.get(Property.MESSAGE_EXPIRY_INTERVAL)
        if exp is None:
            return False
        return ((now or now_ms()) - self.timestamp) / 1000.0 >= exp

    def is_sys(self) -> bool:
        return self.topic.startswith("$SYS/")
