"""Bounded priority message queue with drop-oldest policy.

Analog of `emqx_mqueue.erl`/`emqx_pqueue.erl` (SURVEY.md §2.1): buffers
messages for offline sessions or when the inflight window is full; per-topic
priorities; optional QoS0 buffering; drop-oldest within the lowest occupied
priority when full.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional

from .message import Message


class MQueue:
    def __init__(
        self,
        max_len: int = 1000,
        store_qos0: bool = True,
        priorities: Optional[Dict[str, int]] = None,
        default_priority: int = 0,
    ):
        self.max_len = max_len
        self.store_qos0 = store_qos0
        self.priorities = priorities or {}
        self.default_priority = default_priority
        self._qs: Dict[int, deque] = {}
        self._len = 0
        self.dropped = 0

    def __len__(self) -> int:
        return self._len

    def _prio(self, m: Message) -> int:
        return self.priorities.get(m.topic, self.default_priority)

    def insert(self, m: Message) -> Optional[Message]:
        """Queue a message; returns a dropped message if any.

        QoS0 messages are dropped immediately when store_qos0 is off.  When
        full, the oldest message in the lowest occupied priority is dropped
        (the new message itself if its priority is lowest).
        """
        if m.qos == 0 and not self.store_qos0:
            self.dropped += 1
            return m
        dropped = None
        if self.max_len > 0 and self._len >= self.max_len:
            low = min(self._qs)
            if self._prio(m) < low:
                self.dropped += 1
                return m
            dropped = self._qs[low].popleft()
            if not self._qs[low]:
                del self._qs[low]
            self._len -= 1
            self.dropped += 1
        self._qs.setdefault(self._prio(m), deque()).append(m)
        self._len += 1
        return dropped

    def pop(self) -> Optional[Message]:
        if not self._len:
            return None
        hi = max(self._qs)
        m = self._qs[hi].popleft()
        if not self._qs[hi]:
            del self._qs[hi]
        self._len -= 1
        return m

    def drain_all(self) -> List[Message]:
        """Pop everything (session-death redispatch sweep)."""
        out: List[Message] = []
        while True:
            m = self.pop()
            if m is None:
                return out
            out.append(m)

    def peek_all(self) -> List[Message]:
        out: List[Message] = []
        for p in sorted(self._qs, reverse=True):
            out.extend(self._qs[p])
        return out
