"""MQTT topic utilities: split/join/validate/wildcard/match.

Semantics mirror the reference's topic layer (see SURVEY.md §2.1 "Topic utils",
reference `apps/emqx/src/emqx_topic.erl`): levels are '/'-separated words,
``+`` matches exactly one level, ``#`` matches any number of trailing levels
(including zero), and topics whose first level begins with ``$`` are never
matched by a wildcard at the root level.

This module is the host-side golden implementation; the TPU engine
(`emqx_tpu.ops.match`) must agree with :func:`match` on every input.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

MAX_TOPIC_LEN = 65535

PLUS = "+"
HASH = "#"

SHARE_PREFIX = "$share"
QUEUE_PREFIX = "$queue"
SEM_PREFIX = "$semantic"


def words(topic: str) -> List[str]:
    """Split a topic into its levels. ``"a//b"`` has an empty middle level."""
    return topic.split("/")


def join(ws: List[str]) -> str:
    return "/".join(ws)


def levels(topic: str) -> int:
    return len(words(topic))


def wildcard(topic: str) -> bool:
    """True if the filter contains any wildcard level."""
    return any(w in (PLUS, HASH) for w in words(topic))


def is_sys(topic: str) -> bool:
    return topic.startswith("$")


def validate_filter(topic: str) -> bool:
    """Validate a subscription filter (wildcards allowed)."""
    if not topic or len(topic.encode("utf-8", "surrogatepass")) > MAX_TOPIC_LEN:
        return False
    if "\x00" in topic:
        return False
    ws = words(topic)
    for i, w in enumerate(ws):
        if HASH in w:
            # '#' must occupy a whole level and be the last level
            if w != HASH or i != len(ws) - 1:
                return False
        if PLUS in w and w != PLUS:
            return False
    return True


def validate_name(topic: str) -> bool:
    """Validate a publish topic name (no wildcards)."""
    if not topic or len(topic.encode("utf-8", "surrogatepass")) > MAX_TOPIC_LEN:
        return False
    if "\x00" in topic:
        return False
    return not wildcard(topic)


def match_words(name: List[str], filt: List[str]) -> bool:
    """Match topic-name words against filter words (both pre-split)."""
    # Root-level wildcard never matches a $-topic.
    if name and name[0].startswith("$") and filt and filt[0] in (PLUS, HASH):
        return False
    i = 0
    n, m = len(name), len(filt)
    while i < m:
        fw = filt[i]
        if fw == HASH:
            return True  # '#' matches the remaining levels, including zero
        if i >= n:
            # name exhausted: only a trailing '#' can still match
            return False
        if fw != PLUS and fw != name[i]:
            return False
        i += 1
    # Filter exhausted: match iff the name is exhausted too, or the next
    # (and only remaining) filter level would have been '#'. Handled above.
    return i == n


def match(name: str, filt: str) -> bool:
    """Does topic `name` match subscription `filt`?"""
    return match_words(words(name), words(filt))


def parse_share(topic: str) -> Tuple[Optional[str], str]:
    """Parse a shared-subscription filter.

    ``$share/<group>/<real-filter>`` -> (group, real-filter)
    ``$queue/<real-filter>``         -> ("$queue", real-filter)
    Anything else                    -> (None, topic)
    """
    if topic.startswith(SHARE_PREFIX + "/"):
        rest = topic[len(SHARE_PREFIX) + 1 :]
        group, sep, real = rest.partition("/")
        if sep and group and real:
            return group, real
        return None, topic
    if topic.startswith(QUEUE_PREFIX + "/"):
        real = topic[len(QUEUE_PREFIX) + 1 :]
        if real:
            return QUEUE_PREFIX, real
    return None, topic


def parse_semantic(topic: str) -> Optional[str]:
    """Parse a semantic-subscription filter (the `$share/` discipline).

    ``$semantic/<query>`` -> query text (which may itself contain '/');
    anything else -> None.  Semantic filters are a subscription CLASS,
    not a topic pattern: they bypass the trie/churn plane entirely
    (emqx_tpu/semantic/) and never reach the match engine or the route
    oplog.
    """
    if topic.startswith(SEM_PREFIX + "/"):
        query = topic[len(SEM_PREFIX) + 1 :]
        if query:
            return query
    return None


def feed_var(var: str, value: str, topic: str) -> str:
    """Substitute a placeholder level (e.g. ``%c``/``%u``) in a topic."""
    return join([value if w == var else w for w in words(topic)])


def join_share(group: Optional[str], real: str) -> str:
    """Inverse of :func:`parse_share`."""
    if group is None:
        return real
    if group == QUEUE_PREFIX:
        return f"{QUEUE_PREFIX}/{real}"
    return f"{SHARE_PREFIX}/{group}/{real}"


def mount_filter(mountpoint: Optional[str], filt: str) -> str:
    """Prepend the mountpoint to the *real* filter inside any $share prefix.

    `$share/g/t` with mountpoint `mp/` -> `$share/g/mp/t` (the reference
    mounts the inner topic, not the share wrapper — emqx_mountpoint.erl).
    """
    if not mountpoint:
        return filt
    group, real = parse_share(filt)
    return join_share(group, mountpoint + real)


def prepend_mountpoint(mountpoint: Optional[str], topic: str) -> str:
    if not mountpoint:
        return topic
    return mountpoint + topic


def strip_mountpoint(mountpoint: Optional[str], topic: str) -> str:
    if mountpoint and topic.startswith(mountpoint):
        return topic[len(mountpoint) :]
    return topic
