"""Shared-subscription group dispatch.

Analog of `emqx_shared_sub.erl` (SURVEY.md §1.7): `$share/<group>/<filter>`
(and `$queue/<filter>`) subscribers form a group; each matched publish is
delivered to ONE member, picked by a configurable strategy
(`emqx_shared_sub.erl:61-66,234-288`).  Strategy state (round-robin cursors,
sticky picks) is host-side by design — the device returns candidate sets
only (SURVEY.md §7.3).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Set, Tuple

STRATEGIES = (
    "random", "round_robin", "sticky", "hash_clientid", "hash_topic",
    "local",
)


class SharedSub:
    def __init__(self, strategy: str = "random", seed: Optional[int] = None,
                 group_strategies: Optional[Dict[str, str]] = None):
        if strategy not in STRATEGIES:
            raise ValueError(f"unknown shared-sub strategy {strategy!r}")
        self.strategy = strategy
        # per-group overrides (`emqx_shared_sub.erl:61-66` strategy() is
        # read per dispatch; the reference configs it per group in 5.x)
        self.group_strategies: Dict[str, str] = dict(group_strategies or {})
        for g, st in self.group_strategies.items():
            if st not in STRATEGIES:
                raise ValueError(
                    f"group {g!r}: unknown shared-sub strategy {st!r}"
                )
        self._rng = random.Random(seed)
        # (group, filter) -> ordered member clientids
        self._groups: Dict[Tuple[str, str], List[str]] = {}
        self._rr: Dict[Tuple[str, str], int] = {}
        self._sticky: Dict[Tuple[str, str], str] = {}

    def is_member(self, group: str, filt: str, clientid: str) -> bool:
        return clientid in self._groups.get((group, filt), ())

    def subscribe(self, group: str, filt: str, clientid: str) -> bool:
        """Returns True if this (group, filter) is newly populated (the
        caller announces it); a duplicate subscribe returns False."""
        key = (group, filt)
        members = self._groups.setdefault(key, [])
        if clientid in members:
            return False
        members.append(clientid)
        return len(members) == 1

    def unsubscribe(self, group: str, filt: str, clientid: str) -> bool:
        """Returns True if the group became empty (route removal)."""
        key = (group, filt)
        members = self._groups.get(key)
        if not members:
            return False
        if clientid in members:
            members.remove(clientid)
        if self._sticky.get(key) == clientid:
            del self._sticky[key]
        if not members:
            self._groups.pop(key, None)
            self._rr.pop(key, None)
            return True
        return False

    def drop_member(self, clientid: str) -> List[Tuple[str, str, bool]]:
        """Remove a dead subscriber from every group (nodedown/kick
        analog); returns (group, filter, became_empty) per removed
        membership so the caller can release refs/routes for each."""
        removed: List[Tuple[str, str, bool]] = []
        for key in list(self._groups):
            if clientid in self._groups.get(key, ()):
                emptied = self.unsubscribe(key[0], key[1], clientid)
                removed.append((key[0], key[1], emptied))
        return removed

    def groups_for(self, filt: str) -> List[Tuple[str, str]]:
        return [k for k in self._groups if k[1] == filt]

    def strategy_for(self, group: str) -> str:
        return self.group_strategies.get(group, self.strategy)

    def members(self, group: str, filt: str) -> List[str]:
        return list(self._groups.get((group, filt), ()))

    def pick(
        self,
        group: str,
        filt: str,
        topic: str,
        from_client: str,
        exclude: Optional[Set[str]] = None,
    ) -> Optional[str]:
        """Pick the receiving member for one publish (None if none eligible).

        `exclude` carries members that already failed this delivery — the
        redispatch loop (`emqx_shared_sub:redispatch`, `:118-130`) retries
        with the failed picks excluded until the group is exhausted.
        """
        key = (group, filt)
        members = self._groups.get(key)
        if exclude:
            members = [m for m in members or () if m not in exclude]
        if not members:
            return None
        s = self.strategy_for(group)
        if s in ("random", "local"):
            # 'local' restricts the candidate set to this node (the
            # broker layer handles remote fallback); among local
            # members it picks uniformly, like the reference
            return self._rng.choice(members)
        if s == "round_robin":
            i = self._rr.get(key, 0) % len(members)
            self._rr[key] = i + 1
            return members[i]
        if s == "sticky":
            cur = self._sticky.get(key)
            if cur in members:
                return cur
            cur = self._rng.choice(members)
            self._sticky[key] = cur
            return cur
        if s == "hash_clientid":
            return members[hash(from_client) % len(members)]
        return members[hash(topic) % len(members)]  # hash_topic

    def member_failed(self, group: str, filt: str, clientid: str) -> None:
        """A delivery to this member failed: invalidate a sticky pick so
        the next publish re-picks (`emqx_shared_sub.erl:347-350` clears
        the sticky pid on DOWN)."""
        key = (group, filt)
        if self._sticky.get(key) == clientid:
            del self._sticky[key]
