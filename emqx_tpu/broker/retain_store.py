"""Disc persistence for retained messages.

Analog of `emqx_retainer_mnesia.erl` disc copies: retained messages
survive a broker restart.  Implementation is an append-only binary log
of set/delete records with compaction — on load the log is replayed
into the live trie; when dead records dominate, the file is rewritten
as a snapshot of the live set.

Record framing (little-endian):
    [u8 op]  1=set 2=delete
    [u32 header_len][header json utf-8]
    [u32 payload_len][payload bytes]     (set only)
header: topic, qos, retain, from, username, mid(hex), ts, props.
"""

from __future__ import annotations

import json
import logging
import os
import struct
import threading
from typing import Dict, Iterator, Tuple

from .message import Message

log = logging.getLogger("emqx_tpu.retain_store")

_OP_SET = 1
_OP_DEL = 2


def _enc_val(v):
    """JSON-encode any v5 property value losslessly (bytes, pair lists)."""
    if isinstance(v, (bytes, bytearray)):
        return {"__b": bytes(v).hex()}
    if isinstance(v, (list, tuple)):
        return {"__l": [_enc_val(x) for x in v]}
    return v


def _dec_val(v):
    if isinstance(v, dict):
        if "__b" in v:
            return bytes.fromhex(v["__b"])
        if "__l" in v:
            return [_dec_val(x) for x in v["__l"]]
    return v


def _msg_header(msg: Message) -> bytes:
    props = {str(k): _enc_val(v) for k, v in msg.properties.items()}
    return json.dumps({
        "topic": msg.topic,
        "qos": msg.qos,
        "from": msg.from_client,
        "username": msg.from_username,
        "mid": msg.mid.hex(),
        "ts": msg.timestamp,
        "props": props,
    }).encode("utf-8")


def _msg_from(header: dict, payload: bytes) -> Message:
    props = {}
    for k, v in (header.get("props") or {}).items():
        v = _dec_val(v)
        try:
            props[int(k)] = v
        except ValueError:
            props[k] = v
    return Message(
        topic=header["topic"],
        payload=payload,
        qos=header.get("qos", 0),
        retain=True,
        from_client=header.get("from", ""),
        from_username=header.get("username"),
        mid=bytes.fromhex(header["mid"]),
        timestamp=header.get("ts", 0),
        properties=props,
    )


class DiscRetainStore:
    """Append-log + compaction store (write-through from the Retainer)."""

    def __init__(self, path: str, compact_ratio: int = 4):
        self.path = path
        self.compact_ratio = compact_ratio
        # set/delete append on the event loop; flush() runs on the node
        # ticker's to_thread hop — the handle + record count are shared
        # across those threads and every access holds this lock
        # (reentrant: _compact re-enters through set())
        self._lock = threading.RLock()
        self._records = 0  # total records in the log file
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "ab")

    # ------------------------------------------------------------- write

    def set(self, msg: Message) -> None:
        """Buffered append (no per-message flush: retained publish rides
        the event loop; the node ticker calls flush() off-loop)."""
        hdr = _msg_header(msg)
        with self._lock:
            self._f.write(struct.pack("<BI", _OP_SET, len(hdr)))  # analysis: allow-blocking(buffered page-cache append, no fsync; flush is off-loop)
            self._f.write(hdr)  # analysis: allow-blocking(buffered page-cache append)
            self._f.write(struct.pack("<I", len(msg.payload)))  # analysis: allow-blocking(buffered page-cache append)
            self._f.write(msg.payload)  # analysis: allow-blocking(buffered page-cache append)
            self._records += 1

    def delete(self, topic: str) -> None:
        hdr = json.dumps({"topic": topic}).encode("utf-8")
        with self._lock:
            self._f.write(struct.pack("<BI", _OP_DEL, len(hdr)))  # analysis: allow-blocking(buffered page-cache append)
            self._f.write(hdr)  # analysis: allow-blocking(buffered page-cache append)
            self._records += 1

    def flush(self) -> None:
        """Flush buffered appends to the OS.  Called from the node
        ticker via asyncio.to_thread — never on the event loop."""
        try:
            with self._lock:
                self._f.flush()
        except OSError:
            log.exception("retain store flush")

    def needs_compact(self, live_count: int) -> bool:
        """True when dead records dominate — the Retainer then streams
        its live set through compact() (bounds the log between restarts,
        not just at load)."""
        with self._lock:
            return self._records > self.compact_ratio * max(live_count, 1)

    def compact(self, messages) -> None:
        self._compact({m.topic: m for m in messages})

    def close(self) -> None:
        try:
            with self._lock:
                self._f.flush()  # analysis: allow-blocking(shutdown: final flush)
                self._f.close()
        except OSError:
            pass

    # -------------------------------------------------------------- load

    def _replay(self) -> Iterator[Tuple[int, dict, bytes]]:
        # boot-time load: the node constructs the retainer before any
        # listener serves traffic, so these reads never stall a client
        with open(self.path, "rb") as f:
            while True:
                head = f.read(5)  # analysis: allow-blocking(boot-time load)
                if len(head) < 5:
                    if head:
                        log.warning("truncated record tail in %s", self.path)
                    return
                op, hlen = struct.unpack("<BI", head)
                hdr_raw = f.read(hlen)  # analysis: allow-blocking(boot-time load)
                if len(hdr_raw) < hlen:
                    log.warning("truncated header in %s", self.path)
                    return
                try:
                    hdr = json.loads(hdr_raw)
                except ValueError:
                    log.warning("corrupt header in %s", self.path)
                    return
                payload = b""
                if op == _OP_SET:
                    plen_raw = f.read(4)  # analysis: allow-blocking(boot-time load)
                    if len(plen_raw) < 4:
                        return
                    (plen,) = struct.unpack("<I", plen_raw)
                    payload = f.read(plen)  # analysis: allow-blocking(boot-time load)
                    if len(payload) < plen:
                        return
                yield op, hdr, payload

    def load(self) -> Dict[str, Message]:
        """Replay the log; compacts the file when dead records dominate."""
        if not os.path.exists(self.path):
            return {}
        live: Dict[str, Message] = {}
        n = 0
        for op, hdr, payload in self._replay():
            n += 1
            topic = hdr.get("topic", "")
            if op == _OP_SET:
                live[topic] = _msg_from(hdr, payload)
            else:
                live.pop(topic, None)
        with self._lock:
            self._records = n
        live = {t: m for t, m in live.items() if not m.expired()}
        if n > self.compact_ratio * max(len(live), 1):
            self._compact(live)
        return live

    def _compact(self, live: Dict[str, Message]) -> None:
        tmp = self.path + ".tmp"
        with self._lock:
            self._f.close()
            self._f = open(tmp, "wb")
            self._records = 0
            try:
                for msg in live.values():
                    self.set(msg)
                self._f.close()
                os.replace(tmp, self.path)
            finally:
                self._f = open(self.path, "ab")
        log.info("compacted %s to %d retained messages", self.path, len(live))
