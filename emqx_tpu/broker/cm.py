"""Connection/session manager: clientid -> channel registry, takeover.

Analog of `emqx_cm.erl` (SURVEY.md §1.6): open_session with clean-start
discard vs resume, session takeover when a clientid reconnects while a live
channel exists (`emqx_cm.erl:225-285,320-361`), and expiry of disconnected
persistent sessions.  Single-node in-process registry; the cluster layer
wraps it with a distributed registry + per-clientid locks.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Protocol, Tuple

from .packet import ReasonCode
from .session import Session
from ..observe.tracepoints import tp


class ChannelLike(Protocol):
    clientid: str
    session: Session

    def kick(self, reason_code: int) -> None: ...
    def deliver(self, delivers) -> None: ...


class ConnectionManager:
    def __init__(self) -> None:
        self.channels: Dict[str, ChannelLike] = {}
        # disconnected persistent sessions: clientid -> (session, expire_at)
        self.pending: Dict[str, Tuple[Session, float]] = {}
        self.on_discard: Optional[Callable[[Session], None]] = None
        # fires with the clientid on EVERY channel-registry mutation
        # (register / unregister / kick): the broker invalidates its
        # per-uid scatter-callback cache through this
        self.on_channel_change: Optional[Callable[[str], None]] = None
        # fires when a disconnected session is parked (persistence point)
        self.on_park: Optional[Callable[[str, Session, float], None]] = None
        # fires when a parked session is resumed by a reconnect; the
        # session rides along so the durable-log replay can rebuild its
        # mqueue before the channel takes over (ds/manager.py)
        self.on_resume: Optional[Callable[[str, Session], None]] = None
        # v5 Will Delay Interval (MQTT-3.1.3.2.2): a will scheduled at
        # disconnect, published when the delay passes or the session
        # ends — whichever first — and cancelled by a resume.
        # clientid -> (fire closure, fire_at)
        self.delayed_wills: Dict[str, Tuple[Callable[[], None], float]] = {}

    # ------------------------------------------------------------- open

    def open_session(
        self,
        clean_start: bool,
        clientid: str,
        make_session: Callable[[], Session],
    ) -> Tuple[Session, bool]:
        """Returns (session, session_present).

        Mirrors `emqx_cm:open_session`: clean_start discards any existing
        state; otherwise a live channel is taken over (its session is
        stolen and the old connection kicked) or a pending disconnected
        session is resumed.
        """
        old = self.channels.get(clientid)
        if clean_start:
            if old is not None:
                tp("session_discarded", clientid=clientid, live=True)
                self._kick(old, ReasonCode.SESSION_TAKEN_OVER)
                if self.on_discard:
                    # the kicked channel's terminate() skips cleanup (it
                    # believes its session was taken over), so the broker
                    # must clean its routes here
                    self.on_discard(old.session)
            dropped = self.pending.pop(clientid, None)
            if dropped and self.on_discard:
                tp("session_discarded", clientid=clientid, live=False)
                self.on_discard(dropped[0])
            # the OLD session (if any) ends here: its delayed will, if
            # still pending, publishes now (delay-or-session-end rule)
            self.fire_will_now(clientid)
            tp("session_created", clientid=clientid)
            return make_session(), False
        if old is not None:
            session = old.session
            tp("session_takeover_begin", clientid=clientid)
            self._kick(old, ReasonCode.SESSION_TAKEN_OVER)
            tp("session_takeover_end", clientid=clientid)
            self.cancel_will(clientid)
            return session, True
        ent = self.pending.pop(clientid, None)
        if ent is not None:
            session, expire_at = ent
            if time.time() < expire_at or session.expiry_interval == 0xFFFFFFFF:
                if self.on_resume:
                    self.on_resume(clientid, session)
                # resumed before the will delay elapsed: the will MUST
                # NOT be sent (MQTT-3.1.3-9)
                self.cancel_will(clientid)
                tp("session_resumed", clientid=clientid)
                return session, True
            if self.on_discard:
                tp("session_discarded", clientid=clientid, live=False)
                self.on_discard(session)
        tp("session_created", clientid=clientid)
        return make_session(), False

    def _kick(self, ch: ChannelLike, rc: int) -> None:
        self.channels.pop(ch.clientid, None)
        if self.on_channel_change:
            self.on_channel_change(ch.clientid)
        try:
            ch.kick(rc)
        except Exception:
            pass

    # --------------------------------------------------------- registry

    def register_channel(self, ch: ChannelLike) -> None:
        self.channels[ch.clientid] = ch
        if self.on_channel_change:
            self.on_channel_change(ch.clientid)

    def unregister_channel(self, ch: ChannelLike) -> None:
        cur = self.channels.get(ch.clientid)
        if cur is ch:
            del self.channels[ch.clientid]
            if self.on_channel_change:
                self.on_channel_change(ch.clientid)

    def disconnect_channel(self, ch: ChannelLike) -> None:
        """Connection closed: park the session if it has an expiry."""
        self.unregister_channel(ch)
        s = ch.session
        if s.expiry_interval > 0:
            ttl = (
                float("inf")
                if s.expiry_interval == 0xFFFFFFFF
                else s.expiry_interval
            )
            expire_at = time.time() + ttl
            self.pending[ch.clientid] = (s, expire_at)
            if self.on_park:
                self.on_park(ch.clientid, s, expire_at)
        elif self.on_discard:
            self.on_discard(s)

    def lookup(self, clientid: str) -> Optional[ChannelLike]:
        return self.channels.get(clientid)

    def lookup_session(self, clientid: str) -> Optional[Session]:
        ch = self.channels.get(clientid)
        if ch is not None:
            return ch.session
        ent = self.pending.get(clientid)
        return ent[0] if ent else None

    def discard_session(self, clientid: str) -> None:
        old = self.channels.get(clientid)
        if old is not None:
            self._kick(old, ReasonCode.SESSION_TAKEN_OVER)
            if self.on_discard:
                self.on_discard(old.session)
        ent = self.pending.pop(clientid, None)
        if ent and self.on_discard:
            self.on_discard(ent[0])
        self.fire_will_now(clientid)  # session ends: delayed will due

    def kick_session(self, clientid: str, rc: int = ReasonCode.ADMINISTRATIVE_ACTION) -> bool:
        old = self.channels.get(clientid)
        if old is not None:
            self._kick(old, rc)
            return True
        if self.pending.pop(clientid, None) is not None:
            # killing a parked session ends it: its delayed will is due
            # now, like discard_session/evict_expired (session-end arm)
            self.fire_will_now(clientid)
            return True
        return False

    def evict_expired(self, now: Optional[float] = None) -> int:
        now = now if now is not None else time.time()
        dead = [cid for cid, (_s, exp) in self.pending.items() if exp <= now]
        for cid in dead:
            s, _ = self.pending.pop(cid)
            self.fire_will_now(cid)  # session end precedes any will delay
            if self.on_discard:
                self.on_discard(s)
        self.fire_due_wills(now)
        return len(dead)

    # -------------------------------------------------------- delayed wills

    def schedule_will(
        self, clientid: str, fire: Callable[[], None], fire_at: float
    ) -> None:
        self.delayed_wills[clientid] = (fire, fire_at)

    def cancel_will(self, clientid: str) -> bool:
        return self.delayed_wills.pop(clientid, None) is not None

    def fire_will_now(self, clientid: str) -> None:
        ent = self.delayed_wills.pop(clientid, None)
        if ent is not None:
            ent[0]()

    def fire_due_wills(self, now: Optional[float] = None) -> int:
        now = now if now is not None else time.time()
        due = [cid for cid, (_f, at) in self.delayed_wills.items()
               if at <= now]
        for cid in due:
            fire, _ = self.delayed_wills.pop(cid)
            fire()
        return len(due)

    @property
    def connection_count(self) -> int:
        return len(self.channels)

    @property
    def session_count(self) -> int:
        return len(self.channels) + len(self.pending)
