"""MQTT packet model: types, flags, v5 properties, reason codes.

Dataclass equivalents of the reference's packet records
(`apps/emqx/include/emqx_mqtt.hrl`, helpers `apps/emqx/src/emqx_packet.erl`,
reason codes `emqx_reason_codes.erl`).  Wire codec lives in
`emqx_tpu.broker.frame`.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union


class PacketType(enum.IntEnum):
    CONNECT = 1
    CONNACK = 2
    PUBLISH = 3
    PUBACK = 4
    PUBREC = 5
    PUBREL = 6
    PUBCOMP = 7
    SUBSCRIBE = 8
    SUBACK = 9
    UNSUBSCRIBE = 10
    UNSUBACK = 11
    PINGREQ = 12
    PINGRESP = 13
    DISCONNECT = 14
    AUTH = 15


# protocol versions
MQTT_V3 = 3  # MQIsdp 3.1
MQTT_V4 = 4  # MQTT 3.1.1
MQTT_V5 = 5  # MQTT 5.0

PROTO_NAMES = {MQTT_V3: "MQIsdp", MQTT_V4: "MQTT", MQTT_V5: "MQTT"}

QOS_0, QOS_1, QOS_2 = 0, 1, 2


class ReasonCode(enum.IntEnum):
    """MQTT v5 reason codes (subset used across packet types)."""

    SUCCESS = 0x00
    GRANTED_QOS_1 = 0x01
    GRANTED_QOS_2 = 0x02
    DISCONNECT_WITH_WILL = 0x04
    NO_MATCHING_SUBSCRIBERS = 0x10
    NO_SUBSCRIPTION_EXISTED = 0x11
    CONTINUE_AUTHENTICATION = 0x18
    REAUTHENTICATE = 0x19
    UNSPECIFIED_ERROR = 0x80
    MALFORMED_PACKET = 0x81
    PROTOCOL_ERROR = 0x82
    IMPLEMENTATION_SPECIFIC = 0x83
    UNSUPPORTED_PROTOCOL_VERSION = 0x84
    CLIENT_IDENTIFIER_NOT_VALID = 0x85
    BAD_USERNAME_OR_PASSWORD = 0x86
    NOT_AUTHORIZED = 0x87
    SERVER_UNAVAILABLE = 0x88
    SERVER_BUSY = 0x89
    BANNED = 0x8A
    SERVER_SHUTTING_DOWN = 0x8B
    BAD_AUTHENTICATION_METHOD = 0x8C
    KEEP_ALIVE_TIMEOUT = 0x8D
    SESSION_TAKEN_OVER = 0x8E
    TOPIC_FILTER_INVALID = 0x8F
    TOPIC_NAME_INVALID = 0x90
    PACKET_IDENTIFIER_IN_USE = 0x91
    PACKET_IDENTIFIER_NOT_FOUND = 0x92
    RECEIVE_MAXIMUM_EXCEEDED = 0x93
    TOPIC_ALIAS_INVALID = 0x94
    PACKET_TOO_LARGE = 0x95
    MESSAGE_RATE_TOO_HIGH = 0x96
    QUOTA_EXCEEDED = 0x97
    ADMINISTRATIVE_ACTION = 0x98
    PAYLOAD_FORMAT_INVALID = 0x99
    RETAIN_NOT_SUPPORTED = 0x9A
    QOS_NOT_SUPPORTED = 0x9B
    USE_ANOTHER_SERVER = 0x9C
    SERVER_MOVED = 0x9D
    SHARED_SUBSCRIPTIONS_NOT_SUPPORTED = 0x9E
    CONNECTION_RATE_EXCEEDED = 0x9F
    MAXIMUM_CONNECT_TIME = 0xA0
    SUBSCRIPTION_IDENTIFIERS_NOT_SUPPORTED = 0xA1
    WILDCARD_SUBSCRIPTIONS_NOT_SUPPORTED = 0xA2


# v3 CONNACK return codes (emqx_reason_codes:compat/2 analog)
CONNACK_V3 = {
    ReasonCode.SUCCESS: 0,
    ReasonCode.UNSUPPORTED_PROTOCOL_VERSION: 1,
    ReasonCode.CLIENT_IDENTIFIER_NOT_VALID: 2,
    ReasonCode.SERVER_UNAVAILABLE: 3,
    ReasonCode.BAD_USERNAME_OR_PASSWORD: 4,
    ReasonCode.NOT_AUTHORIZED: 5,
}


def compat_connack_v3(rc: int) -> int:
    """Map a v5 CONNACK reason code to a v3 return code."""
    return CONNACK_V3.get(ReasonCode(rc) if rc in ReasonCode._value2member_map_ else rc, 3)


# ---------------------------------------------------------------- properties

class Property(enum.IntEnum):
    PAYLOAD_FORMAT_INDICATOR = 0x01
    MESSAGE_EXPIRY_INTERVAL = 0x02
    CONTENT_TYPE = 0x03
    RESPONSE_TOPIC = 0x08
    CORRELATION_DATA = 0x09
    SUBSCRIPTION_IDENTIFIER = 0x0B
    SESSION_EXPIRY_INTERVAL = 0x11
    ASSIGNED_CLIENT_IDENTIFIER = 0x12
    SERVER_KEEP_ALIVE = 0x13
    AUTHENTICATION_METHOD = 0x15
    AUTHENTICATION_DATA = 0x16
    REQUEST_PROBLEM_INFORMATION = 0x17
    WILL_DELAY_INTERVAL = 0x18
    REQUEST_RESPONSE_INFORMATION = 0x19
    RESPONSE_INFORMATION = 0x1A
    SERVER_REFERENCE = 0x1C
    REASON_STRING = 0x1F
    RECEIVE_MAXIMUM = 0x21
    TOPIC_ALIAS_MAXIMUM = 0x22
    TOPIC_ALIAS = 0x23
    MAXIMUM_QOS = 0x24
    RETAIN_AVAILABLE = 0x25
    USER_PROPERTY = 0x26
    MAXIMUM_PACKET_SIZE = 0x27
    WILDCARD_SUBSCRIPTION_AVAILABLE = 0x28
    SUBSCRIPTION_IDENTIFIER_AVAILABLE = 0x29
    SHARED_SUBSCRIPTION_AVAILABLE = 0x2A


# wire type of each property: byte|u16|u32|varint|utf8|bin|utf8pair
PROPERTY_TYPES: Dict[int, str] = {
    Property.PAYLOAD_FORMAT_INDICATOR: "byte",
    Property.MESSAGE_EXPIRY_INTERVAL: "u32",
    Property.CONTENT_TYPE: "utf8",
    Property.RESPONSE_TOPIC: "utf8",
    Property.CORRELATION_DATA: "bin",
    Property.SUBSCRIPTION_IDENTIFIER: "varint",
    Property.SESSION_EXPIRY_INTERVAL: "u32",
    Property.ASSIGNED_CLIENT_IDENTIFIER: "utf8",
    Property.SERVER_KEEP_ALIVE: "u16",
    Property.AUTHENTICATION_METHOD: "utf8",
    Property.AUTHENTICATION_DATA: "bin",
    Property.REQUEST_PROBLEM_INFORMATION: "byte",
    Property.WILL_DELAY_INTERVAL: "u32",
    Property.REQUEST_RESPONSE_INFORMATION: "byte",
    Property.RESPONSE_INFORMATION: "utf8",
    Property.SERVER_REFERENCE: "utf8",
    Property.REASON_STRING: "utf8",
    Property.RECEIVE_MAXIMUM: "u16",
    Property.TOPIC_ALIAS_MAXIMUM: "u16",
    Property.TOPIC_ALIAS: "u16",
    Property.MAXIMUM_QOS: "byte",
    Property.RETAIN_AVAILABLE: "byte",
    Property.USER_PROPERTY: "utf8pair",
    Property.MAXIMUM_PACKET_SIZE: "u32",
    Property.WILDCARD_SUBSCRIPTION_AVAILABLE: "byte",
    Property.SUBSCRIPTION_IDENTIFIER_AVAILABLE: "byte",
    Property.SHARED_SUBSCRIPTION_AVAILABLE: "byte",
}

# Properties: dict {Property: value}; USER_PROPERTY maps to list[(k, v)];
# SUBSCRIPTION_IDENTIFIER may appear multiple times -> list[int].
Properties = Dict[int, Union[int, str, bytes, List]]


# ------------------------------------------------------------------ packets

@dataclass
class SubOpts:
    """Subscription options (v5 3.8.3.1; v3 carries only qos).

    `sub_id` is the v5 Subscription Identifier granted at subscribe time —
    session state, not part of the wire byte.
    """

    qos: int = 0
    no_local: bool = False
    retain_as_published: bool = False
    retain_handling: int = 0
    sub_id: Optional[int] = None

    def to_byte(self) -> int:
        return (
            (self.qos & 0x3)
            | (int(self.no_local) << 2)
            | (int(self.retain_as_published) << 3)
            | ((self.retain_handling & 0x3) << 4)
        )

    @staticmethod
    def from_byte(b: int) -> "SubOpts":
        return SubOpts(
            qos=b & 0x3,
            no_local=bool(b >> 2 & 1),
            retain_as_published=bool(b >> 3 & 1),
            retain_handling=b >> 4 & 0x3,
        )


@dataclass
class Connect:
    proto_name: str = "MQTT"
    proto_ver: int = MQTT_V4
    clean_start: bool = True
    keepalive: int = 60
    clientid: str = ""
    username: Optional[str] = None
    password: Optional[bytes] = None
    will_flag: bool = False
    will_qos: int = 0
    will_retain: bool = False
    will_topic: Optional[str] = None
    will_payload: Optional[bytes] = None
    will_props: Properties = field(default_factory=dict)
    properties: Properties = field(default_factory=dict)

    type: PacketType = PacketType.CONNECT


@dataclass
class Connack:
    session_present: bool = False
    reason_code: int = 0
    properties: Properties = field(default_factory=dict)

    type: PacketType = PacketType.CONNACK


@dataclass
class Publish:
    topic: str = ""
    payload: bytes = b""
    qos: int = 0
    retain: bool = False
    dup: bool = False
    packet_id: Optional[int] = None
    properties: Properties = field(default_factory=dict)

    type: PacketType = PacketType.PUBLISH


@dataclass
class PubAck:
    packet_id: int = 0
    reason_code: int = 0
    properties: Properties = field(default_factory=dict)
    type: PacketType = PacketType.PUBACK


@dataclass
class PubRec:
    packet_id: int = 0
    reason_code: int = 0
    properties: Properties = field(default_factory=dict)
    type: PacketType = PacketType.PUBREC


@dataclass
class PubRel:
    packet_id: int = 0
    reason_code: int = 0
    properties: Properties = field(default_factory=dict)
    type: PacketType = PacketType.PUBREL


@dataclass
class PubComp:
    packet_id: int = 0
    reason_code: int = 0
    properties: Properties = field(default_factory=dict)
    type: PacketType = PacketType.PUBCOMP


@dataclass
class Subscribe:
    packet_id: int = 0
    topic_filters: List[Tuple[str, SubOpts]] = field(default_factory=list)
    properties: Properties = field(default_factory=dict)
    type: PacketType = PacketType.SUBSCRIBE


@dataclass
class SubAck:
    packet_id: int = 0
    reason_codes: List[int] = field(default_factory=list)
    properties: Properties = field(default_factory=dict)
    type: PacketType = PacketType.SUBACK


@dataclass
class Unsubscribe:
    packet_id: int = 0
    topic_filters: List[str] = field(default_factory=list)
    properties: Properties = field(default_factory=dict)
    type: PacketType = PacketType.UNSUBSCRIBE


@dataclass
class UnsubAck:
    packet_id: int = 0
    reason_codes: List[int] = field(default_factory=list)
    properties: Properties = field(default_factory=dict)
    type: PacketType = PacketType.UNSUBACK


@dataclass
class PingReq:
    type: PacketType = PacketType.PINGREQ


@dataclass
class PingResp:
    type: PacketType = PacketType.PINGRESP


@dataclass
class Disconnect:
    reason_code: int = 0
    properties: Properties = field(default_factory=dict)
    type: PacketType = PacketType.DISCONNECT


@dataclass
class Auth:
    reason_code: int = 0
    properties: Properties = field(default_factory=dict)
    type: PacketType = PacketType.AUTH


Packet = Union[
    Connect,
    Connack,
    Publish,
    PubAck,
    PubRec,
    PubRel,
    PubComp,
    Subscribe,
    SubAck,
    Unsubscribe,
    UnsubAck,
    PingReq,
    PingResp,
    Disconnect,
    Auth,
]
