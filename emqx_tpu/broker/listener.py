"""Asyncio TCP/WebSocket listeners + per-connection driver.

Analog of `emqx_listeners.erl` + `emqx_connection.erl` (SURVEY.md §1.3-1.4):
where the reference runs one Erlang process per socket, the TPU-native host
plane runs one asyncio task per connection around the shared event loop —
connections are cheap coroutines, and publish batching across connections
feeds the device matcher (`PublishBatcher`).

Connection loop: read bytes -> Parser.feed -> Channel.handle_in -> actions
(send/close) -> writer.  Keepalive enforcement mirrors the reference's
1.5x window.
"""

from __future__ import annotations

import asyncio
import logging
import ssl
import time
from typing import Dict, List, Optional

from . import packet as pkt
from .broker import Broker
from .channel import Action, Channel, ChannelConfig
from .frame import (DEFAULT_MAX_SIZE, FrameError, Parser, serialize,
                    serialize_cached)
from ..observe.tracepoints import tp

log = logging.getLogger("emqx_tpu.listener")


class Connection:
    """Owns one client socket; drives its Channel."""

    def __init__(
        self,
        broker: Broker,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        config: Optional[ChannelConfig] = None,
        max_packet_size: Optional[int] = None,
        limiter=None,
    ):
        peer = writer.get_extra_info("peername")
        from ..utils.net import format_peername

        peername = format_peername(peer) if peer else "?"
        self.reader = reader
        self.writer = writer
        if max_packet_size is None:
            # single source: the zone-merged mqtt.max_packet_size (the
            # same limit the v5 CONNACK advertises)
            max_packet_size = (
                config.max_packet_size if config else DEFAULT_MAX_SIZE
            )
        self.parser = Parser(max_size=max_packet_size)
        # per-client token buckets chained to the listener's zone roots
        self._bytes_bucket = limiter.client("bytes_in") if limiter else None
        self._msg_bucket = limiter.client("message_in") if limiter else None
        self.channel = Channel(broker, config=config, peername=peername)
        self.channel.out_cb = self._send_actions
        self.channel.on_kick = self._on_kick
        # slow-consumer accounting for force_shutdown (unflushed bytes)
        self.channel.conn_buffer_fn = (
            lambda: writer.transport.get_write_buffer_size()
        )
        self.channel.conn_abort_fn = lambda: writer.transport.abort()
        self._closing: Optional[int] = None
        self._normal = False
        self._last_rx = time.monotonic()
        # CONNECT must COMPLETE within mqtt.idle_timeout of accept; a
        # fixed deadline, so trickled junk bytes cannot extend it
        self._connect_deadline = self._last_rx + (
            config.idle_timeout if config else 15.0
        )
        self._retry_task: Optional[asyncio.Task] = None
        self._paced_tasks: Dict[str, asyncio.Task] = {}
        # deferred-ack / cluster-sync tasks: retained so the GC cannot
        # drop them mid-flight; they self-evict on completion and the
        # stragglers are cancelled at connection shutdown
        self._io_tasks: set = set()
        # asyncio allows only one drain() waiter per transport
        self._drain_lock = asyncio.Lock()

    # -- outbound ---------------------------------------------------------

    def _send_actions(self, actions: List[Action]) -> None:
        bufs: List[bytes] = []
        for action in actions:
            kind = action[0]
            arg = action[1] if len(action) > 1 else None
            if kind == "send":
                try:
                    bufs.append(
                        serialize_cached(arg, self.channel.proto_ver)
                    )
                except Exception:
                    log.exception("serialize/send failed")
            elif kind == "ack_async":
                fut, builder = action[1], action[2]
                self._spawn_io(self._ack_when_done(fut, builder))
            elif kind == "cluster_sync":
                self._spawn_io(self._cluster_sync(action[1], action[2]))
            elif kind == "retained_paced":
                # flow-controlled retained re-delivery on subscribe;
                # a re-subscribe supersedes the previous paced tail
                real = action[1]
                old = self._paced_tasks.pop(real, None)
                if old is not None:
                    old.cancel()
                t = asyncio.ensure_future(
                    self._paced_retained(real, action[2])
                )
                self._paced_tasks[real] = t
                t.add_done_callback(
                    lambda _t, r=real: self._paced_tasks.pop(r, None)
                    if self._paced_tasks.get(r) is _t else None
                )
            elif kind == "retained_stop":
                # UNSUBSCRIBE: the remaining retained tail must not flow
                t = self._paced_tasks.pop(action[1], None)
                if t is not None:
                    t.cancel()
            elif kind == "close":
                self._closing = arg if arg is not None else -1
                self._normal = arg is None
            # 'connected' is informational
        if bufs:
            self._flush_bufs(bufs)

    def _flush_bufs(self, bufs: List[bytes]) -> None:
        """Vectored flush: every frame produced by one action batch
        (a connection's whole per-tick delivery batch on the scatter
        path) lands in the transport as ONE writelines call instead of
        one write per packet."""
        m = self.channel.broker.metrics
        try:
            if len(bufs) == 1:
                self.writer.write(bufs[0])
                m.inc("bytes.sent", len(bufs[0]))
                return
            total = sum(len(b) for b in bufs)
            self.writer.writelines(bufs)
            m.inc("bytes.sent", total)
            m.inc("deliver.flush.vectored")
            tp("deliver.flush", n=len(bufs), bytes=total)
        except Exception:
            log.exception("vectored send failed")

    def _spawn_io(self, coro) -> asyncio.Task:
        t = asyncio.ensure_future(coro)
        self._io_tasks.add(t)
        t.add_done_callback(self._io_tasks.discard)
        return t

    async def _cluster_sync(self, clientid: str, clean_start: bool) -> None:
        """Run the cross-node discard/takeover (post-auth; see
        Channel._connect_phase2), then resume the CONNECT."""
        cluster = getattr(self.channel.broker, "cluster", None)
        if cluster is not None:
            try:
                if clean_start:
                    await cluster.discard_remote(clientid)
                else:
                    await cluster.import_session(clientid)
            except Exception:
                log.exception("cluster session sync for %s", clientid)
        if self._closing is None:
            self._send_actions(self.channel.finish_cluster_sync())
            await self._drain()

    async def _ack_when_done(self, fut, builder) -> None:
        """Deferred publish ack: wait for the batched match, then respond."""
        try:
            n = await fut
        except Exception:
            n = 0
        p = builder(n)
        if p is not None and self._closing is None:
            try:
                data = serialize(p, self.channel.proto_ver)
                self.writer.write(data)
                self.channel.broker.metrics.inc("bytes.sent", len(data))
                await self._drain()
            except Exception:
                pass

    def _on_kick(self, rc: int) -> None:
        if self.channel.v5:
            try:
                self.writer.write(
                    serialize(pkt.Disconnect(reason_code=rc), pkt.MQTT_V5)
                )
            except Exception:
                pass
        self._closing = rc
        self._normal = False
        # wake the read loop
        try:
            self.writer.close()
        except Exception:
            pass

    # -- main loop --------------------------------------------------------

    async def run(self) -> None:
        m = self.channel.broker.metrics
        try:
            while self._closing is None:
                timeout = self._keepalive_timeout()
                try:
                    data = await asyncio.wait_for(self.reader.read(65536), timeout)
                except asyncio.TimeoutError:
                    if self._keepalive_expired():
                        log.info("keepalive timeout %s", self.channel.clientid)
                        break
                    continue
                if not data:
                    break
                self._last_rx = time.monotonic()
                m.inc("bytes.received", len(data))
                if self._bytes_bucket is not None:
                    await self._acquire(self._bytes_bucket, len(data), "bytes_in")
                try:
                    packets = self.parser.feed(data)
                except FrameError as e:
                    log.info("frame error from %s: %s", self.channel.peername, e)
                    # process wire-valid packets parsed before the error
                    for p in e.packets:
                        self._send_actions(self.channel.handle_in(p))
                    if self.channel.v5 and self.channel.state == "connected":
                        self.writer.write(
                            serialize(
                                pkt.Disconnect(reason_code=e.reason_code), pkt.MQTT_V5
                            )
                        )
                    self._normal = False
                    break
                for p in packets:
                    if (
                        self._msg_bucket is not None
                        and getattr(p, "type", None) == pkt.PacketType.PUBLISH
                    ):
                        await self._acquire(self._msg_bucket, 1, "message_in")
                    self._send_actions(self.channel.handle_in(p))
                    if self._closing is not None:
                        break
                await self._drain()
        except (ConnectionResetError, BrokenPipeError, ssl.SSLError):
            # SSLError: malformed records / close_notify races on a TLS
            # listener must drop the connection, not poison the event loop
            self._normal = False
        finally:
            await self._shutdown()

    async def _acquire(self, bucket, n: float, kind: str) -> None:
        """Park this connection's coroutine until n tokens are granted —
        the asyncio analog of the reference parking a client process in
        the limiter server's queue (backpressure, never drops)."""
        while not bucket.try_consume(n):
            self.channel.broker.metrics.inc(f"olp.delayed.{kind}")
            await asyncio.sleep(min(max(bucket.wait_time(n), 0.001), 5.0))

    async def _drain(self) -> None:
        try:
            async with self._drain_lock:
                await self.writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            self._closing = self._closing or -1

    def _deadline_remaining(self) -> Optional[float]:
        """Seconds until this connection's silence deadline; None = no
        deadline.  One place for the three-state rule: pre-CONNECT
        sockets die at a FIXED mqtt.idle_timeout after accept (without
        the gate a silent — or byte-trickling — socket held a Connection
        forever); mid enhanced-auth / cluster-sync waits are broker-side
        and never expire here; connected clients get the keepalive *
        backoff window, no keepalive = no deadline (MQTT-3.1.2-22)."""
        ch = self.channel
        if ch.state == "idle":
            return self._connect_deadline - time.monotonic()
        if ch.state != "connected":
            if getattr(ch, "_pending_phase2", None) is not None:
                return None  # broker-side cluster sync: own RPC timeouts
            # enhanced-auth waits on the CLIENT: the connect deadline
            # still applies (a silent mid-AUTH socket must not be held)
            return self._connect_deadline - time.monotonic()
        ka = ch.keepalive
        if not ka:
            return None
        return (ka * ch.cfg.keepalive_multiplier
                - (time.monotonic() - self._last_rx))

    def _keepalive_timeout(self) -> float:
        rem = self._deadline_remaining()
        return 30.0 if rem is None else rem + 0.05

    def _keepalive_expired(self) -> bool:
        rem = self._deadline_remaining()
        return rem is not None and rem <= 0

    async def _paced_retained(self, real: str, msgs) -> None:
        """Deliver a large retained set in paced batches from the lazy
        trie iterator (`emqx_retainer` flow control: batch_read_number +
        deliver interval); stops silently when the connection closes."""
        import itertools
        from dataclasses import replace as _replace

        batch = self.channel.cfg.retained_batch
        ivl = self.channel.cfg.retained_interval
        while self._closing is None:
            chunk = list(itertools.islice(msgs, batch))
            if not chunk:
                return
            self.channel.deliver([
                (real, _replace(m, headers=dict(m.headers, retained=True)))
                for m in chunk
            ])
            await self._drain()
            await asyncio.sleep(ivl)

    async def _shutdown(self) -> None:
        for t in list(self._paced_tasks.values()):
            t.cancel()
        self._paced_tasks.clear()
        for t in list(self._io_tasks):
            t.cancel()
        self._io_tasks.clear()
        try:
            await self._drain()
        except Exception:
            pass
        self.channel.terminate(normal=self._normal)
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except Exception:
            pass


class Listener:
    """One TCP listening socket fanning out Connections."""

    def __init__(
        self,
        broker: Broker,
        host: str = "127.0.0.1",
        port: int = 1883,
        config: Optional[ChannelConfig] = None,
        max_connections: int = 0,
        batcher=None,  # PublishBatcher: batch publishes across connections
        housekeeping_interval: float = 1.0,
        limiter=None,
        olp=None,
        tls=None,  # TlsConfig: terminate TLS on this listener (ssl type)
        psk_store=None,  # PskStore wired into the TLS handshake (3.13+)
        reuse_port: bool = False,  # SO_REUSEPORT: wire workers bind the
        # same port; the kernel load-balances accepts across processes
        sock_fd: Optional[int] = None,  # pre-bound listening socket
        # inherited from the wire supervisor (reuseport fallback)
        max_conn_rate: float = 0.0,  # per-listener accept token bucket
        # (wire.max_conn_rate); 0 = unlimited
    ):
        self.broker = broker
        self.host = host
        self.port = port
        self.config = config
        self.max_connections = max_connections
        self.batcher = batcher
        self.housekeeping_interval = housekeeping_interval
        self.limiter = limiter
        self.olp = olp
        self.tls = tls
        self.psk_store = psk_store
        self.reuse_port = reuse_port
        self.sock_fd = sock_fd
        self._accept_bucket = None
        if max_conn_rate and max_conn_rate > 0:
            from .limiter import TokenBucket

            # burst 2x: a brief legitimate spike (fleet wake) clears,
            # a sustained reconnect storm sheds at the configured rate
            self._accept_bucket = TokenBucket(
                max_conn_rate, burst=max(2 * max_conn_rate, 1.0)
            )
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()
        self._hk_task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        ssl_ctx = None
        handshake_timeout = None
        if self.tls is not None:
            from .tls import make_server_context

            ssl_ctx = make_server_context(self.tls, self.psk_store)
            handshake_timeout = self.tls.handshake_timeout
        kw = dict(ssl=ssl_ctx, ssl_handshake_timeout=handshake_timeout)
        if self.sock_fd is not None:
            # wire-plane reuseport fallback: adopt the listening socket
            # the supervisor bound once and passed down (family/type
            # recovered from the fd) — all workers accept on ONE socket
            import socket as _socket

            sock = _socket.socket(fileno=self.sock_fd)
            sock.setblocking(False)
            self._server = await asyncio.start_server(
                self._on_client, sock=sock, **kw
            )
        else:
            if self.reuse_port:
                kw["reuse_port"] = True
            self._server = await asyncio.start_server(
                self._on_client, self.host, self.port, **kw
            )
        addr = self._server.sockets[0].getsockname()
        self.port = addr[1]  # resolve port 0
        if self.batcher is not None:
            self.batcher.start()
        # broker-global timers run once per broker, not once per listener;
        # the listener set lets ownership hand over when the owner stops
        if not hasattr(self.broker, "_listeners"):
            self.broker._listeners = set()
        self.broker._listeners.add(self)
        if getattr(self.broker, "_hk_owner", None) is None:
            self.broker._hk_owner = self
            self._hk_task = asyncio.create_task(self._housekeeping())
        log.info("mqtt listener on %s:%s", self.host, self.port)

    async def _housekeeping(self) -> None:
        """Periodic broker timers: QoS retries, awaiting-rel expiry, auth
        expiry, pending-session eviction, retained GC (`emqx_session`
        timers + `emqx_cm`/retainer GC processes in the reference)."""
        n = 0
        while True:
            t0 = time.monotonic()
            await asyncio.sleep(self.housekeeping_interval)
            if self.olp is not None:
                # scheduling lag of this loop = how overloaded the host is
                lag = time.monotonic() - t0 - self.housekeeping_interval
                self.olp.note_lag(lag)
            n += 1
            try:
                now = time.time()
                for ch in list(self.broker.cm.channels.values()):
                    try:
                        exp = ch.clientinfo.attrs.get("expire_at")
                        if exp is not None and now >= exp:
                            # credential expired: force disconnect
                            self.broker.cm.kick_session(
                                ch.clientid, pkt.ReasonCode.NOT_AUTHORIZED
                            )
                            continue
                        if self._force_shutdown_check(ch):
                            continue
                        acts = ch.handle_retry() + ch.handle_expire_awaiting_rel()
                        if acts:
                            ch.out_cb(acts)
                    except Exception:
                        log.exception(
                            "housekeeping for %s", getattr(ch, "clientid", "?")
                        )
                self.broker.cm.evict_expired()
                p = getattr(self.broker, "persistence", None)
                if p is not None:
                    p.tick()
                if n % 60 == 0:
                    self.broker.retainer.clean_expired()
            except Exception:
                log.exception("housekeeping tick failed")

    def _force_shutdown_check(self, ch) -> bool:
        """force_shutdown (emqx_channel force-shutdown policy analog):
        kill a connection whose unflushed outbound backlog exceeds
        max_message_queue_len KiB — the reference bounds the channel
        process's mailbox in messages; this runtime bounds the
        transport's pending bytes, the closest slow-consumer signal an
        asyncio transport exposes.  Returns True when the channel was
        killed."""
        fs = getattr(self.broker, "force_shutdown", None)
        if not fs or not fs[0]:
            return False
        fn = getattr(ch, "conn_buffer_fn", None)
        if fn is None:
            return False
        try:
            backlog = fn()
        except Exception:
            return False
        if backlog > fs[1] * 1024:
            log.warning("force_shutdown: %s outbound backlog %d bytes",
                        getattr(ch, "clientid", "?"), backlog)
            self.broker.metrics.inc("channels.force_shutdown")
            self.broker.cm.kick_session(
                ch.clientid, pkt.ReasonCode.QUOTA_EXCEEDED
            )
            # hard-abort: a graceful close would wait for the very
            # backlog this kill exists to reclaim
            abort = getattr(ch, "conn_abort_fn", None)
            if abort is not None:
                try:
                    abort()
                except Exception:
                    pass
            return True
        return False

    def accept_gate(self, writer) -> bool:
        """Shed-before-protocol-work gate shared by the TCP and WS
        accept paths (emqx_olp + esockd limiter ordering): connection
        cap, loop-lag overload shed, the per-listener accept-rate
        bucket (`wire.max_conn_rate` — a reconnect storm is refused at
        the accept boundary instead of stalling the loop with thousands
        of half-born Connections), then the zone connection limiter.
        False = socket closed, caller must not build a Connection."""
        if self.max_connections and len(self._conns) >= self.max_connections:
            writer.close()
            return False
        if self.olp is not None and not self.olp.should_accept():
            # overloaded: shed before any protocol work (emqx_olp)
            self.broker.metrics.inc("olp.new_conn.shed")
            writer.close()
            return False
        if self._accept_bucket is not None \
                and not self._accept_bucket.try_consume(1.0):
            self.broker.metrics.inc("olp.new_conn.rate_limited")
            tp("olp.accept.shed", port=self.port)
            writer.close()
            return False
        if self.limiter is not None and not self.limiter.check("connection"):
            self.broker.metrics.inc("olp.new_conn.rate_limited")
            writer.close()
            return False
        return True

    async def _on_client(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        if not self.accept_gate(writer):
            return
        conn = Connection(
            self.broker, reader, writer, self.config, limiter=self.limiter
        )
        self._attach_tls_identity(conn, writer)
        if self.batcher is not None:
            conn.channel.publish_fn = self.batcher.submit
        task = asyncio.current_task()
        self._conns.add(task)
        try:
            await conn.run()
        finally:
            self._conns.discard(task)

    def _attach_tls_identity(self, conn: Connection, writer) -> None:
        """Expose the verified peer cert (and the listener's cert-as-identity
        options) to the channel; shared by the TCP and WS listener paths."""
        if self.tls is None:
            return
        from .tls import peer_cert_info

        conn.channel.peer_cert = peer_cert_info(
            writer.get_extra_info("ssl_object")
        )
        conn.channel.cert_as_username = self.tls.peer_cert_as_username
        conn.channel.cert_as_clientid = self.tls.peer_cert_as_clientid

    async def stop(self) -> None:
        getattr(self.broker, "_listeners", set()).discard(self)
        if self._hk_task:
            self._hk_task.cancel()
            self._hk_task = None
            if getattr(self.broker, "_hk_owner", None) is self:
                self.broker._hk_owner = None
                # hand broker housekeeping to a surviving listener
                for other in getattr(self.broker, "_listeners", set()):
                    if other._server is not None:
                        self.broker._hk_owner = other
                        other._hk_task = asyncio.create_task(
                            other._housekeeping()
                        )
                        break
        if self.batcher is not None:
            await self.batcher.stop()
        if self._server:
            self._server.close()
        # Python 3.12: Server.wait_closed() waits for all connection
        # handlers, so live connections must be cancelled first.
        tasks = list(self._conns)
        for t in tasks:
            t.cancel()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
        if self._server:
            await self._server.wait_closed()
        # a stopped listener reports running=False and can be started
        # again (REST /listeners/{id}/start)
        self._server = None

    @property
    def current_connections(self) -> int:
        return len(self._conns)
