"""Rate limiting + overload protection — `emqx_limiter`/`emqx_olp` analog.

The reference runs a hierarchical token bucket server: per-client
buckets refill from shared zone buckets, limiting connection rate,
inbound message rate, and inbound bytes (SURVEY.md §2.1 Limiter row).
`emqx_olp` defers load (new connections, GC) when the VM is congested;
`emqx_congestion` raises alarms when a socket's send buffer backs up.

Redesign for the asyncio host plane:
  * `TokenBucket` — monotonic-clock lazy refill, optional parent chain
    (child consume draws from every ancestor, the htb topology);
  * `Limiter` — named root buckets per zone with `client()` children;
  * an over-budget connection coroutine simply `await`s its wait time —
    the per-task analog of the reference parking a process in the
    limiter server's queue;
  * `Olp` — event-loop lag watermark gate for new connections;
  * `Congestion` — write-buffer watermark alarms per connection.
"""

from __future__ import annotations

import time
from typing import Dict, Optional


class TokenBucket:
    def __init__(
        self,
        rate: float,
        burst: Optional[float] = None,
        parent: Optional["TokenBucket"] = None,
    ):
        """rate: tokens/second; burst: bucket capacity (default = rate)."""
        self.rate = float(rate)
        self.capacity = float(burst if burst is not None else rate)
        self.parent = parent
        self.tokens = self.capacity
        self._t = time.monotonic()

    def _refill(self, now: float) -> None:
        dt = now - self._t
        if dt > 0:
            self.tokens = min(self.capacity, self.tokens + dt * self.rate)
            self._t = now

    def try_consume(self, n: float = 1.0, now: Optional[float] = None) -> bool:
        """Atomically take n tokens from self and all ancestors."""
        now = now if now is not None else time.monotonic()
        chain = []
        node: Optional[TokenBucket] = self
        while node is not None:
            node._refill(now)
            if node.tokens < n:
                return False
            chain.append(node)
            node = node.parent
        for node in chain:
            node.tokens -= n
        return True

    def wait_time(self, n: float = 1.0, now: Optional[float] = None) -> float:
        """Seconds until n tokens could be available along the chain."""
        now = now if now is not None else time.monotonic()
        worst = 0.0
        node: Optional[TokenBucket] = self
        while node is not None:
            node._refill(now)
            if node.tokens < n:
                if node.rate <= 0:
                    return float("inf")
                worst = max(worst, (n - node.tokens) / node.rate)
            node = node.parent
        return worst


class Limiter:
    """Zone-level shared buckets with per-client children.

    kinds mirror the reference's limiter types: "connection" (accept
    rate), "message_in" (PUBLISH/s), "bytes_in" (inbound bytes/s).
    rate <= 0 disables a kind (infinite).
    """

    KINDS = ("connection", "message_in", "bytes_in")

    def __init__(self, **rates: Optional[dict]):
        # rates: kind -> {"rate": r, "burst": b, "client_rate": cr,
        #                 "client_burst": cb}
        self.roots: Dict[str, TokenBucket] = {}
        self.client_cfg: Dict[str, dict] = {}
        for kind in self.KINDS:
            cfg = rates.get(kind)
            if not cfg or cfg.get("rate", 0) <= 0:
                continue
            self.roots[kind] = TokenBucket(cfg["rate"], cfg.get("burst"))
            self.client_cfg[kind] = cfg

    def enabled(self, kind: str) -> bool:
        return kind in self.roots

    def check(self, kind: str, n: float = 1.0) -> bool:
        """Zone-level check (connection accepts use this directly)."""
        root = self.roots.get(kind)
        return True if root is None else root.try_consume(n)

    def client(self, kind: str) -> Optional[TokenBucket]:
        """A fresh per-client bucket chained to the zone root."""
        root = self.roots.get(kind)
        if root is None:
            return None
        cfg = self.client_cfg[kind]
        rate = cfg.get("client_rate") or cfg["rate"]
        burst = cfg.get("client_burst") or cfg.get("burst")
        return TokenBucket(rate, burst, parent=root)


class Olp:
    """Overload protection: shed new connections under event-loop lag.

    The reference's `lc` flags the VM overloaded from run-queue length;
    here the listener housekeeping loop reports its own scheduling lag
    (`note_lag`), and while the high watermark was crossed recently,
    `should_accept()` answers False (`emqx_olp:backoff_new_conn`).
    """

    def __init__(self, lag_high_s: float = 0.5, cooldown_s: float = 5.0):
        self.lag_high = lag_high_s
        self.cooldown = cooldown_s
        self.enabled = True  # runtime kill switch (emqx_ctl olp enable)
        self._overloaded_until = 0.0
        self.shed_count = 0
        # extra pressure source beyond loop lag: the pipelined publish
        # path keeps the loop responsive even when the device falls
        # behind, so the batcher's in-flight tick depth must feed the
        # same shed decision (wired by the node runtime)
        self.pressure_fn = None  # () -> bool

    def note_lag(self, lag_s: float, now: Optional[float] = None) -> None:
        now = now if now is not None else time.monotonic()
        if lag_s >= self.lag_high:
            self._overloaded_until = now + self.cooldown

    @property
    def overloaded(self) -> bool:
        if time.monotonic() < self._overloaded_until:
            return True
        return self.pressure_fn is not None and bool(self.pressure_fn())

    def should_accept(self) -> bool:
        if self.enabled and self.overloaded:
            self.shed_count += 1
            return False
        return True

    def status(self) -> dict:
        return {
            "enable": self.enabled,
            "overloaded": self.overloaded,
            "lag_high_s": self.lag_high,
            "cooldown_s": self.cooldown,
            "shed_count": self.shed_count,
        }


class Congestion:
    """Per-connection TCP send-buffer congestion alarms
    (`emqx_congestion.erl`): alarm when the asyncio transport's write
    buffer exceeds the high watermark, clear once fully drained."""

    def __init__(self, alarms=None, high_watermark: int = 1_048_576):
        self.alarms = alarms
        self.high = high_watermark
        self.congested: set = set()

    def check(self, clientid: str, writer) -> bool:
        try:
            size = writer.transport.get_write_buffer_size()
        except Exception:
            return False
        if size > self.high and clientid not in self.congested:
            self.congested.add(clientid)
            if self.alarms is not None:
                self.alarms.activate(
                    f"conn_congestion/{clientid}",
                    {"buffer": size, "high_watermark": self.high},
                )
            return True
        if size == 0 and clientid in self.congested:
            self.congested.discard(clientid)
            if self.alarms is not None:
                self.alarms.deactivate(f"conn_congestion/{clientid}")
        return clientid in self.congested
