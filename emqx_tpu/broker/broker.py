"""Broker core: subscribe/publish/dispatch over the TPU match engine.

Analog of `emqx_broker.erl` + `emqx_router.erl` (SURVEY.md §1.7, §3.3-3.4),
redesigned around batched device matching:

* subscriptions feed the `TopicMatchEngine` (the HBM route/trie mirror) and
  host-side fid -> subscriber maps (the ETS `emqx_subscriber` analog);
* a publish batch is matched on device in one shot; the broker expands
  matched fids to sessions, applies shared-subscription picks host-side,
  and drives per-channel delivery;
* every stage runs its hook points ('message.publish', 'message.dropped',
  'message.delivered', 'session.subscribed', ...) so the extension layer
  (rule engine, exhook bridge, retainer) composes exactly like the
  reference's.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from . import topic as topiclib
from .cm import ConnectionManager
from .delivery import scatter_template
from .hooks import Hooks
from .message import Message
from ..observe import spans as _spans
from ..observe.tracepoints import tp
from .metrics import Metrics
from .packet import Property, SubOpts
from .retainer import Retainer
from .session import Session
from .shared_sub import SharedSub
from .subshard import SubscriberShards
from ..models.engine import TopicMatchEngine


@dataclass
class PendingPublish:
    """An in-flight three-phase publish (submit -> collect -> finish)."""

    todo: List[Tuple[int, Message]]
    results: List[int]
    pending: object  # engine _PendingMatch (or None for an empty tick)
    matched: Optional[List[List[int]]] = None
    exc: Optional[BaseException] = None  # collect failure (batcher drain)
    # sampled message-lifecycle span contexts riding this tick
    # (observe/spans.py; empty when the plane is disarmed)
    spans: List[object] = field(default_factory=list)
    # in-flight semantic-plane tick riding the same three phases
    # (semantic/plane.py _PendingPlane; None when the plane is off or
    # has no live queries)
    sem: Optional[object] = None


@dataclass
class Route:
    """Host-side fan-out record for one unique filter (one fid).

    Direct subscribers live in the broker's `SubscriberShards` expansion
    layer (the `emqx_broker_helper` analog), keyed by the same fid."""

    filt: str
    groups: Set[str] = field(default_factory=set)  # shared groups


class Broker:
    def __init__(
        self,
        engine: Optional[TopicMatchEngine] = None,
        cm: Optional[ConnectionManager] = None,
        hooks: Optional[Hooks] = None,
        retainer: Optional[Retainer] = None,
        shared: Optional[SharedSub] = None,
        metrics: Optional[Metrics] = None,
    ):
        self.engine = engine or TopicMatchEngine()
        self.cm = cm or ConnectionManager()
        self.hooks = hooks or Hooks()
        self.retainer = retainer or Retainer()
        self.shared = shared or SharedSub()
        self.metrics = metrics or Metrics()
        # durable message log (ds/DsManager when ds.enable): QoS>=1
        # publishes reaching parked cursor-holding sessions append to
        # the shared log instead of per-session mqueues
        self.ds = None
        # sharded asyncio delivery-worker pool (delivery.DeliveryPool,
        # wired by the node when broker.delivery_workers > 0): dispatch
        # hands per-connection batches to per-shard queues instead of
        # walking every receiver on its own call stack; None = deliver
        # inline (tests, benches, non-async callers)
        self.delivery = None
        self._routes: Dict[int, Route] = {}  # fid -> fan-out record
        self.subs = SubscriberShards()  # fid -> sharded subscriber lists
        self._sub_count = 0
        # broadcast scatter-lane cache: uid -> (out_cb, proto_ver,
        # scatter_plain map) for scatter_fast channels, False for
        # receivers the general path must serve.  Entries die with the
        # channel registration (cm.on_channel_change) or the uid slot
        # (subs.on_uid_released — uids are recycled); the maps inside
        # an entry are the session's own, mutated in place by
        # subscribe/unsubscribe, so subscription churn needs no
        # invalidation here.
        self._fast_cbs: Dict[int, Any] = {}
        self.cm.on_channel_change = self._drop_fast_cb
        self.subs.on_uid_released = (
            lambda uid: self._fast_cbs.pop(uid, None)
        )
        self.cm.on_discard = self._on_discard_session
        # exact-match guarantee: surface discarded hash collisions
        self.engine.on_collision = lambda topic, fid: self.metrics.inc(
            "match.hash_collision"
        )
        # route-table change callbacks (cluster layer announces these to
        # peers — the `emqx_router:do_add_route` replication point)
        self.on_route_added: Optional[callable] = None
        self.on_route_removed: Optional[callable] = None
        # shared-group membership announcements + remote dispatch hooks
        # (cluster layer; the mria shared_sub table analog).  A shared
        # message is delivered by exactly ONE node: the origin picks
        # local members first (or by the group's strategy), and falls
        # back to a TARGETED forward to one member-holding peer — the
        # generic route forward never dispatches shared groups.
        self.on_shared_added: Optional[callable] = None  # (group, filt)
        self.on_shared_removed: Optional[callable] = None
        self.shared_remote_nodes: Optional[callable] = None  # -> Set[str]
        self.forward_shared: Optional[callable] = None  # (node, msg, g, f)
        # semantic subscription plane (semantic/plane.py, wired by the
        # node when semantic.enable): `$semantic/<query>` filters bypass
        # the trie/churn plane entirely and live here.  forward_semantic
        # ships a matched message to the wire worker owning the remote
        # queries (cluster layer; sem-tagged FORWARD frames).
        self.semantic = None
        self.forward_semantic: Optional[callable] = None  # (node, msg, qids)

    def _drop_fast_cb(self, cid: str) -> None:
        uid = self.subs._uids.get(cid)
        if uid is not None:
            self._fast_cbs.pop(uid, None)

    def _on_discard_session(self, session: Session) -> None:
        """Discarded session: drop its routes (kicked channels skip this)."""
        self.client_down(
            session.clientid, list(session.subscriptions), session=session
        )
        self.metrics.inc("session.discarded")

    # -------------------------------------------------------- subscribe

    def subscribe(self, clientid: str, filt: str, opts: SubOpts) -> None:
        """Register one subscription (parses $share/$queue prefixes).

        The engine's filter refcount mirrors UNIQUE memberships exactly:
        a duplicate subscribe (same client, same filter) takes no extra
        reference, so a later unsubscribe can never free a fid that
        routes/subscribers still use."""
        # semantic filters are a subscription CLASS (the $share/
        # discipline): they never touch the engine, churn WAL,
        # checkpoint registry, or route oplog — the plane owns them
        query = topiclib.parse_semantic(filt)
        if query is not None:
            if self.semantic is not None and \
                    self.semantic.subscribe(clientid, query):
                self._sub_count += 1
                self.metrics.gauge_set(
                    "subscriptions.count", self._sub_count
                )
            self.hooks.run("session.subscribed", (clientid, filt, opts))
            return
        group, real = topiclib.parse_share(filt)
        fid = self.engine.add_filter(real)
        route = self._routes.get(fid)
        if route is None:
            route = self._routes[fid] = Route(filt=real)
        if group is None:
            added = self.subs.add(fid, clientid)
            # DIRECT routes only ride the generic route table (shared
            # membership is announced separately — a generic forward
            # must not reach shared-only nodes)
            if (
                added
                and self.subs.count(fid) == 1
                and self.on_route_added is not None
            ):
                self.on_route_added(real)
        else:
            added = not self.shared.is_member(group, real, clientid)
            new_group = self.shared.subscribe(group, real, clientid)
            route.groups.add(group)
            if new_group and self.on_shared_added is not None:
                self.on_shared_added(group, real)
        if added:
            self._sub_count += 1
        else:
            self.engine.remove_filter(real)  # duplicate: drop the extra ref
        self.metrics.gauge_set("subscriptions.count", self._sub_count)
        self.hooks.run("session.subscribed", (clientid, filt, opts))

    def subscribe_bulk(
        self, clientid: str, filts: Sequence[str], opts: SubOpts
    ) -> List[int]:
        """Bulk subscribe for bootstrap paths (persistent-session restore,
        bench/dryrun loads): one engine.add_filters pass plus batched
        route/subscriber bookkeeping — semantically identical to calling
        subscribe() per filter (non-shared filters only; $share prefixes
        route through the per-op path)."""
        plain: List[str] = []
        plain_pos: List[int] = []
        fids_out: List[Optional[int]] = [None] * len(filts)
        for i, f in enumerate(filts):
            if topiclib.parse_semantic(f) is not None:
                self.subscribe(clientid, f, opts)  # plane, no fid
                continue
            group, real = topiclib.parse_share(f)
            if group is not None:  # shared: per-op semantics
                self.subscribe(clientid, f, opts)
                fids_out[i] = self.engine.fid_of(real)
                continue
            plain.append(f)
            plain_pos.append(i)
        if plain:
            fids = self.engine.add_filters(plain)
            for f, fid, pos in zip(plain, fids, plain_pos):
                route = self._routes.get(fid)
                if route is None:
                    self._routes[fid] = Route(filt=f)
                added = self.subs.add(fid, clientid)
                if (
                    added
                    and self.subs.count(fid) == 1
                    and self.on_route_added is not None
                ):
                    self.on_route_added(f)
                if added:
                    self._sub_count += 1
                else:
                    self.engine.remove_filter(f)  # duplicate membership
                self.hooks.run("session.subscribed", (clientid, f, opts))
                fids_out[pos] = fid
        self.metrics.gauge_set("subscriptions.count", self._sub_count)
        return fids_out

    def unsubscribe(self, clientid: str, filt: str) -> None:
        query = topiclib.parse_semantic(filt)
        if query is not None:
            if self.semantic is not None and \
                    self.semantic.unsubscribe(clientid, query):
                self._sub_count -= 1
                self.metrics.gauge_set(
                    "subscriptions.count", self._sub_count
                )
            self.hooks.run("session.unsubscribed", (clientid, filt))
            return
        group, real = topiclib.parse_share(filt)
        fid = self.engine.fid_of(real)
        if fid is None:
            return
        route = self._routes.get(fid)
        removed = False
        if route is not None:
            if group is None:
                removed = self.subs.remove(fid, clientid)
                if (
                    removed
                    and not self.subs.count(fid)
                    and self.on_route_removed is not None
                ):
                    self.on_route_removed(real)
            else:
                removed = self.shared.is_member(group, real, clientid)
                if self.shared.unsubscribe(group, real, clientid):
                    route.groups.discard(group)
                    if self.on_shared_removed is not None:
                        self.on_shared_removed(group, real)
            if removed:
                self._sub_count -= 1
            if not self.subs.count(fid) and not route.groups:
                del self._routes[fid]
        if removed:
            # only an actual membership drops an engine reference — an
            # unsubscribe from a never-subscribed client is a no-op
            self.engine.remove_filter(real)
        self.metrics.gauge_set("subscriptions.count", self._sub_count)
        self.hooks.run("session.unsubscribed", (clientid, filt))

    def client_down(
        self, clientid: str, filters: Sequence[str], session=None
    ) -> None:
        """Clean a dead client's routes (`emqx_broker_helper:clean_down`).

        When the dying session is supplied, its undelivered shared-group
        messages are redispatched to surviving members first."""
        if session is not None:
            self.redispatch_shared_pending(session)
        for f in list(filters):
            self.unsubscribe(clientid, f)
        # stragglers not covered by the filters list: every removed
        # membership holds one engine ref + one sub count, and an
        # emptied group must release its route + announcement
        for group, real, emptied in self.shared.drop_member(clientid):
            self._sub_count -= 1
            fid = self.engine.fid_of(real)
            route = self._routes.get(fid) if fid is not None else None
            if emptied:
                if route is not None:
                    route.groups.discard(group)
                if self.on_shared_removed is not None:
                    self.on_shared_removed(group, real)
            if (
                route is not None
                and not self.subs.count(fid)
                and not route.groups
            ):
                del self._routes[fid]
            self.engine.remove_filter(real)
        # semantic stragglers (filters list incomplete): the plane knows
        # every query the client still holds
        if self.semantic is not None:
            self._sub_count -= self.semantic.client_down(clientid)
        self.metrics.gauge_set("subscriptions.count", self._sub_count)

    @property
    def subscription_count(self) -> int:
        return self._sub_count

    @property
    def route_count(self) -> int:
        return len(self._routes)

    def sync_engine_metrics(self) -> None:
        """Copy the match engine's cumulative telemetry counters into the
        metrics table (engine.* names in PREDEFINED).  The engine owns
        the counters — they increment on its hot path without touching
        the broker — and this sync runs at observation points only
        (stats collect, exporter render, $SYS heartbeat)."""
        e = self.engine
        c = self.metrics.counters
        fl = getattr(e, "flight", None)
        c["engine.ticks"] = (
            fl.n if fl is not None
            else getattr(e, "host_serve_count", 0)
            + getattr(e, "dev_serve_count", 0)
        )
        c["engine.host_serve"] = getattr(e, "host_serve_count", 0)
        c["engine.dev_serve"] = getattr(e, "dev_serve_count", 0)
        c["engine.dev_timeout"] = getattr(e, "dev_timeout_count", 0)
        c["engine.path_flips"] = getattr(e, "path_flips", 0)
        c["engine.verify_mismatch"] = getattr(e, "collision_count", 0)
        c["engine.probes"] = getattr(e, "probe_count", 0)
        c["engine.breaker_trips"] = getattr(e, "breaker_trips", 0)
        c["engine.churn_shed"] = getattr(e, "churn_shed", 0)
        # fused-prep topic memo + prep-ahead degrade counters (both
        # engines carry a TopicPrep; PR 6's bench-JSON-only counters
        # promoted to first-class metrics)
        c["engine.memo_hits"] = getattr(e, "memo_hits", 0)
        c["engine.memo_misses"] = getattr(e, "memo_misses", 0)
        c["engine.prep_degraded"] = getattr(e, "prep_degraded", 0)
        # shared-memory match plane client (shm/client.py): submit and
        # degrade accounting for an engine-less wire worker
        if getattr(e, "shm_submits", None) is not None:
            c["shm.submits"] = e.shm_submits
            c["shm.degraded"] = e.shm_degraded
            c["shm.local_serves"] = e.shm_local
            c["shm.oversize"] = e.shm_oversize
            c["shm.reregisters"] = e.shm_reregisters
        # delivery plane: codec-owned shared-prefix cache telemetry
        # (frame.PREFIX_STATS) copied at the same observation points
        from . import frame as framelib

        c["deliver.prefix.hit"] = framelib.PREFIX_STATS["hit"]
        c["deliver.prefix.miss"] = framelib.PREFIX_STATS["miss"]
        r = self.retainer
        c["retained.lookups.index"] = r.index_serves
        c["retained.lookups.trie"] = r.trie_serves
        c["retained.index.flips"] = r.path_flips
        c["retained.index.probes"] = r.probe_count
        idx = r.index
        if idx is not None:
            c["retained.index.collisions"] = idx.collision_count
            c["retained.index.fallbacks"] = idx.fallbacks
            c["retained.index.refetches"] = idx.refetches
            self.metrics.gauge_set("retained.index.shapes",
                                   idx.shape_count)
            self.metrics.gauge_set("retained.index.entries",
                                   idx.entry_count)
        # semantic plane: the plane owns its counters (engine's ride
        # along in local mode), copied at the same observation points
        if self.semantic is not None:
            c.update(self.semantic.counters())
            self.metrics.gauge_set("semantic.queries",
                                   self.semantic.n_queries)
            self.metrics.gauge_set("semantic.subscribers",
                                   self.semantic.n_subs)

    # ---------------------------------------------------------- publish

    def publish(self, msg: Message) -> int:
        """Publish one message; returns the number of deliveries."""
        return self.publish_many([msg])[0]

    def publish_many(self, msgs: Sequence[Message]) -> List[int]:
        """Batched publish — the TPU hot path (`emqx_broker:publish`).

        Runs 'message.publish' hooks, retains, matches the whole batch on
        device in one kernel, then dispatches host-side.
        """
        pp = self.publish_submit(msgs)
        self.publish_collect(pp)
        return self.publish_finish(pp)

    # The three-phase publish contract (used by PublishBatcher to pipeline
    # ticks and keep the engine's blocking collect OFF the event loop —
    # the reference's dispatch hot loop never parks the scheduler,
    # `emqx_broker.erl:499-524`):
    #   submit  (loop thread)   hooks + retain + cluster forwards + match
    #                           dispatch; returns immediately
    #   collect (any thread)    blocks on the match result; touches no
    #                           broker state, so it is executor-safe
    #   finish  (loop thread)   fid expansion + local delivery

    def publish_submit(
        self, msgs: Sequence[Message], prep=None
    ) -> "PendingPublish":
        """``prep`` is an optional prep-ahead ticket (the sharded
        engine's `prep_submit`, staged by PublishBatcher for the next
        queued chunk): the engine claims it when its topics still match
        the accepted batch and degrades to inline prep otherwise."""
        todo, results, ticked = self._prepare_publish(msgs)
        if todo:
            self._pre_match(todo)
        pending = None
        sem = None
        if todo:
            topics = [m.topic for _, m in todo]
            pending = (
                self.engine.match_submit(topics, prep=prep)
                if prep is not None
                else self.engine.match_submit(topics)
            )
            if self.semantic is not None:
                # meaning-match rides the same tick: device/hub work
                # overlaps the engine's hash match
                sem = self.semantic.submit([m.payload for _, m in todo])
        elif prep is not None:
            self.engine.prep_discard(prep)
        for ctx in ticked:
            _spans.mark(ctx, "submit")
        return PendingPublish(todo, results, pending, spans=ticked,
                              sem=sem)

    def publish_collect(self, pp: "PendingPublish") -> "PendingPublish":
        if pp.pending is not None:
            pp.matched = self.engine.match_collect_raw(pp.pending)
        if pp.sem is not None:
            self.semantic.collect(pp.sem)  # blocking half, loop-free
        for ctx in pp.spans:
            _spans.mark(ctx, "collect")
        return pp

    def publish_finish(self, pp: "PendingPublish") -> List[int]:
        if pp.pending is not None:
            # per-connection delivery batches accumulate across the
            # WHOLE tick (uid -> (cid, ch, [(filt, msg)...])) and flush
            # once per connection — one vectored write per receiver per
            # tick instead of one write per (receiver, message)
            sink: Dict[int, Tuple[str, object, list]] = {}
            sem_local: List[List[Tuple[str, str]]] = []
            if pp.sem is not None:
                sem_local, sem_remote = self.semantic.finish(pp.sem)
                fwd = self.forward_semantic
                for node, qids, k in sem_remote:
                    # full message to the worker owning the queries —
                    # the hub only ever saw the embed prefix
                    if fwd is not None and fwd(node, pp.todo[k][1], qids):
                        self.metrics.inc("semantic.forwards")
            for k, ((i, msg), fids) in enumerate(zip(pp.todo, pp.matched)):
                n = self._dispatch(msg, fids, sink=sink)
                if k < len(sem_local):
                    for cid, sfilt in sem_local[k]:
                        n += self._deliver_to(cid, [sfilt], msg)
                tp("dispatch_done", topic=msg.topic, mid=msg.mid, receivers=n)
                pp.results[i] = n
                if n == 0:
                    self.metrics.inc("messages.dropped.no_subscribers")
                    self.hooks.run("message.dropped", (msg, "no_subscribers"))
            # delivery-plane hand-off boundary: batches built, shards
            # (or the inline flush below) take over the wire movement
            for ctx in pp.spans:
                _spans.mark(ctx, "enqueue")
            self._flush_deliveries(sink)
        return pp.results

    def _flush_deliveries(
        self, sink: Dict[int, Tuple[str, object, list]]
    ) -> None:
        """Hand each connection's tick batch to its delivery shard (or
        deliver inline when no pool is wired / the shard pushed back)."""
        pool = self.delivery
        for uid, (cid, ch, delivers) in sink.items():
            if len(delivers) > 1:
                self.metrics.inc(
                    "messages.delivered.batched", len(delivers)
                )
            if pool is not None:
                if not pool.submit(uid, cid, ch, delivers):
                    pool._deliver(cid, ch, delivers)
            elif self.cm.lookup(cid) is ch:
                ch.deliver(delivers)
            else:
                # receiver vanished mid-tick (hook kicked it): park the
                # copies in its session rather than dropping them
                for f, m in delivers:
                    self.deliver_offline(cid, [f], m)

    def _pre_match(self, todo: List[Tuple[int, Message]]) -> None:
        """Between accept and match: the cluster layer forwards here."""

    def _prepare_publish(
        self, msgs: Sequence[Message]
    ) -> Tuple[List[Tuple[int, Message]], List[int], List[object]]:
        """Hook + retain stage; returns the accepted (index, msg) list
        plus any sampled span contexts (observe/spans.py: head-sampled
        at ingress, the 'hooks' boundary closes on accept)."""
        todo: List[Tuple[int, Message]] = []
        results = [0] * len(msgs)
        ticked: List[object] = []
        sp_on = _spans.enabled()
        for i, msg in enumerate(msgs):
            ctx = _spans.begin(msg.topic, msg.mid) if sp_on else None
            msg = self.hooks.run_fold("message.publish", (), msg)
            if msg is None or msg.headers.get("allow_publish") is False:
                self.metrics.inc("messages.dropped")
                self.hooks.run("message.dropped", (msg, "publish_denied"))
                continue
            self.retainer.on_publish(msg)
            self.metrics.inc("messages.received")
            tp("publish_enter", topic=msg.topic, mid=msg.mid)
            if ctx is not None:
                msg.headers["__span"] = ctx
                _spans.mark(ctx, "hooks")
                ticked.append(ctx)
            todo.append((i, msg))
        return todo, results, ticked

    def _match_dispatch(
        self, todo: List[Tuple[int, Message]], results: List[int]
    ) -> None:
        """Device-match the accepted batch and deliver locally."""
        if not todo:
            return
        pending = self.engine.match_submit([m.topic for _, m in todo])
        matched = self.engine.match_collect_raw(pending)
        for (i, msg), fids in zip(todo, matched):
            n = self._dispatch(msg, fids)
            tp("dispatch_done", topic=msg.topic, mid=msg.mid, receivers=n)
            results[i] = n
            if n == 0:
                self.metrics.inc("messages.dropped.no_subscribers")
                self.hooks.run("message.dropped", (msg, "no_subscribers"))

    def _dispatch(
        self, msg: Message, fids, include_shared: bool = True,
        sink: Optional[Dict[int, Tuple[str, object, list]]] = None,
    ) -> int:
        """Expand matched fids to receivers and deliver (`do_dispatch`).

        Expansion is vectorized through the subscriber-shard layer: one
        concatenate over the matched fids' bucket arrays + one grouping
        pass, so per-receiver cost is a single delivery call regardless
        of fan-out (`emqx_broker.erl:499-524` without per-sub dict ops).

        With `sink` (the tick-scoped per-connection accumulator from
        publish_finish), online receivers are APPENDED per uid instead
        of delivered inline — receiver counts, metrics and hooks still
        settle here at dispatch time; only the wire movement is
        deferred to the flush/worker stage."""
        fid_filts = []
        for fid in fids:
            route = self._routes.get(fid)
            if route is not None:
                fid_filts.append((fid, route.filt))
        n = 0
        if len(fid_filts) == 1:
            n += self._scatter_one_filter(msg, fid_filts[0], sink)
        elif sink is None:
            for cid, filts in self.subs.expand(fid_filts):
                n += self._deliver_to(cid, filts, msg)
        else:
            lookup = self.cm.lookup
            minc = self.metrics.inc
            hrun = self.hooks.run
            for uid, cid, filts in self.subs.expand_uids(fid_filts):
                ch = lookup(cid)
                if ch is None:
                    n += self.deliver_offline(cid, filts, msg)
                    continue
                ent = sink.get(uid)
                if ent is None:
                    ent = sink[uid] = (cid, ch, [])
                ent[2].extend((f, msg) for f in filts)
                minc("messages.delivered", len(filts))
                hrun("message.delivered", (cid, msg))
                n += len(filts)
        # shared groups deliver one-at-a-time with failover so a dead
        # pick redispatches to a peer (`emqx_shared_sub:dispatch` retry)
        if include_shared:
            for fid in fids:
                route = self._routes.get(fid)
                if route is None:
                    continue
                for group in route.groups:
                    n += self._dispatch_shared(msg, group, route.filt)
        return n

    def _scatter_one_filter(
        self, msg: Message, fid_filt: Tuple[int, str], sink,
    ) -> int:
        """Broadcast lane of _dispatch: ONE matched filter, many
        receivers — the shape that caps alert-to-millions scenarios.
        Everything receiver-invariant is hoisted out of the loop (the
        delivers pair-list is shared across receivers: channels never
        retain or mutate it), per-receiver allocation drops to zero on
        the online path, and metrics/hook dispatch batch to one update
        per broadcast when no hook subscribes."""
        fid, filt = fid_filt
        uids, cids = self.subs.scatter(fid)
        if not uids:
            return 0
        lookup = self.cm.lookup
        hooks_live = self.hooks.has("message.delivered")
        hrun = self.hooks.run
        dl = [(filt, msg)]  # shared: deliver() treats it as read-only
        n = 0
        delivered = 0
        if sink is None:
            # plain-receiver fast lane: a QoS0 message without an
            # expiry rewrite reaches every scatter_fast channel whose
            # subscription is plain (session.scatter_plain) through ONE
            # shared action list per proto version — the receiver loop
            # touches the channel, its plain map, and out_cb, nothing
            # else (metrics batch below; the packet/message counters a
            # channel would have incremented live in the same broker
            # table, so batching is observationally identical)
            fast_msg = (
                msg.qos == 0
                and Property.MESSAGE_EXPIRY_INTERVAL not in msg.properties
            )
            retain_inv = msg.retain if msg.headers.get("retained") \
                else False
            by_ver: Dict[int, list] = {}
            scache = None
            fcbs = self._fast_cbs
            fget = fcbs.get
            fastn = 0
            for uid, cid in zip(uids, cids):
                ent = fget(uid) if fast_msg else False
                if ent is None:  # uncached receiver: classify once
                    ch = lookup(cid)
                    if ch is None:
                        n += self.deliver_offline(cid, [filt], msg)
                        continue
                    ent = fcbs[uid] = (
                        (ch.out_cb, ch.proto_ver, ch.scatter_plain)
                        if getattr(ch, "scatter_fast", False)
                        else False
                    )
                if ent and ent[2].get(filt):
                    cb, ver, _plain = ent
                    act = by_ver.get(ver)
                    if act is None:
                        if scache is None:
                            scache = msg.headers.get("__scatter")
                            if scache is None:
                                scache = msg.headers["__scatter"] = {}
                        key = (ver, retain_inv, None)
                        tent = scache.get(key)
                        if tent is None:
                            tent = scache[key] = scatter_template(msg, key)
                        act = by_ver[ver] = tent[1]
                    cb(act)
                    fastn += 1
                else:
                    ch = lookup(cid)
                    if ch is None:
                        n += self.deliver_offline(cid, [filt], msg)
                        continue
                    ch.deliver(dl)
                if hooks_live:
                    hrun("message.delivered", (cid, msg))
                delivered += 1
            if fastn:
                self.metrics.inc("packets.publish.sent", fastn)
                self.metrics.inc("messages.sent", fastn)
            if delivered and _spans.armed:
                # the fast-cb lane bypasses Channel.deliver (the wire
                # boundary's usual close point): close it here, once
                # per broadcast, never per receiver
                _spans.wire(dl)
        else:
            pair = (filt, msg)
            sget = sink.get
            for uid, cid in zip(uids, cids):
                ch = lookup(cid)
                if ch is None:
                    n += self.deliver_offline(cid, [filt], msg)
                    continue
                ent = sget(uid)
                if ent is None:
                    sink[uid] = (cid, ch, [pair])
                else:
                    ent[2].append(pair)
                if hooks_live:
                    hrun("message.delivered", (cid, msg))
                delivered += 1
        if delivered:
            self.metrics.inc("messages.delivered", delivered)
        return n + delivered

    def dispatch_semantic_forwarded(self, msg: Message,
                                    hub_qids: List[int]) -> int:
        """Receiving side of a sem-tagged cluster forward: the origin
        worker matched this message against the POOL's query table and
        we own some of the hits — map the hub's qids to local queries
        and deliver.  No re-match, no further forwarding (no loops)."""
        if self.semantic is None:
            return 0
        self.metrics.inc("messages.forward.in")
        n = 0
        for cid, sfilt in self.semantic.deliver_remote(hub_qids):
            n += self._deliver_to(cid, [sfilt], msg)
        return n

    def dispatch_shared_forwarded(self, msg: Message, group: str, filt: str) -> int:
        """Receiving side of a TARGETED shared forward: deliver to one
        local member only — the origin owns cluster-wide responsibility
        for this copy, so no further remote fallback (no loops)."""
        self.metrics.inc("messages.forward.in")
        return self._dispatch_shared(msg, group, filt, allow_remote=False)

    def _dispatch_shared(
        self,
        msg: Message,
        group: str,
        filt: str,
        exclude: Optional[Set[str]] = None,
        allow_remote: bool = True,
    ) -> int:
        """Deliver to ONE group member, failing over across members until
        a delivery lands (`emqx_shared_sub.erl:118-130`).  The delivered
        copy is tagged with its (group, filter) so pending copies can be
        redispatched if the member dies before acking.

        Cluster order of preference: live local members (per the group's
        strategy), then a member-holding peer node (targeted forward),
        then a parked local persistent session.  The `local` strategy
        (`emqx_shared_sub.erl:61-66`) is this ordering by construction;
        for the other strategies the local preference is a documented
        approximation of the reference's cluster-wide member pick."""
        from dataclasses import replace

        tried: Set[str] = set(exclude or ())
        skey = topiclib.join_share(group, filt)
        tagged = replace(
            msg, headers={**msg.headers, "shared": (group, filt)}
        )
        parked_fallback: Optional[str] = None
        while True:
            pick = self.shared.pick(
                group, filt, msg.topic, msg.from_client, exclude=tried
            )
            if pick is None:
                break
            if self.cm.lookup(pick) is None:
                # disconnected member: prefer a live one; remember the
                # first parked persistent session as last resort
                if (
                    parked_fallback is None
                    and self.cm.lookup_session(pick) is not None
                ):
                    parked_fallback = pick
                tried.add(pick)
                self.shared.member_failed(group, filt, pick)
                continue
            # deliver under the client's own subscription key
            # ($share/<g>/<filt>) so session subopts/QoS apply
            n = self._deliver_to(pick, [skey], tagged)
            if n > 0:
                return n
            tried.add(pick)
            self.shared.member_failed(group, filt, pick)
        if allow_remote and self.shared_remote_nodes is not None:
            nodes = list(self.shared_remote_nodes(group, filt))
            self.shared._rng.shuffle(nodes)  # spread failover load
            for node in nodes:
                if self.forward_shared is not None and self.forward_shared(
                    node, msg, group, filt
                ):
                    return 1
        if parked_fallback is not None:
            n = self._deliver_to(parked_fallback, [skey], tagged)
            if n > 0:
                return n
        self.metrics.inc("messages.dropped.no_shared_member")
        return 0

    def redispatch_shared_pending(self, session) -> int:
        """A member died with undelivered shared messages: hand its
        pending copies (mqueue + unacked inflight) to other members
        (`emqx_shared_sub:redispatch`, session-terminate path).

        wait_comp entries are excluded — the receiver already holds the
        QoS2 message; redispatching would duplicate it.

        Entries are CONSUMED from the dying session as they are handed
        over, so a second sweep over the same session (terminate and
        discard can both fire) redispatches nothing twice."""
        dead = session.clientid
        pending: List[Message] = []
        for m in session.mqueue.drain_all():
            if m.headers.get("shared"):
                pending.append(m)
        for pid, ent in list(session.inflight.items()):
            m = ent.message
            if (
                ent.phase in ("wait_ack", "wait_rec")
                and m is not None
                and m.headers.get("shared")
            ):
                session.inflight.delete(pid)
                pending.append(m)
        n = 0
        for m in pending:
            group, filt = m.headers["shared"]
            if self.shared.is_member(group, filt, dead):
                # membership not yet dropped (redispatch before clean)
                n += self._dispatch_shared(m, group, filt, exclude={dead})
            else:
                n += self._dispatch_shared(m, group, filt)
            self.metrics.inc("messages.shared.redispatched")
        return n

    def _deliver_to(self, cid: str, filts: List[str], msg: Message) -> int:
        ch = self.cm.lookup(cid)
        if ch is not None:
            ch.deliver([(f, msg) for f in filts])
            self.metrics.inc("messages.delivered", len(filts))
            self.hooks.run("message.delivered", (cid, msg))
            return len(filts)
        return self.deliver_offline(cid, filts, msg)

    def deliver_offline(self, cid: str, filts: List[str],
                        msg: Message) -> int:
        """Queue one message for a parked persistent session (also the
        delivery-worker fallback for a receiver that disconnected
        between dispatch and drain)."""
        session = self.cm.lookup_session(cid)
        if session is None:
            return 0
        # offline persistent session: queue per matched filter, honoring
        # the same subopts Session.deliver applies online.  With the
        # durable log enabled and the session holding a replay cursor,
        # QoS>=1 copies live in the SHARED log instead — appended once
        # per message (mid-deduped across parked receivers) and
        # reconstructed by cursor replay on resume; shared-group copies
        # stay on the in-memory path (exactly-one-member ownership).
        use_ds = (
            self.ds is not None
            and msg.qos >= 1
            and not msg.headers.get("shared")
            and session.ds_cursor is not None
        )
        n = 0
        for f in filts:
            opts = session.subscriptions.get(f)
            if opts is None:
                continue
            if opts.no_local and msg.from_client == session.clientid:
                continue
            if use_ds:
                n += 1
                continue
            qos = max(msg.qos, opts.qos) if session.upgrade_qos else min(msg.qos, opts.qos)
            from dataclasses import replace

            session.enqueue(replace(msg, qos=qos))
            n += 1
        if n:
            if use_ds:
                self.ds.on_offline_publish(msg)
            self.metrics.inc("messages.queued", n)
            p = getattr(self, "persistence", None)
            if p is not None:
                p.mark_dirty(cid)
        return n

    # ------------------------------------------------- retained delivery

    def retained_iter(self, filt: str, rh: int, is_new_sub: bool):
        """Lazily yield retained messages for a new subscription (v5
        retain-handling); large sets are consumed in paced batches by
        the connection (flow control, `emqx_retainer.erl:85-150`)."""
        if topiclib.parse_semantic(filt) is not None:
            return iter(())  # semantic filters match meaning, not names
        group, real = topiclib.parse_share(filt)
        if group is not None:
            return iter(())  # shared subs never get retained messages
        if rh == 2 or (rh == 1 and not is_new_sub):
            return iter(())
        return self.retainer.iter_filter(real)

    def retained_for(self, filt: str, rh: int, is_new_sub: bool) -> List[Message]:
        """Retained messages to deliver on subscribe (v5 retain-handling)."""
        return list(self.retained_iter(filt, rh, is_new_sub))
