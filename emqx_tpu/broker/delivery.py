"""Sharded asyncio delivery-worker pool — the esockd conn-sup analog.

The reference decomposes its listeners into acceptor + connection
supervisor pools (`esockd_acceptor_sup` / `esockd_connection_sup`,
PAPER.md §1.3) so one slow socket never serializes the others.  Here the
same decomposition is applied to the broadcast fan-out hot loop: the
broker's dispatch stage partitions receivers by connection shard
(`shard = subscriber-uid % workers`, keeping per-connection packet order
by construction), appends per-connection delivery batches to per-shard
queues, and a pool of asyncio worker tasks drains the shards
concurrently — a 50k-receiver broadcast no longer runs as one
uninterruptible loop on the dispatch call stack.

Backpressure is per shard and per connection, and NEVER blocks:

* a shard queue past ``queue_max`` items delivers the overflow batch
  inline on the dispatch path (counted ``deliver.shard.backpressure``)
  instead of growing without bound;
* a connection whose transport write buffer exceeds
  ``backpressure_bytes`` is counted + traced but not awaited — the
  worker moves on to the next receiver, so a stalled socket cannot
  head-of-line-block its shard (the force_shutdown policy in
  listener.py reaps the pathological cases).

A receiver that disconnects between dispatch and drain is re-routed to
its parked session (offline enqueue) instead of dropped, so a
mid-broadcast disconnect loses nothing and duplicates nothing.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, List, Tuple

from . import packet as pkt
from .message import Message
from .packet import Property
from ..observe.tracepoints import tp

log = logging.getLogger("emqx_tpu.delivery")


def scatter_template(msg: Message, key: Tuple[int, bool, Any]) -> tuple:
    """Build the shared PUBLISH template (and its reusable one-item
    action list) for one (proto version, retain, sub-id) receiver class
    of a message — the unit the broadcast scatter lane hands to every
    receiver of that class (channel._scatter_deliver and
    broker._scatter_one_filter share these via msg.headers['__scatter'])."""
    _ver, retain, sub = key
    props = dict(msg.properties)
    if sub is not None:
        props[Property.SUBSCRIPTION_IDENTIFIER] = [sub]
    tmpl = pkt.Publish(
        topic=msg.topic,
        payload=msg.payload,
        qos=0,
        retain=retain,
        dup=False,
        packet_id=None,
        properties=props,
    )
    # a sub-id makes the properties receiver-class-specific: such
    # templates hold a PRIVATE prefix dict (the shared per-message dict
    # assumes props == msg.properties)
    tmpl._wire_prefix = (
        msg.headers.setdefault("__wire_prefix", {})
        if sub is None else {}
    )
    return tmpl, [("send", tmpl)]


class DeliveryPool:
    def __init__(
        self,
        broker,
        workers: int = 4,
        queue_max: int = 4096,
        backpressure_bytes: int = 1 << 20,
    ):
        self.broker = broker
        self.workers = max(1, int(workers))
        self.queue_max = queue_max
        self.backpressure_bytes = backpressure_bytes
        self._queues: List[asyncio.Queue] = []
        self._tasks: List[asyncio.Task] = []
        self.active = False
        self.batches = 0
        self.delivered = 0

    # ---------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self.active:
            return
        self._queues = [asyncio.Queue() for _ in range(self.workers)]
        self._tasks = [
            asyncio.create_task(self._worker(i)) for i in range(self.workers)
        ]
        self.active = True

    async def stop(self) -> None:
        """Drain every shard queue, then stop the workers.  Queued
        batches are delivered inline so shutdown loses nothing."""
        self.active = False
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
        self._tasks = []
        for q in self._queues:
            while not q.empty():
                cid, ch, delivers = q.get_nowait()
                self._deliver(cid, ch, delivers)
        self._queues = []

    # ----------------------------------------------------------- dispatch

    def shard_of(self, uid: int) -> int:
        return uid % self.workers

    def queue_depths(self) -> List[int]:
        """Per-shard queue depth snapshot (contention telemetry:
        observe/contention.py gauges `deliver.queue_depth*`)."""
        return [q.qsize() for q in self._queues]

    def submit(self, uid: int, cid: str, ch, delivers: List[Tuple]) -> bool:
        """Queue one connection's delivery batch on its shard; returns
        False when the pool is down or the shard is saturated — the
        caller must then deliver inline (bounded memory, no silent
        drops)."""
        if not self.active:
            return False
        q = self._queues[uid % self.workers]
        if q.qsize() >= self.queue_max:
            self.broker.metrics.inc("deliver.shard.backpressure")
            tp("deliver.backpressure", shard=uid % self.workers,
               depth=q.qsize())
            return False
        q.put_nowait((cid, ch, delivers))
        return True

    # ------------------------------------------------------------ workers

    async def _worker(self, i: int) -> None:
        q = self._queues[i]
        drained = 0
        while True:
            cid, ch, delivers = await q.get()
            try:
                self._deliver(cid, ch, delivers, shard=i)
            except Exception:
                log.exception("delivery shard %d: %s", i, cid)
            drained += 1
            if q.empty() or drained >= 64:
                # yield between bursts so other shards (and the
                # connections' own read loops) interleave with a long
                # broadcast drain
                drained = 0
                await asyncio.sleep(0)

    def _deliver(self, cid: str, ch, delivers: List[Tuple],
                 shard: int = -1) -> None:
        live = self.broker.cm.lookup(cid)
        if live is not ch:
            # receiver disconnected (or was taken over) mid-broadcast:
            # the message set is re-routed through the offline path so
            # a persistent session still gets exactly one copy
            for filt, msg in delivers:
                self.broker.deliver_offline(cid, [filt], msg)
            return
        ch.deliver(delivers)
        self.batches += 1
        self.delivered += len(delivers)
        tp("deliver.batch", shard=shard, cid=cid, n=len(delivers))
        buf_fn = getattr(ch, "conn_buffer_fn", None)
        if buf_fn is not None:
            try:
                backlog = buf_fn()
            except Exception:
                return
            if backlog > self.backpressure_bytes:
                # slow consumer: record it and MOVE ON — the transport
                # buffers, force_shutdown reaps the extreme cases, and
                # the rest of the shard keeps flowing
                self.broker.metrics.inc("deliver.shard.backpressure")
                tp("deliver.backpressure", cid=cid, bytes=backlog)
