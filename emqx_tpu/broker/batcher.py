"""Publish batcher: aggregate concurrent publishes into one device match.

This is the TPU-native replacement for the reference's per-message hot loop
(`emqx_broker:publish` -> `emqx_router:match_routes`, one ETS walk per
message): publishes from all connections are drained into a tick batch and
matched on device in a single static-shape kernel call (BASELINE.json: "on
each tick the plugin drains the publish mailbox, ships a batch of topic
strings to a TPU-resident topic-matching automaton").

Latency/throughput trade: a batch closes either when `max_batch` messages
are pending or `max_delay` elapses after the first message of the tick —
the small-tick policy that keeps p99 inside the latency budget
(SURVEY.md §7.3).
"""

from __future__ import annotations

import asyncio
from typing import List, Optional, Tuple

from .broker import Broker
from .message import Message


class PublishBatcher:
    def __init__(
        self,
        broker: Broker,
        max_batch: int = 4096,
        max_delay: float = 0.002,
    ):
        self.broker = broker
        self.max_batch = max_batch
        self.max_delay = max_delay
        self._q: List[Tuple[Message, asyncio.Future]] = []
        self._wakeup: Optional[asyncio.Event] = None
        self._task: Optional[asyncio.Task] = None
        self.ticks = 0
        self.batched_messages = 0

    def start(self) -> None:
        if self._task is None:
            self._wakeup = asyncio.Event()
            self._task = asyncio.create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        self._flush_now()

    def submit(self, msg: Message) -> "asyncio.Future[int]":
        """Queue a message for the next tick; resolves to delivery count."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._q.append((msg, fut))
        if self._task is None or self._task.done():
            self._task = None  # restart after a crashed tick
            self.start()
        self._wakeup.set()
        if len(self._q) >= self.max_batch:
            self._flush_now()
        return fut

    def _flush_now(self) -> None:
        batch, self._q = self._q, []
        if not batch:
            return
        self.ticks += 1
        self.batched_messages += len(batch)
        try:
            results = self.broker.publish_many([m for m, _ in batch])
        except Exception as e:
            # a failed tick must never strand futures (acks would hang)
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)
            return
        for (m, fut), n in zip(batch, results):
            if not fut.done():
                fut.set_result(n)

    async def _run(self) -> None:
        import logging

        log = logging.getLogger("emqx_tpu.batcher")
        while True:
            await self._wakeup.wait()
            self._wakeup.clear()
            if not self._q:
                continue
            # tick window: let concurrent publishers join the batch
            try:
                await asyncio.sleep(self.max_delay)
                self._flush_now()
            except asyncio.CancelledError:
                self._flush_now()
                raise
            except Exception:  # keep the batcher alive at all costs
                log.exception("batch tick failed")
