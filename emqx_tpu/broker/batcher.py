"""Publish batcher: aggregate concurrent publishes into one device match.

This is the TPU-native replacement for the reference's per-message hot loop
(`emqx_broker:publish` -> `emqx_router:match_routes`, one ETS walk per
message): publishes from all connections are drained into a tick batch and
matched on device in a single static-shape kernel call (BASELINE.json: "on
each tick the plugin drains the publish mailbox, ships a batch of topic
strings to a TPU-resident topic-matching automaton").

Latency/throughput trade: a batch closes either when `max_batch` messages
are pending or `max_delay` elapses after the first message of the tick —
the small-tick policy that keeps p99 inside the latency budget
(SURVEY.md §7.3).

Pipelined: each tick is SUBMITTED on the event loop (hooks, retain,
cluster forwards, match dispatch — all non-blocking), then its blocking
match collect runs in an executor thread while the loop keeps serving
connections, keepalives and REST, and while the NEXT tick submits — so
host hashing/upload of tick N overlaps device compute of tick N-1, and a
device stall can never freeze the node (the reference's dispatch hot loop
never parks the scheduler either, `emqx_broker.erl:499-524`).  Delivery
(`publish_finish`) happens back on the loop in tick order.

The engines bound their own submitted-but-unresolved window at
``engine.pipeline_depth`` (force-resolving the oldest tick past it), so
``max_inflight`` here only has to be AT LEAST that deep to keep the
dispatch pipeline fed — the node wires it to
``max(32, engine.pipeline_depth)``.
"""

from __future__ import annotations

import asyncio
import logging
from typing import List, Optional, Tuple

from .broker import Broker
from .message import Message

log = logging.getLogger("emqx_tpu.batcher")


class PublishBatcher:
    def __init__(
        self,
        broker: Broker,
        max_batch: int = 4096,
        max_delay: float = 0.002,
        max_inflight: int = 32,
    ):
        self.broker = broker
        self.max_batch = max_batch
        self.max_delay = max_delay
        # hard ceiling on queued in-flight ticks: past it _run holds new
        # flushes until the consumer frees a slot (ordering preserved,
        # tick memory bounded).  Soft pressure is shed earlier via
        # Olp.pressure_fn, which the node wires to inflight_ticks.
        self.max_inflight = max_inflight
        self._q: List[Tuple[Message, asyncio.Future]] = []
        # prep-ahead ticket for the NEXT chunk (sharded engine's prep
        # pipeline stage): staged at the previous flush so the packed
        # upload buffer is built while this tick's dispatch is in
        # flight; the engine validates topics at claim time and
        # degrades to inline prep on any mismatch
        self._prep_ticket = None
        self._wakeup: Optional[asyncio.Event] = None
        self._task: Optional[asyncio.Task] = None
        self._consumer: Optional[asyncio.Task] = None
        self._ticks_q: Optional[asyncio.Queue] = None
        # tick whose collect thread was cancelled mid-flight; stop()
        # finishes it after the executor thread drains
        self._interrupted: Optional[tuple] = None
        self.ticks = 0
        self.batched_messages = 0

    def start(self) -> None:
        """(Re)start the tick and consumer tasks.  The tick queue is
        created once and survives restarts — queued in-flight ticks must
        never be orphaned (their publish futures would hang QoS acks)."""
        if self._wakeup is None:
            self._wakeup = asyncio.Event()
        if self._ticks_q is None:
            self._ticks_q = asyncio.Queue()
        if self._task is None or self._task.done():
            self._task = asyncio.create_task(self._run())
        if self._consumer is None or self._consumer.done():
            self._consumer = asyncio.create_task(self._consume())

    async def stop(self) -> None:
        for t in (self._task, self._consumer):
            if t is not None:
                t.cancel()
                try:
                    await t
                except asyncio.CancelledError:
                    pass
        self._task = None
        self._consumer = None
        # drain in order: the interrupted tick (waiting for its executor
        # thread — collect must never run twice concurrently), then the
        # queued ticks, then the open batch
        if self._interrupted is not None:
            batch, pp, done_evt = self._interrupted
            self._interrupted = None
            if done_evt is None:
                # collect never started: run it end-to-end here
                self._finish_tick(batch, pp)
            else:
                # wait OFF the loop; on timeout the thread is wedged on
                # a dead device — fail the futures, never collect twice
                done = await asyncio.to_thread(done_evt.wait, 60.0)
                err = pp.exc if done else TimeoutError(
                    "publish collect wedged at shutdown"
                )
                if err is None:
                    self._finish_tick(batch, pp, collected=True)
                else:
                    for _, fut in batch:
                        if not fut.done():
                            fut.set_exception(err)
        if self._ticks_q is not None:
            while not self._ticks_q.empty():
                batch, pp = self._ticks_q.get_nowait()
                self._finish_tick(batch, pp)
        self._flush_now(pipelined=False)
        ticket, self._prep_ticket = self._prep_ticket, None
        if ticket is not None:
            # the chunk it was staged for flushed unpipelined above
            self.broker.engine.prep_discard(ticket)

    def submit(self, msg: Message) -> "asyncio.Future[int]":
        """Queue a message for the next tick; resolves to delivery count."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._q.append((msg, fut))
        self.start()  # no-op when healthy; restarts a crashed task
        self._wakeup.set()
        if (
            len(self._q) >= self.max_batch
            and self._ticks_q.qsize() < self.max_inflight
        ):
            # at the in-flight ceiling the _run task flushes once room
            # appears (ordering preserved; memory bounded; Olp pressure
            # sheds new load meanwhile)
            self._flush_now()
        return fut

    @property
    def inflight_ticks(self) -> int:
        return self._ticks_q.qsize() if self._ticks_q is not None else 0

    def _flush_now(self, pipelined: bool = True) -> None:
        """Close the open batch and submit it in max_batch-sized ticks
        (a backlog accumulated during a ceiling wait must not become one
        giant never-compiled-before batch shape); synchronous end-to-end
        on the shutdown path (pipelined=False)."""
        while self._q:
            self._flush_chunk(pipelined)
            if pipelined and self._q:
                # remainder flushes from _run (respecting the ceiling)
                self._wakeup.set()
                break

    def _flush_chunk(self, pipelined: bool = True) -> None:
        batch = self._q[: self.max_batch]
        self._q = self._q[self.max_batch:]
        if not batch:
            return
        self.ticks += 1
        self.batched_messages += len(batch)
        ticket, self._prep_ticket = self._prep_ticket, None
        # stage the next queued chunk's prep while this chunk's
        # submit+dispatch runs (engines without a prep stage skip this)
        prep_submit = getattr(self.broker.engine, "prep_submit", None)
        if pipelined and prep_submit is not None and self._q:
            self._prep_ticket = prep_submit(
                [m.topic for m, _ in self._q[: self.max_batch]]
            )
        try:
            pp = self.broker.publish_submit(
                [m for m, _ in batch], prep=ticket
            )
        except Exception as e:
            # a failed tick must never strand futures (acks would hang)
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)
            return
        if pipelined and self._ticks_q is not None:
            self._ticks_q.put_nowait((batch, pp))
        else:
            self._finish_tick(batch, pp)

    def _finish_tick(self, batch, pp, collected: bool = False) -> None:
        try:
            if not collected:
                self.broker.publish_collect(pp)
            results = self.broker.publish_finish(pp)
        except Exception as e:
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)
            return
        for (m, fut), n in zip(batch, results):
            if not fut.done():
                fut.set_result(n)

    def _collect_tick(self, pp, done_evt) -> None:
        """Executor-thread body: collect, always signalling completion
        (stop() waits on the event to avoid a concurrent second collect)."""
        try:
            self.broker.publish_collect(pp)
        except BaseException as e:
            pp.exc = e  # visible to stop()'s interrupted-tick drain
            raise
        finally:
            done_evt.set()

    async def _consume(self) -> None:
        """Collect + deliver ticks in submit order; the blocking collect
        runs in the default executor so the loop never waits on the
        device, and delivery happens back on the loop thread."""
        import threading

        loop = asyncio.get_running_loop()
        while True:
            batch, pp = await self._ticks_q.get()
            done_evt = threading.Event()
            efut = loop.run_in_executor(None, self._collect_tick, pp, done_evt)
            try:
                await efut
            except asyncio.CancelledError:
                if efut.cancelled():
                    # the work item was cancelled BEFORE a pool thread
                    # picked it up: nothing is running, collect fresh in
                    # stop()'s drain (evt None marks not-started)
                    self._interrupted = (batch, pp, None)
                else:
                    # the executor thread cannot be interrupted — hand
                    # the tick to stop(), which waits for the thread and
                    # then delivers (never two collects on one tick)
                    self._interrupted = (batch, pp, done_evt)
                raise
            except Exception as e:
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)
                continue
            try:
                results = self.broker.publish_finish(pp)
            except Exception as e:
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)
                log.exception("publish finish failed")
                continue
            for (m, fut), n in zip(batch, results):
                if not fut.done():
                    fut.set_result(n)

    async def _run(self) -> None:
        while True:
            await self._wakeup.wait()
            self._wakeup.clear()
            if not self._q:
                continue
            # tick window: let concurrent publishers join the batch
            try:
                await asyncio.sleep(self.max_delay)
                # in-flight ceiling: hold the batch until the consumer
                # frees a slot — the loop stays live, ordering holds,
                # and tick memory is bounded (Olp.pressure_fn sheds new
                # load from inflight_ticks well before this point)
                while self._ticks_q.qsize() >= self.max_inflight:
                    await asyncio.sleep(self.max_delay)
                self._flush_now()
                if self._q:  # arrivals during the ceiling wait
                    self._wakeup.set()
            except asyncio.CancelledError:
                self._flush_now()
                raise
            except Exception:  # keep the batcher alive at all costs
                log.exception("batch tick failed")
