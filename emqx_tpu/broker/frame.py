"""MQTT wire codec: incremental parser + serializer for v3.1/3.1.1/5.0.

The Python analog of the reference's `emqx_frame.erl` (continuation-state
binary parser, `apps/emqx/src/emqx_frame.erl:114-169,221+`) — property-tested
round-trip like `prop_emqx_frame`.  A C++ fast path can replace the byte
loops behind the same API (see ops/native).
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

from . import packet as pkt
from .packet import PacketType, Property, PROPERTY_TYPES, ReasonCode

MAX_REMAINING = 268_435_455  # 4-byte varint max
DEFAULT_MAX_SIZE = 1_048_576  # matches reference default max_packet_size 1MB


class FrameError(Exception):
    def __init__(self, reason_code: int, msg: str = ""):
        super().__init__(msg or hex(reason_code))
        self.reason_code = reason_code
        # packets successfully parsed from the same feed() call before the
        # error — the caller should process these before disconnecting
        self.packets: List["pkt.Packet"] = []


MALFORMED = ReasonCode.MALFORMED_PACKET
PROTO_ERR = ReasonCode.PROTOCOL_ERROR


# ------------------------------------------------------------------ reader

class _Reader:
    __slots__ = ("buf", "pos", "end")

    def __init__(self, buf: bytes, pos: int = 0, end: Optional[int] = None):
        self.buf = buf
        # each _Reader is constructed, consumed and dropped inside one
        # decode() call — it never escapes the decoding thread
        self.pos = pos  # analysis: owner=local
        self.end = len(buf) if end is None else end

    def remaining(self) -> int:
        return self.end - self.pos

    def u8(self) -> int:
        if self.pos + 1 > self.end:
            raise FrameError(MALFORMED, "truncated u8")
        v = self.buf[self.pos]
        self.pos += 1
        return v

    def u16(self) -> int:
        if self.pos + 2 > self.end:
            raise FrameError(MALFORMED, "truncated u16")
        v = int.from_bytes(self.buf[self.pos : self.pos + 2], "big")
        self.pos += 2
        return v

    def u32(self) -> int:
        if self.pos + 4 > self.end:
            raise FrameError(MALFORMED, "truncated u32")
        v = int.from_bytes(self.buf[self.pos : self.pos + 4], "big")
        self.pos += 4
        return v

    def varint(self) -> int:
        mult, val = 1, 0
        for _ in range(4):
            b = self.u8()
            val += (b & 0x7F) * mult
            if not b & 0x80:
                return val
            mult *= 128
        raise FrameError(MALFORMED, "varint too long")

    def bin(self) -> bytes:
        n = self.u16()
        if self.pos + n > self.end:
            raise FrameError(MALFORMED, "truncated binary")
        v = bytes(self.buf[self.pos : self.pos + n])
        self.pos += n
        return v

    def utf8(self) -> str:
        try:
            return self.bin().decode("utf-8")
        except UnicodeDecodeError:
            raise FrameError(MALFORMED, "invalid utf8")

    def take(self, n: int) -> bytes:
        if self.pos + n > self.end:
            raise FrameError(MALFORMED, "truncated bytes")
        v = bytes(self.buf[self.pos : self.pos + n])
        self.pos += n
        return v

    def rest(self) -> bytes:
        v = bytes(self.buf[self.pos : self.end])
        self.pos = self.end
        return v


# -------------------------------------------------------------- properties

def _parse_properties(r: _Reader) -> pkt.Properties:
    total = r.varint()
    end = r.pos + total
    if end > r.end:
        raise FrameError(MALFORMED, "truncated properties")
    props: pkt.Properties = {}
    sub = _Reader(r.buf, r.pos, end)
    while sub.remaining() > 0:
        pid = sub.varint()
        try:
            prop = Property(pid)
        except ValueError:
            raise FrameError(MALFORMED, f"unknown property {pid:#x}")
        t = PROPERTY_TYPES[prop]
        if t == "byte":
            v = sub.u8()
        elif t == "u16":
            v = sub.u16()
        elif t == "u32":
            v = sub.u32()
        elif t == "varint":
            v = sub.varint()
        elif t == "utf8":
            v = sub.utf8()
        elif t == "bin":
            v = sub.bin()
        else:  # utf8pair
            v = (sub.utf8(), sub.utf8())
        if prop == Property.USER_PROPERTY:
            props.setdefault(prop, []).append(v)
        elif prop == Property.SUBSCRIPTION_IDENTIFIER:
            props.setdefault(prop, []).append(v)
        elif prop in props:
            raise FrameError(PROTO_ERR, f"duplicate property {prop}")
        else:
            props[prop] = v
    r.pos = end
    return props


def _varint_bytes(n: int) -> bytes:
    if n < 0 or n > MAX_REMAINING:
        raise FrameError(MALFORMED, "varint out of range")
    out = bytearray()
    while True:
        b = n % 128
        n //= 128
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _utf8_bytes(s: str) -> bytes:
    b = s.encode("utf-8")
    if len(b) > 0xFFFF:
        raise FrameError(MALFORMED, "string too long")
    return struct.pack(">H", len(b)) + b


def _bin_bytes(b: bytes) -> bytes:
    if len(b) > 0xFFFF:
        raise FrameError(MALFORMED, "binary too long")
    return struct.pack(">H", len(b)) + b


def _serialize_properties(props: pkt.Properties) -> bytes:
    body = bytearray()
    for pid, v in props.items():
        prop = Property(pid)
        t = PROPERTY_TYPES[prop]
        vals = v if prop in (Property.USER_PROPERTY, Property.SUBSCRIPTION_IDENTIFIER) and isinstance(v, list) else [v]
        for val in vals:
            body += _varint_bytes(int(prop))
            if t == "byte":
                body.append(int(val) & 0xFF)
            elif t == "u16":
                body += struct.pack(">H", int(val))
            elif t == "u32":
                body += struct.pack(">I", int(val))
            elif t == "varint":
                body += _varint_bytes(int(val))
            elif t == "utf8":
                body += _utf8_bytes(val)
            elif t == "bin":
                body += _bin_bytes(val)
            else:  # utf8pair
                k, vv = val
                body += _utf8_bytes(k) + _utf8_bytes(vv)
    return _varint_bytes(len(body)) + bytes(body)


# ----------------------------------------------------------------- parser

class Parser:
    """Incremental MQTT parser with continuation state.

    feed(data) -> list of parsed packets; partial packets are buffered.
    The protocol version is latched from the CONNECT packet (like
    `emqx_frame:parse` threading `#{version := Ver}` options).
    """

    def __init__(self, version: int = pkt.MQTT_V4, max_size: int = DEFAULT_MAX_SIZE, strict: bool = True):
        self.version = version
        self.max_size = max_size
        self.strict = strict
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[pkt.Packet]:
        self._buf += data
        out: List[pkt.Packet] = []
        if self._fast_scan(out):
            return out
        while True:
            try:
                parsed = self._try_parse_one()
            except FrameError as e:
                e.packets = out  # don't lose wire-valid packets before the error
                raise
            if parsed is None:
                return out
            out.append(parsed)

    def _fast_scan(self, out: List[pkt.Packet]) -> bool:
        """C++ frame-boundary scan (native/matchhash.cc etpu_scan_frames);
        returns False to fall back to the Python loop."""
        from ..ops import native

        while True:
            if len(self._buf) < 2:
                return True
            scan = native.scan_frames(bytes(self._buf), self.max_size)
            if scan is None:
                return False  # no native lib
            buf = bytes(self._buf[: scan.consumed])
            del self._buf[: scan.consumed]
            try:
                for i in range(scan.count):
                    off = scan.body_offs[i]
                    out.append(self._parse_packet(
                        int(scan.headers[i]),
                        buf[off:off + scan.body_lens[i]],
                    ))
            except FrameError as e:
                e.packets = out
                raise
            if scan.err == 1:
                e = FrameError(MALFORMED, "remaining length varint too long")
                e.packets = out
                # drop the poisoned tail; the connection closes on this error
                self._buf.clear()
                raise e
            if scan.err == 2:
                e = FrameError(ReasonCode.PACKET_TOO_LARGE,
                               f"packet > max {self.max_size}")
                e.packets = out
                self._buf.clear()
                raise e
            if scan.count == 0:
                return True  # incomplete frame left buffered

    def _try_parse_one(self) -> Optional[pkt.Packet]:
        buf = self._buf
        if len(buf) < 2:
            return None
        # remaining-length varint: bytes 1..4 after the header byte
        rl, mult, idx = 0, 1, 1
        while True:
            if idx >= len(buf):
                return None  # need more data for length
            b = buf[idx]
            rl += (b & 0x7F) * mult
            idx += 1
            if not b & 0x80:
                break
            if idx > 4:
                raise FrameError(MALFORMED, "remaining length varint too long")
            mult *= 128
        total = idx + rl
        if total > self.max_size:
            raise FrameError(ReasonCode.PACKET_TOO_LARGE, f"packet {total} > max {self.max_size}")
        if len(buf) < total:
            return None
        header = buf[0]
        body = bytes(buf[idx:total])
        del self._buf[:total]
        return self._parse_packet(header, body)

    # -- per-type body parsing

    def _parse_packet(self, header: int, body: bytes) -> pkt.Packet:
        ptype = header >> 4
        flags = header & 0x0F
        r = _Reader(body)
        try:
            t = PacketType(ptype)
        except ValueError:
            raise FrameError(MALFORMED, f"bad packet type {ptype}")

        if t == PacketType.PUBLISH:
            return self._parse_publish(flags, r)
        if self.strict:
            want = (
                0x2
                if t in (PacketType.PUBREL, PacketType.SUBSCRIBE, PacketType.UNSUBSCRIBE)
                else 0x0
            )
            if flags != want:
                raise FrameError(MALFORMED, f"bad flags {flags:#x} for {t.name}")

        if t == PacketType.CONNECT:
            return self._parse_connect(r)
        if t == PacketType.CONNACK:
            return self._parse_connack(r)
        if t in (PacketType.PUBACK, PacketType.PUBREC, PacketType.PUBREL, PacketType.PUBCOMP):
            return self._parse_puback_like(t, r)
        if t == PacketType.SUBSCRIBE:
            return self._parse_subscribe(r)
        if t == PacketType.SUBACK:
            return self._parse_suback(r)
        if t == PacketType.UNSUBSCRIBE:
            return self._parse_unsubscribe(r)
        if t == PacketType.UNSUBACK:
            return self._parse_unsuback(r)
        if t == PacketType.PINGREQ:
            return pkt.PingReq()
        if t == PacketType.PINGRESP:
            return pkt.PingResp()
        if t == PacketType.DISCONNECT:
            return self._parse_disconnect(r)
        if t == PacketType.AUTH:
            return self._parse_auth(r)
        raise FrameError(MALFORMED, f"unhandled type {t}")

    def _parse_connect(self, r: _Reader) -> pkt.Connect:
        proto_name = r.utf8()
        proto_ver = r.u8()
        if (proto_name, proto_ver) not in (("MQIsdp", 3), ("MQTT", 4), ("MQTT", 5)):
            raise FrameError(
                ReasonCode.UNSUPPORTED_PROTOCOL_VERSION,
                f"unsupported protocol {proto_name!r} v{proto_ver}",
            )
        self.version = proto_ver
        flags = r.u8()
        if self.strict and flags & 0x01:
            raise FrameError(MALFORMED, "reserved connect flag set")
        has_user = bool(flags >> 7 & 1)
        has_pass = bool(flags >> 6 & 1)
        will_retain = bool(flags >> 5 & 1)
        will_qos = flags >> 3 & 0x3
        will_flag = bool(flags >> 2 & 1)
        clean_start = bool(flags >> 1 & 1)
        if self.strict and not will_flag and (will_qos or will_retain):
            raise FrameError(MALFORMED, "will flags without will")
        if self.strict and will_qos > 2:
            raise FrameError(MALFORMED, "bad will qos")
        keepalive = r.u16()
        props: pkt.Properties = {}
        if proto_ver == pkt.MQTT_V5:
            props = _parse_properties(r)
        clientid = r.utf8()
        will_props: pkt.Properties = {}
        will_topic = will_payload = None
        if will_flag:
            if proto_ver == pkt.MQTT_V5:
                will_props = _parse_properties(r)
            will_topic = r.utf8()
            will_payload = r.bin()
        username = r.utf8() if has_user else None
        password = r.bin() if has_pass else None
        if self.strict and r.remaining():
            raise FrameError(MALFORMED, "trailing bytes in CONNECT")
        return pkt.Connect(
            proto_name=proto_name,
            proto_ver=proto_ver,
            clean_start=clean_start,
            keepalive=keepalive,
            clientid=clientid,
            username=username,
            password=password,
            will_flag=will_flag,
            will_qos=will_qos,
            will_retain=will_retain,
            will_topic=will_topic,
            will_payload=will_payload,
            will_props=will_props,
            properties=props,
        )

    def _parse_connack(self, r: _Reader) -> pkt.Connack:
        ack = r.u8()
        if self.strict and ack & 0xFE:
            raise FrameError(MALFORMED, "bad connack flags")
        rc = r.u8()
        props: pkt.Properties = {}
        if self.version == pkt.MQTT_V5:
            props = _parse_properties(r)
        return pkt.Connack(session_present=bool(ack & 1), reason_code=rc, properties=props)

    def _parse_publish(self, flags: int, r: _Reader) -> pkt.Publish:
        dup = bool(flags >> 3 & 1)
        qos = flags >> 1 & 0x3
        retain = bool(flags & 1)
        if qos == 3:
            raise FrameError(MALFORMED, "bad publish qos")
        topic = r.utf8()
        packet_id = r.u16() if qos > 0 else None
        if packet_id == 0:
            raise FrameError(MALFORMED, "zero packet id")
        props: pkt.Properties = {}
        if self.version == pkt.MQTT_V5:
            props = _parse_properties(r)
        return pkt.Publish(
            topic=topic,
            payload=r.rest(),
            qos=qos,
            retain=retain,
            dup=dup,
            packet_id=packet_id,
            properties=props,
        )

    def _parse_puback_like(self, t: PacketType, r: _Reader):
        cls = {
            PacketType.PUBACK: pkt.PubAck,
            PacketType.PUBREC: pkt.PubRec,
            PacketType.PUBREL: pkt.PubRel,
            PacketType.PUBCOMP: pkt.PubComp,
        }[t]
        packet_id = r.u16()
        rc, props = 0, {}
        if self.version == pkt.MQTT_V5 and r.remaining():
            rc = r.u8()
            if r.remaining():
                props = _parse_properties(r)
        return cls(packet_id=packet_id, reason_code=rc, properties=props)

    def _parse_subscribe(self, r: _Reader) -> pkt.Subscribe:
        packet_id = r.u16()
        props: pkt.Properties = {}
        if self.version == pkt.MQTT_V5:
            props = _parse_properties(r)
        filters: List[Tuple[str, pkt.SubOpts]] = []
        while r.remaining():
            tf = r.utf8()
            ob = r.u8()
            if self.strict and self.version == pkt.MQTT_V5 and ob & 0xC0:
                raise FrameError(MALFORMED, "reserved subopts bits")
            opts = pkt.SubOpts.from_byte(ob if self.version == pkt.MQTT_V5 else ob & 0x3)
            if self.strict and opts.qos > 2:
                raise FrameError(MALFORMED, "bad sub qos")
            filters.append((tf, opts))
        if not filters and self.strict:
            raise FrameError(PROTO_ERR, "empty subscribe")
        return pkt.Subscribe(packet_id=packet_id, topic_filters=filters, properties=props)

    def _parse_suback(self, r: _Reader) -> pkt.SubAck:
        packet_id = r.u16()
        props: pkt.Properties = {}
        if self.version == pkt.MQTT_V5:
            props = _parse_properties(r)
        codes = list(r.rest())
        return pkt.SubAck(packet_id=packet_id, reason_codes=codes, properties=props)

    def _parse_unsubscribe(self, r: _Reader) -> pkt.Unsubscribe:
        packet_id = r.u16()
        props: pkt.Properties = {}
        if self.version == pkt.MQTT_V5:
            props = _parse_properties(r)
        filters = []
        while r.remaining():
            filters.append(r.utf8())
        if not filters and self.strict:
            raise FrameError(PROTO_ERR, "empty unsubscribe")
        return pkt.Unsubscribe(packet_id=packet_id, topic_filters=filters, properties=props)

    def _parse_unsuback(self, r: _Reader) -> pkt.UnsubAck:
        packet_id = r.u16()
        props: pkt.Properties = {}
        codes: List[int] = []
        if self.version == pkt.MQTT_V5:
            props = _parse_properties(r)
            codes = list(r.rest())
        return pkt.UnsubAck(packet_id=packet_id, reason_codes=codes, properties=props)

    def _parse_disconnect(self, r: _Reader) -> pkt.Disconnect:
        if self.version != pkt.MQTT_V5 or r.remaining() == 0:
            return pkt.Disconnect()
        rc = r.u8()
        props = _parse_properties(r) if r.remaining() else {}
        return pkt.Disconnect(reason_code=rc, properties=props)

    def _parse_auth(self, r: _Reader) -> pkt.Auth:
        if self.version != pkt.MQTT_V5:
            raise FrameError(PROTO_ERR, "AUTH requires v5")
        if r.remaining() == 0:
            return pkt.Auth()
        rc = r.u8()
        props = _parse_properties(r) if r.remaining() else {}
        return pkt.Auth(reason_code=rc, properties=props)


# -------------------------------------------------------------- serializer

# shared-prefix cache telemetry, synced into broker metrics
# (`deliver.prefix.hit|miss`) by Broker.sync_engine_metrics at
# observation points — the codec owns the counters, the hot path never
# touches the metrics table
PREFIX_STATS = {"hit": 0, "miss": 0}


class PublishPrefix:
    """One shared wire form of a fanned-out PUBLISH.

    The frame is serialized ONCE with a 2-byte placeholder in the
    packet-id slot; every receiver splices only its own packet id into
    a copy of the cached bytes (QoS0 has no packet id, so `splice`
    returns the cached bytes untouched — zero copies).  Byte-parity
    contract: ``splice(pid)`` is byte-identical to
    ``serialize(replace(p, packet_id=pid), version)``."""

    __slots__ = ("data", "pid_off")

    def __init__(self, data: bytes, pid_off: Optional[int]):
        self.data = data
        self.pid_off = pid_off

    def splice(self, packet_id: Optional[int]) -> bytes:
        if self.pid_off is None:
            return self.data
        if not packet_id:
            raise FrameError(PROTO_ERR, "qos>0 publish needs packet_id")
        buf = bytearray(self.data)
        struct.pack_into(">H", buf, self.pid_off, packet_id)
        return bytes(buf)

    def __len__(self) -> int:
        # exact wire size for ANY packet id (the slot is fixed-width)
        return len(self.data)


def publish_prefix(p: "pkt.Publish", version: int) -> PublishPrefix:
    """Serialize a PUBLISH with a placeholder packet-id slot; mirrors
    the PUBLISH branch of serialize() field-for-field so the parity
    contract holds structurally."""
    v5 = version == pkt.MQTT_V5
    flags = (int(p.dup) << 3) | ((p.qos & 0x3) << 1) | int(p.retain)
    body = bytearray()
    body += _utf8_bytes(p.topic)
    pid_in_body = None
    if p.qos > 0:
        pid_in_body = len(body)
        body += b"\x00\x00"
    if v5:
        body += _serialize_properties(p.properties)
    body += p.payload
    rl = _varint_bytes(len(body))
    data = (
        bytes([(int(PacketType.PUBLISH) << 4) | flags]) + rl + bytes(body)
    )
    pid_off = None if pid_in_body is None else 1 + len(rl) + pid_in_body
    return PublishPrefix(data, pid_off)


def _prefix_entry(p: "pkt.Publish", version: int,
                  cache: dict) -> PublishPrefix:
    """The channel attaches one `_wire_prefix` dict per message, shared
    by every receiver whose (topic, properties, dup) equal the
    message's — so within a cache the wire form varies only by
    (version, qos, retain), the key here."""
    key = (version, p.qos, p.retain)
    ent = cache.get(key)
    if ent is None:
        ent = cache[key] = publish_prefix(p, version)
        PREFIX_STATS["miss"] += 1
    else:
        PREFIX_STATS["hit"] += 1
    return ent


def serialize_cached(p: pkt.Packet, version: int) -> bytes:
    """Serialize honoring the fan-out fast path: PUBLISH packets on the
    build-once/scatter-many path carry a `_wire_prefix` dict shared by
    every receiver of one message — one serialization per distinct wire
    form (proto version x QoS x retain) plus a per-receiver packet-id
    splice, instead of one full serialization per receiver."""
    cache = getattr(p, "_wire_prefix", None)
    if cache is None:
        return serialize(p, version)
    return _prefix_entry(p, version, cache).splice(p.packet_id)


def exact_publish_size(p: "pkt.Publish", version: int) -> int:
    """Exact serialized size of an outbound PUBLISH, memoized on the
    shared prefix entry when the scatter path is active — identical
    payloads measure once per wire form, not once per receiver (the
    Channel max-packet-size slow path)."""
    cache = getattr(p, "_wire_prefix", None)
    if cache is None:
        return len(serialize(p, version))
    return len(_prefix_entry(p, version, cache))


def serialize(p: pkt.Packet, version: int = pkt.MQTT_V4) -> bytes:
    t = p.type
    v5 = version == pkt.MQTT_V5
    flags = 0
    body = bytearray()

    if t == PacketType.CONNECT:
        version = p.proto_ver
        v5 = version == pkt.MQTT_V5
        body += _utf8_bytes(p.proto_name)
        body.append(p.proto_ver)
        cf = (
            (int(p.username is not None) << 7)
            | (int(p.password is not None) << 6)
            | (int(p.will_retain) << 5)
            | ((p.will_qos & 0x3) << 3)
            | (int(p.will_flag) << 2)
            | (int(p.clean_start) << 1)
        )
        body.append(cf)
        body += struct.pack(">H", p.keepalive)
        if v5:
            body += _serialize_properties(p.properties)
        body += _utf8_bytes(p.clientid)
        if p.will_flag:
            if v5:
                body += _serialize_properties(p.will_props)
            body += _utf8_bytes(p.will_topic or "")
            body += _bin_bytes(p.will_payload or b"")
        if p.username is not None:
            body += _utf8_bytes(p.username)
        if p.password is not None:
            body += _bin_bytes(p.password)

    elif t == PacketType.CONNACK:
        body.append(int(p.session_present))
        body.append(
            p.reason_code if v5 else pkt.compat_connack_v3(p.reason_code)
        )
        if v5:
            body += _serialize_properties(p.properties)

    elif t == PacketType.PUBLISH:
        flags = (int(p.dup) << 3) | ((p.qos & 0x3) << 1) | int(p.retain)
        body += _utf8_bytes(p.topic)
        if p.qos > 0:
            if not p.packet_id:
                raise FrameError(PROTO_ERR, "qos>0 publish needs packet_id")
            body += struct.pack(">H", p.packet_id)
        if v5:
            body += _serialize_properties(p.properties)
        body += p.payload

    elif t in (PacketType.PUBACK, PacketType.PUBREC, PacketType.PUBREL, PacketType.PUBCOMP):
        if t == PacketType.PUBREL:
            flags = 0x2
        body += struct.pack(">H", p.packet_id)
        if v5 and (p.reason_code or p.properties):
            body.append(p.reason_code)
            if p.properties:
                body += _serialize_properties(p.properties)

    elif t == PacketType.SUBSCRIBE:
        flags = 0x2
        body += struct.pack(">H", p.packet_id)
        if v5:
            body += _serialize_properties(p.properties)
        for tf, opts in p.topic_filters:
            body += _utf8_bytes(tf)
            body.append(opts.to_byte() if v5 else opts.qos & 0x3)

    elif t == PacketType.SUBACK:
        body += struct.pack(">H", p.packet_id)
        if v5:
            body += _serialize_properties(p.properties)
        body += bytes(p.reason_codes)

    elif t == PacketType.UNSUBSCRIBE:
        flags = 0x2
        body += struct.pack(">H", p.packet_id)
        if v5:
            body += _serialize_properties(p.properties)
        for tf in p.topic_filters:
            body += _utf8_bytes(tf)

    elif t == PacketType.UNSUBACK:
        body += struct.pack(">H", p.packet_id)
        if v5:
            body += _serialize_properties(p.properties)
            body += bytes(p.reason_codes)

    elif t in (PacketType.PINGREQ, PacketType.PINGRESP):
        pass

    elif t == PacketType.DISCONNECT:
        if v5 and (p.reason_code or p.properties):
            body.append(p.reason_code)
            if p.properties:
                body += _serialize_properties(p.properties)

    elif t == PacketType.AUTH:
        if p.reason_code or p.properties:
            body.append(p.reason_code)
            if p.properties:
                body += _serialize_properties(p.properties)
    else:
        raise FrameError(MALFORMED, f"cannot serialize {t}")

    header = (int(t) << 4) | flags
    return bytes([header]) + _varint_bytes(len(body)) + bytes(body)
