"""Retained-message store with wildcard lookup on subscribe.

Analog of `apps/emqx_retainer` (`emqx_retainer.erl:85-150`,
`emqx_retainer_mnesia.erl`): PUBLISH with retain=true stores the message
(empty payload deletes); on SUBSCRIBE the filter is matched against stored
topic names and matching messages are re-delivered, honoring the v5
retain-handling subscription option.

The lookup direction is the reverse of the publish hot path (wildcard
filter vs concrete stored names).  Two paths serve it:

* the host topic-name **trie** — canonical truth and the verify oracle,
  output-proportional enumeration;
* the optional **device index** (`models/retained.py`) — stored names
  bucketed by masked hash, probed by batched compact dispatches.

Arbitration mirrors the publish engine (`models/engine.py`): each path's
throughput is EWMA-measured in lookups/s — the trie by a timing wrapper
around its walk, the index per dispatched batch — and the faster one
serves.  While the trie serves, the index is re-probed every
``probe_interval`` seconds with a real lookup batch (non-blocking:
completion is polled on later lookups), which both re-measures the link
AND keeps the device mirror warm, so recovery after a degraded-link
episode is automatic.  While the index serves, the trie rate is
refreshed periodically the same way.  Path changes emit
``retained.flip``.

Lookups are BATCHED: ``iter_filter`` enqueues its filter and the first
generator actually consumed flushes every queued lookup as ONE index
dispatch — so a multi-filter SUBSCRIBE packet (channel.py collects its
iterators before consuming), a session resume, or a durable-log
gap-recovery sweep (`iter_matching`) amortize the dispatch the way
publish ticks amortize matching.  Filters the index bounces (coarse
shapes, huge fan-ins, over-cap shape registry) fall to the trie
per-filter.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ..observe import tracepoints as _tps
from ..observe.tracepoints import tp
from . import topic as topiclib
from .message import Message

_UNSET = object()


class _Node:
    __slots__ = ("children", "msg")

    def __init__(self):
        self.children: Dict[str, _Node] = {}
        self.msg: Optional[Message] = None


class _LookupReq:
    __slots__ = ("filt", "names")

    def __init__(self, filt: str):
        self.filt = filt
        self.names = _UNSET  # list[str] | None (trie serves) | _UNSET


class Retainer:
    def __init__(self, max_retained: int = 0, max_payload: int = 0,
                 enable: bool = True, store=None, device_index=None,
                 probe_interval: float = 10.0):
        self.root = _Node()
        self.count = 0
        self.max_retained = max_retained  # 0 = unlimited
        self.max_payload = max_payload
        self.enable = enable
        # optional write-through disc store (emqx_retainer_mnesia disc
        # copies); retained messages then survive a restart
        self.store = store
        # optional HBM name index (models/retained.py): subscribe-time
        # wildcard fan-in as batched device dispatches instead of a trie
        # walk — the trie stays canonical truth (and the verify oracle)
        self.index = device_index
        # host/device arbitration, same policy as the publish engine
        # (models/engine.py): EWMA lookups/s per path, serve the faster,
        # probe the loser every probe_interval (probes keep the device
        # mirror warm)
        self.probe_interval = probe_interval
        self.rate_trie: Optional[float] = None
        self.rate_index: Optional[float] = None
        self._last_trie_meas = 0.0
        self._last_index_meas = 0.0
        self._probe = None  # (pending, t0, n_filters)
        self.probe_cap = 64
        self.probe_count = 0
        self.index_serves = 0
        self.trie_serves = 0
        self.path_flips = 0
        self._last_path: Optional[str] = None
        self._pending: List[_LookupReq] = []
        if store is not None:
            msgs = store.load().values()
            for msg in msgs:
                self._insert(msg, persist=False)

    # ------------------------------------------------------------- store

    def on_publish(self, msg: Message) -> None:
        if not self.enable or not msg.retain:
            return
        if not msg.payload:
            self.delete(msg.topic)
            return
        if self.max_payload and len(msg.payload) > self.max_payload:
            return
        if self.max_retained and self.count >= self.max_retained and self.get(msg.topic) is None:
            return
        self._insert(msg)

    def _insert(self, msg: Message, persist: bool = True) -> None:
        node = self.root
        for w in topiclib.words(msg.topic):
            node = node.children.setdefault(w, _Node())
        if node.msg is None:
            self.count += 1
        node.msg = msg
        if self.index is not None:
            self.index.insert(msg.topic)
        if persist and self.store is not None:
            self.store.set(msg)
            if self.store.needs_compact(self.count):
                self.store.compact(self.walk_all())

    def get(self, topic: str) -> Optional[Message]:
        node = self.root
        for w in topiclib.words(topic):
            node = node.children.get(w)
            if node is None:
                return None
        return node.msg

    def delete(self, topic: str) -> bool:
        ws = topiclib.words(topic)
        path = [self.root]
        node = self.root
        for w in ws:
            node = node.children.get(w)
            if node is None:
                return False
            path.append(node)
        if node.msg is None:
            return False
        node.msg = None
        self.count -= 1
        if self.index is not None:
            self.index.delete(topic)
        if self.store is not None:
            self.store.delete(topic)
            if self.store.needs_compact(self.count):
                self.store.compact(self.walk_all())
        for i in range(len(ws) - 1, -1, -1):
            child = path[i + 1]
            if child.msg is not None or child.children:
                break
            del path[i].children[ws[i]]
        return True

    # ------------------------------------------------------------ lookup

    def walk_all(self):
        """Every retained message, including $-topics (store compaction)."""
        stack = [self.root]
        while stack:
            n = stack.pop()
            if n.msg is not None:
                yield n.msg
            stack.extend(n.children.values())

    def iter_filter(self, filt: str):
        """Lazily yield retained messages matching the filter.

        A generator so large retained sets can be re-delivered in paced
        batches without one synchronous full-trie collection blocking
        the event loop at subscribe time (`emqx_retainer`'s batched
        mnesia reads).  With the device index attached the lookup is
        QUEUED at generator creation and flushed as one batched index
        dispatch when the first queued generator is consumed — create
        every subscription's iterator before consuming any (channel.py's
        SUBSCRIBE handler, `iter_matching`) and the whole set rides one
        dispatch.
        """
        if self.index is None:
            return self._trie_iter(filt)
        req = _LookupReq(filt)
        self._pending.append(req)
        return self._req_iter(req)

    def _req_iter(self, req: _LookupReq):
        if req.names is _UNSET:
            self._flush_pending()
        if req.names is None:
            yield from self._timed_trie(req.filt)
            return
        for t in req.names:
            msg = self.get(t)
            if msg is not None and not msg.expired():
                yield msg

    # ------------------------------------------------- hybrid arbitration

    def _flush_pending(self) -> None:
        """Serve every queued lookup in one arbitration decision: the
        measured-faster path takes the batch; index-bounced filters
        (None results) fall to the trie individually."""
        reqs, self._pending = self._pending, []
        if not reqs:
            return
        self._poll_probe()
        n = len(reqs)
        if self._pick_index():
            t0 = time.monotonic()
            res = self.index.lookup_batch([r.filt for r in reqs])
            dt = max(time.monotonic() - t0, 1e-9)
            self._note_index_rate(n / dt)
            served = 0
            for r, names in zip(reqs, res):
                r.names = names
                served += names is not None
            self.index_serves += served
            self.trie_serves += n - served
            self._note_path("index")
        else:
            for r in reqs:
                r.names = None
            self.trie_serves += n
            self._note_path("trie")
            self._maybe_probe_index([r.filt for r in reqs])

    def _pick_index(self) -> bool:
        if self.index is None or len(self.index) == 0:
            return False
        if self.rate_index is None or self.rate_trie is None:
            # measure the trie first; the probe measures the index
            return False
        if self.rate_index <= self.rate_trie:
            return False
        # index winning: refresh the trie estimate occasionally
        if time.monotonic() - self._last_trie_meas > self.probe_interval:
            return False
        return True

    def _note_path(self, path: str) -> None:
        if self._last_path is not None and self._last_path != path:
            self.path_flips += 1
            tp("retained.flip", path=path,
               rate_trie=self.rate_trie, rate_index=self.rate_index)
        self._last_path = path

    def _note_trie_rate(self, rps: float) -> None:
        self.rate_trie = (
            rps if self.rate_trie is None
            else 0.5 * self.rate_trie + 0.5 * rps
        )
        self._last_trie_meas = time.monotonic()

    def _note_index_rate(self, rps: float) -> None:
        self.rate_index = (
            rps if self.rate_index is None
            else 0.5 * self.rate_index + 0.5 * rps
        )
        self._last_index_meas = time.monotonic()

    def _timed_trie(self, filt: str):
        """Trie walk with its in-iterator time accumulated, so the lazy
        paced consumption pattern still yields an honest rate sample on
        exhaustion (pauses between batches are not charged)."""
        it = self._trie_iter(filt)
        total = 0.0
        while True:
            t0 = time.perf_counter()
            try:
                msg = next(it)
            except StopIteration:
                total += time.perf_counter() - t0
                self._note_trie_rate(1.0 / max(total, 1e-9))
                return
            total += time.perf_counter() - t0
            yield msg

    def _maybe_probe_index(self, filters: List[str]) -> None:
        """Keep the device index warm + its rate fresh while the trie
        serves: dispatch this batch to the index (syncing any pending
        churn); completion is polled on later lookups — the serving
        path never waits on it."""
        if self.index is None or self._probe is not None:
            return
        if len(self.index) == 0:
            return
        now = time.monotonic()
        if (
            self.rate_index is not None
            and now - self._last_index_meas <= self.probe_interval
        ):
            return
        probe = filters[: self.probe_cap]
        try:
            pend = self.index.lookup_submit(probe)
        except Exception:  # pragma: no cover - probe must not break serving
            import logging

            logging.getLogger("emqx_tpu.retainer").exception(
                "retained index probe"
            )
            return
        self._probe = (pend, now, len(probe))
        self.probe_count += 1
        if _tps._active:
            tp("retained.probe", phase="dispatch", n=len(probe))

    def _poll_probe(self) -> None:
        """Harvest a completed index probe (non-blocking)."""
        p = self._probe
        if p is None:
            return
        pend, t0, n = p
        if not pend.is_ready():
            return
        try:
            self.index.lookup_collect(pend)
        except Exception:  # pragma: no cover
            self._probe = None
            return
        # completion time is an upper bound (ready since some earlier
        # lookup); lookups are frequent while serving, so the bias is
        # small — the same estimate the publish engine's probes accept
        dt = max(time.monotonic() - t0, 1e-9)
        self._note_index_rate(n / dt)
        tp("retained.probe", phase="complete", n=n, dt_ms=dt * 1e3,
           rate_index=self.rate_index)
        self._probe = None

    # ------------------------------------------------------ trie serving

    def _trie_iter(self, filt: str):
        """The host trie walk (canonical truth).  Each node's children
        are snapshotted when visited, so concurrent retain/delete
        between batches is safe (same read-committed looseness as the
        reference's continuations)."""
        fw = topiclib.words(filt)
        stack = [(self.root, 0, True)]
        while stack:
            node, i, root = stack.pop()
            if i == len(fw):
                if node.msg is not None and not node.msg.expired():
                    yield node.msg
                continue
            w = fw[i]
            if w == "#":
                # matches zero+ levels (but not $-roots from a root #)
                sub = [(node, True)]
                while sub:
                    n, at_root = sub.pop()
                    if n.msg is not None and not n.msg.expired():
                        yield n.msg
                    for name, c in list(n.children.items()):
                        if at_root and root and name.startswith("$"):
                            continue
                        sub.append((c, False))
            elif w == "+":
                for name, c in list(node.children.items()):
                    if root and name.startswith("$"):
                        continue
                    stack.append((c, i + 1, False))
            else:
                c = node.children.get(w)
                if c is not None:
                    stack.append((c, i + 1, False))

    def match_filter(self, filt: str) -> List[Message]:
        """All retained messages whose topic matches the filter."""
        return list(self.iter_filter(filt))

    def iter_matching(self, filters):
        """Lazily yield retained messages matching ANY of the filters,
        deduplicated by topic — the durable-log gap-recovery source
        (ds/manager.py): a session whose log window was GC'd away still
        converges to the last value of every retained topic it holds a
        filter for.  All iterators are created up front, so with the
        device index the whole filter set rides one batched dispatch."""
        its = [self.iter_filter(f) for f in filters]
        seen = set()
        for it in its:
            for msg in it:
                if msg.topic in seen:
                    continue
                seen.add(msg.topic)
                yield msg

    def clean_expired(self) -> int:
        """GC expired retained messages; returns count removed."""
        removed = 0

        def collect(node: _Node, prefix: List[str]) -> List[str]:
            topics = []
            if node.msg is not None and node.msg.expired():
                topics.append("/".join(prefix))
            for name, c in list(node.children.items()):
                topics.extend(collect(c, prefix + [name]))
            return topics

        for t in collect(self.root, []):
            if self.delete(t):
                removed += 1
        return removed
