"""Retained-message store with wildcard lookup on subscribe.

Analog of `apps/emqx_retainer` (`emqx_retainer.erl:85-150`,
`emqx_retainer_mnesia.erl`): PUBLISH with retain=true stores the message
(empty payload deletes); on SUBSCRIBE the filter is matched against stored
topic names and matching messages are re-delivered, honoring the v5
retain-handling subscription option.

The lookup direction is the reverse of the publish hot path (wildcard filter
vs concrete stored names), so it uses a host-side topic-name trie rather
than the device tables; retained populations are small relative to
subscription populations and mutate rarely.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from . import topic as topiclib
from .message import Message


class _Node:
    __slots__ = ("children", "msg")

    def __init__(self):
        self.children: Dict[str, _Node] = {}
        self.msg: Optional[Message] = None


class Retainer:
    def __init__(self, max_retained: int = 0, max_payload: int = 0,
                 enable: bool = True, store=None, device_index=None):
        self.root = _Node()
        self.count = 0
        self.max_retained = max_retained  # 0 = unlimited
        self.max_payload = max_payload
        self.enable = enable
        # optional write-through disc store (emqx_retainer_mnesia disc
        # copies); retained messages then survive a restart
        self.store = store
        # optional HBM name index (models/retained.py): subscribe-time
        # wildcard fan-in as ONE device dispatch instead of a trie walk
        # — the trie stays canonical truth (and the verify oracle)
        self.index = device_index
        # host/device arbitration, same policy as the publish engine
        # (models/engine.py): the index serves while its MEASURED
        # dispatch latency stays under budget; past it (a degraded
        # host<->device link) the trie serves and the index is re-probed
        # every probe_interval so recovery is automatic
        self.index_lat_budget = 0.05  # seconds per lookup
        self.probe_interval = 10.0
        self._index_lat: float = 0.0  # EWMA
        self._last_index_use = 0.0
        self.index_serves = 0
        self.trie_serves = 0
        if store is not None:
            for msg in store.load().values():
                self._insert(msg, persist=False)

    # ------------------------------------------------------------- store

    def on_publish(self, msg: Message) -> None:
        if not self.enable or not msg.retain:
            return
        if not msg.payload:
            self.delete(msg.topic)
            return
        if self.max_payload and len(msg.payload) > self.max_payload:
            return
        if self.max_retained and self.count >= self.max_retained and self.get(msg.topic) is None:
            return
        self._insert(msg)

    def _insert(self, msg: Message, persist: bool = True) -> None:
        node = self.root
        for w in topiclib.words(msg.topic):
            node = node.children.setdefault(w, _Node())
        if node.msg is None:
            self.count += 1
        node.msg = msg
        if self.index is not None:
            self.index.insert(msg.topic)
        if persist and self.store is not None:
            self.store.set(msg)
            if self.store.needs_compact(self.count):
                self.store.compact(self.walk_all())

    def get(self, topic: str) -> Optional[Message]:
        node = self.root
        for w in topiclib.words(topic):
            node = node.children.get(w)
            if node is None:
                return None
        return node.msg

    def delete(self, topic: str) -> bool:
        ws = topiclib.words(topic)
        path = [self.root]
        node = self.root
        for w in ws:
            node = node.children.get(w)
            if node is None:
                return False
            path.append(node)
        if node.msg is None:
            return False
        node.msg = None
        self.count -= 1
        if self.index is not None:
            self.index.delete(topic)
        if self.store is not None:
            self.store.delete(topic)
            if self.store.needs_compact(self.count):
                self.store.compact(self.walk_all())
        for i in range(len(ws) - 1, -1, -1):
            child = path[i + 1]
            if child.msg is not None or child.children:
                break
            del path[i].children[ws[i]]
        return True

    # ------------------------------------------------------------ lookup

    def walk_all(self):
        """Every retained message, including $-topics (store compaction)."""
        stack = [self.root]
        while stack:
            n = stack.pop()
            if n.msg is not None:
                yield n.msg
            stack.extend(n.children.values())

    def iter_filter(self, filt: str):
        """Lazily yield retained messages matching the filter.

        A generator so large retained sets can be re-delivered in paced
        batches without one synchronous full-trie collection blocking
        the event loop at subscribe time (`emqx_retainer`'s batched
        mnesia reads).  Each node's children are snapshotted when
        visited, so concurrent retain/delete between batches is safe
        (same read-committed looseness as the reference's continuations).

        With the device index attached, the name set comes from ONE
        kernel dispatch (models/retained.py) and only the hit topics
        touch the trie (message fetch + expiry check) — unless the
        index's measured latency is over budget (degraded link), in
        which case the trie serves until a periodic re-probe succeeds.
        """
        if self.index is not None and len(self.index) and self._index_ok():
            import time as _time

            t0 = _time.monotonic()
            names = self.index.lookup(filt)
            dt = _time.monotonic() - t0
            if dt <= self.index_lat_budget:
                # snap down on a good lookup: one outlier (first-lookup
                # JIT compile, a GC pause) must not bench a healthy
                # index for several probe windows
                self._index_lat = dt
            else:
                self._index_lat = 0.5 * self._index_lat + 0.5 * dt
            self._last_index_use = _time.monotonic()
            self.index_serves += 1
            for t in names:
                msg = self.get(t)
                if msg is not None and not msg.expired():
                    yield msg
            return
        self.trie_serves += 1
        fw = topiclib.words(filt)
        stack = [(self.root, 0, True)]
        while stack:
            node, i, root = stack.pop()
            if i == len(fw):
                if node.msg is not None and not node.msg.expired():
                    yield node.msg
                continue
            w = fw[i]
            if w == "#":
                # matches zero+ levels (but not $-roots from a root #)
                sub = [(node, True)]
                while sub:
                    n, at_root = sub.pop()
                    if n.msg is not None and not n.msg.expired():
                        yield n.msg
                    for name, c in list(n.children.items()):
                        if at_root and root and name.startswith("$"):
                            continue
                        sub.append((c, False))
            elif w == "+":
                for name, c in list(node.children.items()):
                    if root and name.startswith("$"):
                        continue
                    stack.append((c, i + 1, False))
            else:
                c = node.children.get(w)
                if c is not None:
                    stack.append((c, i + 1, False))

    def _index_ok(self) -> bool:
        import time as _time

        if self._index_lat <= self.index_lat_budget:
            return True
        # over budget: re-probe occasionally so a recovered link flips back
        return _time.monotonic() - self._last_index_use > self.probe_interval

    def match_filter(self, filt: str) -> List[Message]:
        """All retained messages whose topic matches the filter."""
        return list(self.iter_filter(filt))

    def iter_matching(self, filters):
        """Lazily yield retained messages matching ANY of the filters,
        deduplicated by topic — the durable-log gap-recovery source
        (ds/manager.py): a session whose log window was GC'd away still
        converges to the last value of every retained topic it holds a
        filter for."""
        seen = set()
        for filt in filters:
            for msg in self.iter_filter(filt):
                if msg.topic in seen:
                    continue
                seen.add(msg.topic)
                yield msg

    def clean_expired(self) -> int:
        """GC expired retained messages; returns count removed."""
        removed = 0

        def collect(node: _Node, prefix: List[str]) -> List[str]:
            topics = []
            if node.msg is not None and node.msg.expired():
                topics.append("/".join(prefix))
            for name, c in list(node.children.items()):
                topics.extend(collect(c, prefix + [name]))
            return topics

        for t in collect(self.root, []):
            if self.delete(t):
                removed += 1
        return removed
