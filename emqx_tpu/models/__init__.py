"""Match-engine frontends: canonical host store + device mirror."""
