"""Brute-force host reference matcher — the correctness oracle.

Used by tests to validate the TPU engine, and by benchmarks as the "CPU
baseline" in the spirit of the reference's in-tree microbench
(`apps/emqx/src/emqx_broker_bench.erl:25-107`, InsertRps/LookupRps).

Also contains a faithful CPU *trie* implementation (dict-based, matching the
semantics of `apps/emqx/src/emqx_trie.erl`) so the baseline isn't a strawman
linear scan.
"""

from __future__ import annotations

from typing import Dict, List, Set

from ..broker import topic as topiclib


class BruteForceIndex:
    """O(n_filters) per lookup. Only for tests on small populations."""

    def __init__(self) -> None:
        self.filters: Dict[str, int] = {}

    def insert(self, filt: str, fid: int) -> None:
        self.filters[filt] = fid

    def delete(self, filt: str) -> None:
        self.filters.pop(filt, None)

    def match(self, name: str) -> Set[int]:
        nw = topiclib.words(name)
        return {
            fid
            for f, fid in self.filters.items()
            if topiclib.match_words(nw, topiclib.words(f))
        }


class _TrieNode:
    __slots__ = ("children", "fids")

    def __init__(self) -> None:
        self.children: Dict[str, _TrieNode] = {}
        self.fids: Set[int] = set()


class CpuTrieIndex:
    """Dict-based topic trie with the reference's match semantics.

    Mirrors the walk of `emqx_trie.erl:272-334`: at each level follow the
    exact child, the '+' child, and collect any '#' child; '#' also matches
    zero trailing levels; root-level wildcards skip $-topics.
    """

    def __init__(self) -> None:
        self.root = _TrieNode()
        # mutations ride the engine's single-mutator churn path (loop
        # at runtime; boot restore on the pre-serving warmup worker) —
        # the trie itself would need the same contract anyway
        self.count = 0  # analysis: owner=loop

    def insert(self, filt: str, fid: int) -> None:
        node = self.root
        for w in topiclib.words(filt):
            node = node.children.setdefault(w, _TrieNode())
        node.fids.add(fid)
        self.count += 1

    def delete(self, filt: str, fid: int) -> None:
        path: List[_TrieNode] = [self.root]
        ws = topiclib.words(filt)
        node = self.root
        for w in ws:
            node = node.children.get(w)
            if node is None:
                return
            path.append(node)
        node.fids.discard(fid)
        self.count -= 1
        # prune empty branches
        for i in range(len(ws) - 1, -1, -1):
            child = path[i + 1]
            if child.fids or child.children:
                break
            del path[i].children[ws[i]]

    def match(self, name: str) -> Set[int]:
        ws = topiclib.words(name)
        out: Set[int] = set()
        dollar = bool(ws) and ws[0].startswith("$")

        def walk(node: _TrieNode, i: int, root: bool) -> None:
            h = node.children.get("#")
            if h is not None and not (root and dollar):
                out.update(h.fids)
            if i == len(ws):
                out.update(node.fids)
                return
            c = node.children.get(ws[i])
            if c is not None:
                walk(c, i + 1, False)
            p = node.children.get("+")
            if p is not None and not (root and dollar):
                walk(p, i + 1, False)

        walk(self.root, 0, True)
        return out
