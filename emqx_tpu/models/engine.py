"""TopicMatchEngine — the flagship: a TPU-resident topic-match automaton.

This is the TPU-native replacement for the reference's route/trie core
(`emqx_router:match_routes/1`, `emqx_trie:match/1` — SURVEY.md §1.7/§3.3).
Canonical truth lives on the host (`MatchTables` + python dicts, the analog of
mnesia/ETS); the device arrays are a cache rebuilt or patched from host truth
(SURVEY.md §5.4 failure model), versioned by an epoch counter.

API:
    fid = engine.add_filter("sensors/+/temp")      # refcounted
    engine.remove_filter("sensors/+/temp")
    sets = engine.match(["sensors/3/temp", ...])   # -> List[Set[fid]]

Filters deeper than the device level cap fall back to a host-side trie —
the same escape hatch as the reference's depth-bounding compaction
(`emqx_trie.erl:202-233`).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..broker import topic as topiclib
from ..ops import hashing
from ..ops.match import (
    DeviceTables,
    TopicBatch,
    match_batch_jit,
    next_pow2 as _next_pow2,
)
from ..ops.tables import MatchTables
from .reference import CpuTrieIndex


def verify_pairs_into(topics, ii, fids, words_map, fbytes_map, out, collide):
    """Exact verification of device hash hits as (topic_idx, fid) pairs.

    Uses the native batch matcher (`native/matchhash.cc
    etpu_verify_pairs`) when available, Python `match_words` otherwise.
    Verified fids land in `out[topic_idx]`; refuted pairs go to
    `collide(topic, fid)`.  Shared by the single-chip and sharded engine
    frontends.  The pair-assembly fast path is a single map over the
    fbytes dict — per-pair Python tuples would dominate at 100k+ hits."""
    from ..ops import native

    fid_list = fids.tolist()
    ii_arr = np.asarray(ii, dtype=np.int32)
    try:
        fblobs = list(map(fbytes_map.__getitem__, fid_list))
    except KeyError:
        # a fid raced a removal between sync and collect: rare slow path
        keep = []
        fblobs = []
        for k, f in enumerate(fid_list):
            fb = fbytes_map.get(f)
            if fb is None:
                collide(topics[int(ii_arr[k])], f)
            else:
                keep.append(k)
                fblobs.append(fb)
        if not keep:
            return
        ii_arr = ii_arr[keep]
        fid_list = [fid_list[k] for k in keep]
    if native.available():
        tblobs = [t.encode("utf-8") for t in topics]
        ok = native.verify_pairs(tblobs, ii_arr, fblobs)
    else:
        ok = None
    if ok is not None:
        if ok.all():  # collisions are astronomically rare: fast path
            for i, f in zip(ii_arr.tolist(), fid_list):
                out[i].add(f)
        else:
            for i, f, good in zip(ii_arr.tolist(), fid_list, ok.tolist()):
                if good:
                    out[i].add(f)
                else:
                    collide(topics[i], f)
    else:
        twcache: Dict[int, List[str]] = {}
        for i, f in zip(ii_arr.tolist(), fid_list):
            tw = twcache.get(i)
            if tw is None:
                tw = twcache[i] = topiclib.words(topics[i])
            if topiclib.match_words(tw, words_map[f]):
                out[i].add(f)
            else:
                collide(topics[i], f)


class TopicMatchEngine:
    def __init__(
        self,
        space: Optional[hashing.HashSpace] = None,
        device=None,
        min_batch: int = 64,
        kcap: int = 32,
    ):
        self.space = space or hashing.HashSpace()
        self.tables = MatchTables(self.space)
        self.device = device
        # even batch floor: the sparse return packs u16 counts in pairs
        self.min_batch = max(2, min_batch + (min_batch & 1))
        self.kcap = kcap  # retained for API compat; sparse path sizes by hits

        self._fids: Dict[str, int] = {}  # filter str -> fid
        self._refs: Dict[int, int] = {}  # fid -> refcount
        self._words: Dict[int, List[str]] = {}
        self._fbytes: Dict[int, bytes] = {}  # utf-8 filter strings (native verify)
        self._next_fid = 0
        self._free_fids: List[int] = []

        # host fallback for filters deeper than the device level cap
        self._deep = CpuTrieIndex()
        self._deep_fids: Set[int] = set()

        # exact-match guarantee: verify device hash hits against stored
        # filter words (default on; see match())
        self.verify_matches = True
        self.collision_count = 0
        self.on_collision = None  # fn(topic, fid) — metrics hook

        self.epoch = 0  # bumps on every device-visible mutation
        self._dev: Optional[DeviceTables] = None
        self._dev_stale = True
        self._hcap_mult = 1  # sparse-return size factor (doubles on overflow)
        # The match hot path is pure XLA by design.  A Pallas kernel for
        # the hash contraction was built and measured on a real TPU
        # (round-1 commit c2423d1): ~46 ms vs XLA's ~0.03-0.2 ms per
        # 4096-topic batch — XLA's fusion of the masked-sum contraction
        # is already near roofline.  A *fused* hash+probe kernel cannot
        # win either at the 10M-filter target: the probe tables
        # (hundreds of MB) exceed VMEM, so the probe stays HBM random
        # access, which XLA's native gather already is.
        self._match_fn = match_batch_jit

    # ------------------------------------------------------------ mutation

    def fid_of(self, filt: str) -> Optional[int]:
        return self._fids.get(filt)

    def add_filter(self, filt: str) -> int:
        fid = self._fids.get(filt)
        if fid is not None:
            self._refs[fid] += 1
            return fid
        fid = self._free_fids.pop() if self._free_fids else self._alloc_fid()
        ws = topiclib.words(filt)
        self._fids[filt] = fid
        self._refs[fid] = 1
        self._words[fid] = ws
        self._fbytes[fid] = filt.encode("utf-8")
        if self._is_deep(ws):
            self._deep.insert(filt, fid)
            self._deep_fids.add(fid)
        else:
            self.tables.insert(ws, fid)
        self.epoch += 1
        return fid

    def add_filters(self, filts: Sequence[str]) -> List[int]:
        """Bulk add (route-table bootstrap): one native key pass + one
        device rebuild instead of len(filts) incremental inserts."""
        fids: List[int] = []
        new_strs: List[str] = []
        new_fids: List[int] = []
        for filt in filts:
            fid = self._fids.get(filt)
            if fid is not None:
                self._refs[fid] += 1
                fids.append(fid)
                continue
            fid = self._free_fids.pop() if self._free_fids else self._alloc_fid()
            ws = topiclib.words(filt)
            self._fids[filt] = fid
            self._refs[fid] = 1
            self._words[fid] = ws
            self._fbytes[fid] = filt.encode("utf-8")
            fids.append(fid)
            if self._is_deep(ws):
                self._deep.insert(filt, fid)
                self._deep_fids.add(fid)
            else:
                new_strs.append(filt)
                new_fids.append(fid)
        if new_strs:
            self.tables.bulk_insert(new_strs, new_fids)
        self.epoch += 1
        return fids

    def remove_filter(self, filt: str) -> Optional[int]:
        """Drop one reference; returns the fid if it was fully removed."""
        fid = self._fids.get(filt)
        if fid is None:
            return None
        self._refs[fid] -= 1
        if self._refs[fid] > 0:
            return None
        del self._refs[fid]
        del self._fids[filt]
        del self._words[fid]
        del self._fbytes[fid]
        if fid in self._deep_fids:
            self._deep_fids.discard(fid)
            self._deep.delete(filt, fid)
        else:
            self.tables.delete(fid)
        self._free_fids.append(fid)
        self.epoch += 1
        return fid

    def apply_churn(
        self, adds: Sequence[str], removes: Sequence[str]
    ) -> List[int]:
        """One churn tick: batched unsubscribes + subscribes.

        The per-op path costs ~30us of host hashing/placement per
        filter — fine for interactive subscribes, but a 5%/s churn
        against 10M routes is ~500k ops/s (BASELINE config 5).  Here the
        adds' key computation and placement run in one native pass
        (matchhash.cc etpu_filter_keys + etpu_bulk_place_slots) and the
        device mirror still receives a single delta scatter.  Returns
        the fids assigned to `adds`.
        """
        dead_fids: List[int] = []
        for filt in removes:
            fid = self._fids.get(filt)
            if fid is None:
                continue
            self._refs[fid] -= 1
            if self._refs[fid] > 0:
                continue
            del self._refs[fid]
            del self._fids[filt]
            ws = self._words.pop(fid)
            self._fbytes.pop(fid, None)
            if fid in self._deep_fids:
                self._deep_fids.discard(fid)
                self._deep.delete(filt, fid)
            else:
                dead_fids.append(fid)
            self._free_fids.append(fid)
        if dead_fids:
            self.tables.delete_batch(dead_fids)
        out: List[int] = []
        new_strs: List[str] = []
        new_fids: List[int] = []
        new_words: List[List[str]] = []
        for filt in adds:
            fid = self._fids.get(filt)
            if fid is not None:
                self._refs[fid] += 1
                out.append(fid)
                continue
            ws = topiclib.words(filt)
            fid = self._free_fids.pop() if self._free_fids else self._alloc_fid()
            self._fids[filt] = fid
            self._refs[fid] = 1
            self._words[fid] = ws
            self._fbytes[fid] = filt.encode("utf-8")
            if self._is_deep(ws):
                self._deep.insert(filt, fid)
                self._deep_fids.add(fid)
            else:
                new_strs.append(filt)
                new_fids.append(fid)
                new_words.append(ws)
            out.append(fid)
        if new_strs:
            self.tables.churn_insert(new_strs, new_fids, words=new_words)
        self.epoch += 1
        return out

    def _alloc_fid(self) -> int:
        self._next_fid += 1
        return self._next_fid - 1

    def _is_deep(self, ws: Sequence[str]) -> bool:
        # effective depth = levels minus a trailing '#': cheap length
        # check on the hot subscribe path (no Shape construction)
        plen = len(ws) - (1 if ws and ws[-1] == "#" else 0)
        return plen > self.space.max_levels

    @property
    def n_filters(self) -> int:
        return len(self._fids)

    # --------------------------------------------------------------- sync

    @staticmethod
    def _pack_delta(delta) -> Optional[np.ndarray]:
        """Slot delta as ONE [4, K] u32 array (or None when empty).

        One transfer instead of four puts: each put is a round trip on a
        tunneled device (slots/vals bit-cast to u32; slot -1 = padding)."""
        if not delta.slots:
            return None
        k = _next_pow2(max(len(delta.slots), 16))
        n = len(delta.slots)
        packed = np.zeros((4, k), dtype=np.uint32)
        packed[0] = np.uint32(0xFFFFFFFF)
        packed[0, :n] = np.asarray(delta.slots, dtype=np.int32).view(np.uint32)
        packed[1, :n] = delta.key_a
        packed[2, :n] = delta.key_b
        packed[3, :n] = np.asarray(delta.val, dtype=np.int32).view(np.uint32)
        return packed

    def _sync_descs(self, delta) -> Optional[np.ndarray]:
        """Apply rebuild/descriptor updates; return the still-unapplied
        packed slot delta (to be fused into the next dispatch)."""
        if self._dev is None or delta.rebuilt:
            self._dev = DeviceTables.from_host(self.tables, self.device)
            return None
        if delta.desc_dirty:
            import jax

            # copies: the host mutates these arrays in place later (see
            # DeviceTables.from_host)
            put = lambda a: jax.device_put(a.copy(), self.device)
            self._dev = self._dev._replace(
                incl=put(self.tables.incl),
                k_a=put(self.tables.k_a),
                k_b=put(self.tables.k_b),
                min_len=put(self.tables.min_len),
                max_len=put(self.tables.max_len),
                wild_root=put(self.tables.wild_root),
                valid=put(self.tables.valid),
            )
        return self._pack_delta(delta)

    def sync_device(self) -> DeviceTables:
        """Bring the HBM mirror up to date with host truth."""
        packed = self._sync_descs(self.tables.drain_delta())
        if packed is not None:
            import jax
            from ..ops.match import apply_delta_packed

            self._dev = apply_delta_packed(
                self._dev, jax.device_put(packed, self.device)
            )
        return self._dev

    # -------------------------------------------------------------- match

    def match_submit(self, topics: Sequence[str]) -> "_PendingMatch":
        """Dispatch the device match WITHOUT blocking.

        Pending subscription churn is fused into the same dispatch
        (`ops.match.fused_step_sparse`), so a churn tick costs the same
        single device round trip as a pure match tick; the return is the
        device-compacted [B, K] top-fid block, not the full [B, M] row.
        Pair with :meth:`match_collect`; submitting batch N before
        collecting batch N-1 overlaps host hashing + upload with device
        compute (the end-to-end pipeline of round-2 VERDICT weak #1)."""
        out = pbatch = None
        hcap = 0
        if self.tables.n_entries:
            import jax

            from ..ops.match import (
                fused_step_sparse,
                match_batch_sparse,
                pack_topic_batch_np,
                prepare_topics_raw,
            )

            delta = self.tables.drain_delta()
            packed = self._sync_descs(delta)
            nb, _n = prepare_topics_raw(self.space, topics, self.min_batch)
            B = nb.terms_a.shape[0]
            hcap = B * self._hcap_mult
            # truncate term levels to this batch's real depth: the terms
            # array IS the upload payload (~64 MB/s real link bandwidth).
            # Rounded UP to the next EVEN depth so the kernel compiles at
            # most max_levels/2 variants instead of one per distinct
            # topic depth — a fresh depth otherwise pays a multi-second
            # XLA compile mid-traffic (and trips the OLP shed) — while
            # wasting at most one level of upload bytes
            L_real = max(1, min(self.space.max_levels, int(nb.length.max())))
            L_used = min(self.space.max_levels, L_real + (L_real & 1))
            pbatch = jax.device_put(
                pack_topic_batch_np(
                    nb.terms_a[:, :L_used], nb.terms_b[:, :L_used],
                    nb.length, nb.dollar,
                ),
                self.device,
            )
            if packed is not None:
                self._dev, out = fused_step_sparse(
                    self._dev, jax.device_put(packed, self.device), pbatch,
                    hcap=hcap,
                )
            else:
                out = match_batch_sparse(self._dev, pbatch, hcap=hcap)
            try:  # start the device->host copy NOW; collect() overlaps it
                out.copy_to_host_async()
            except AttributeError:  # pragma: no cover - older jax
                pass
        # snapshot THIS tick's table version: later pipelined submits may
        # advance self._dev, and the overflow refetch must not see them
        return _PendingMatch(out, hcap, pbatch, self._dev, list(topics))

    def match_collect(self, pending: "_PendingMatch") -> List[Set[int]]:
        """Block on a submitted match and return verified fid sets."""
        topics = pending.topics
        out: List[Set[int]] = [set() for _ in topics]
        if pending.out is not None:
            n = len(topics)
            arr = np.asarray(pending.out)
            hcap = pending.hcap
            total = int(arr[-1])
            counts = arr[hcap:-1].view(np.uint16)[:n].astype(np.int64)
            if total > hcap or (counts >= 0xFFFF).any():
                # more hits than the sparse buffer holds: refetch the full
                # row set once (against THIS tick's tables) and widen the
                # next submits
                from ..ops.match import match_batch_packed

                full = np.asarray(
                    match_batch_packed(pending.tables, pending.batch)
                )[:n]
                self._hcap_mult *= 2
                ii, jj = np.nonzero(full >= 0)
                fids = full[ii, jj]
            else:
                offs = np.zeros(n + 1, dtype=np.int64)
                np.cumsum(counts, out=offs[1:])
                fids = arr[: offs[-1]]
                ii = np.repeat(np.arange(n), counts)
            if ii.size:
                if self.verify_matches:
                    self._verify_into(topics, ii, fids, out)
                else:
                    for i, f in zip(ii.tolist(), fids.tolist()):
                        out[i].add(int(f))
        if self._deep_fids:
            for i, t in enumerate(topics):
                out[i] |= self._deep.match(t) & self._deep_fids
        return out

    def match(self, topics: Sequence[str]) -> List[Set[int]]:
        """Match a publish batch; returns the set of fids per topic.

        Device hits are verified against host truth by default: the
        device compares 2x32-bit lane hashes, so an astronomically-rare
        lane collision between a topic and an unrelated same-shape filter
        would otherwise cause a false delivery.  The reference's trie is
        exact (`emqx_trie.erl:272-334`); `verify_matches` keeps that
        guarantee, counting any discard in `collision_count` /
        `on_collision`."""
        return self.match_collect(self.match_submit(topics))

    def _collide(self, topic: str, fid: int) -> None:
        self.collision_count += 1
        if self.on_collision is not None:
            self.on_collision(topic, fid)

    def _verify_into(
        self,
        topics: Sequence[str],
        ii: np.ndarray,
        fids: np.ndarray,
        out: List[Set[int]],
    ) -> None:
        verify_pairs_into(
            topics, ii, fids, self._words, self._fbytes, out, self._collide
        )

    def match_one(self, name: str) -> Set[int]:
        return self.match([name])[0]


class _PendingMatch:
    """An in-flight device match (see TopicMatchEngine.match_submit)."""

    __slots__ = ("out", "hcap", "batch", "tables", "topics")

    def __init__(self, out, hcap, batch, tables, topics):
        self.out = out
        self.hcap = hcap
        self.batch = batch
        self.tables = tables  # table version this tick matched against
        self.topics = topics
