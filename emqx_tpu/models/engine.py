"""TopicMatchEngine — the flagship: a TPU-resident topic-match automaton.

This is the TPU-native replacement for the reference's route/trie core
(`emqx_router:match_routes/1`, `emqx_trie:match/1` — SURVEY.md §1.7/§3.3).
Canonical truth lives on the host (`MatchTables` + python dicts, the analog of
mnesia/ETS); the device arrays are a cache rebuilt or patched from host truth
(SURVEY.md §5.4 failure model), versioned by an epoch counter.

API:
    fid = engine.add_filter("sensors/+/temp")      # refcounted
    engine.remove_filter("sensors/+/temp")
    sets = engine.match(["sensors/3/temp", ...])   # -> List[Set[fid]]

Filters deeper than the device level cap fall back to a host-side trie —
the same escape hatch as the reference's depth-bounding compaction
(`emqx_trie.erl:202-233`).

Hybrid host/device arbitration: the reference never pays a wire to match
(`emqx_router.erl:127-140` — matching is an in-node ETS walk).  When the
host<->device link is degraded (measured, not assumed), this engine
serves matches from a native host-side probe over the SAME table arrays
the device mirrors (`native/registry.cc etpu_match_host_verified` —
identical shape-enumeration semantics by construction), keeps the HBM
mirror warm
with periodic probe dispatches, and switches back the moment the
measured device rate beats the host rate.  Device-served batches carry a
timeout fallback to the host path, so a mid-traffic device stall can
never block a publish tick behind a multi-second transfer.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .. import fault as _fault
from ..broker import topic as topiclib
from ..observe.flight import (
    FlightRecorder,
    LatencyHistogram,
    PATH_DEVICE,
    PATH_HOST,
    PATHS,
    R_BREAKER,
    R_COLD_MIRROR,
    R_FORCED,
    R_HOST_REFRESH,
    R_LINK_STALL,
    R_OVERFLOW,
    R_RATE,
    R_UNMEASURED,
    REASONS,
)
from ..observe import tracepoints as _tps
from ..observe.tracepoints import tp
from ..ops import hashing
from ..ops.match import (
    DeviceTables,
    next_pow2 as _next_pow2,
)
from ..ops.tables import MatchTables
from .reference import CpuTrieIndex


def verify_pairs_into(topics, ii, fids, words_map, fbytes_map, out, collide):
    """Exact verification of device hash hits as (topic_idx, fid) pairs.

    Uses the native batch matcher (`native/matchhash.cc
    etpu_verify_pairs`) when available, Python `match_words` otherwise.
    Verified fids land in `out[topic_idx]`; refuted pairs go to
    `collide(topic, fid)`.  Shared by the single-chip and sharded engine
    frontends.  The pair-assembly fast path is a single map over the
    fbytes dict — per-pair Python tuples would dominate at 100k+ hits."""
    from ..ops import native

    fid_list = fids.tolist()
    ii_arr = np.asarray(ii, dtype=np.int32)
    try:
        fblobs = list(map(fbytes_map.__getitem__, fid_list))
    except KeyError:
        # a fid raced a removal between sync and collect: rare slow path
        keep = []
        fblobs = []
        for k, f in enumerate(fid_list):
            fb = fbytes_map.get(f)
            if fb is None:
                collide(topics[int(ii_arr[k])], f)
            else:
                keep.append(k)
                fblobs.append(fb)
        if not keep:
            return
        ii_arr = ii_arr[keep]
        fid_list = [fid_list[k] for k in keep]
    if native.available():
        tblobs = [t.encode("utf-8") for t in topics]
        ok = native.verify_pairs(tblobs, ii_arr, fblobs)
    else:
        ok = None
    if ok is not None:
        if ok.all():  # collisions are astronomically rare: fast path
            for i, f in zip(ii_arr.tolist(), fid_list):
                out[i].add(f)
        else:
            for i, f, good in zip(ii_arr.tolist(), fid_list, ok.tolist()):
                if good:
                    out[i].add(f)
                else:
                    collide(topics[i], f)
    else:
        twcache: Dict[int, List[str]] = {}
        for i, f in zip(ii_arr.tolist(), fid_list):
            tw = twcache.get(i)
            if tw is None:
                tw = twcache[i] = topiclib.words(topics[i])
            if topiclib.match_words(tw, words_map[f]):
                out[i].add(f)
            else:
                collide(topics[i], f)


class TopicMatchEngine:
    def __init__(
        self,
        space: Optional[hashing.HashSpace] = None,
        device=None,
        min_batch: int = 64,
        kcap: int = 32,
        use_churn_plane: Optional[bool] = None,
        churn_shards: int = 16,
    ):
        self.space = space or hashing.HashSpace()
        self.tables = MatchTables(self.space)
        self.device = device
        # even batch floor: the sparse return packs u16 counts in pairs
        self.min_batch = max(2, min_batch + (min_batch & 1))
        self.kcap = kcap  # retained for API compat; sparse path sizes by hits

        # ---- engine concurrency contract (cross-thread lint annotations)
        # Mutation state (tables, registries, fid allocation) has ONE
        # mutator at a time: runtime churn is serialized on the event
        # loop; boot warm-restore runs on a to_thread worker BEFORE any
        # listener serves (the executor join publishes the writes).
        # Serve-path telemetry (counters, EWMA rates, breaker flags) is
        # written from collect executor threads and read on the loop as
        # GIL-atomic int/float/bool stores — the benign-dirty-read model
        # PR 6 established for the churn plane; a torn read costs one
        # stat sample, never correctness.
        self._fids: Dict[str, int] = {}  # filter str -> fid
        self._refs: Dict[int, int] = {}  # fid -> refcount
        self._words: Dict[int, List[str]] = {}
        self._fbytes: Dict[int, bytes] = {}  # utf-8 filter strings (native verify)
        self._next_fid = 0  # analysis: owner=loop
        self._free_fids: List[int] = []

        # host fallback for filters deeper than the device level cap
        self._deep = CpuTrieIndex()
        self._deep_fids: Set[int] = set()

        # native fid -> filter-string registry (C++-owned): backs inline
        # verification in the fused host match and registry-backed device
        # verify; None without the native lib (pure-Python fallbacks)
        from ..ops import native as _native

        self._reg = _native.make_registry()

        # parallel churn plane (native/churn.cc): C++-owned filter ->
        # (fid, refcount, key) truth sharded by matchhash(filter) %
        # churn_shards and mutated by the worker pool with the GIL
        # released — replaces the Python dict bookkeeping that was the
        # single-core ceiling at config 5's 500k churn ops/s.  When
        # present it IS the registry of record (_fids/_refs stay empty);
        # without the native lib the dict paths below remain canonical.
        self._plane = None
        if use_churn_plane is None:
            use_churn_plane = True
        if use_churn_plane and self._reg is not None:
            self._plane = _native.make_churn_plane(self.space, churn_shards)

        # fused prep front (ops/prep.py): split + hash + two-generation
        # topic memo + in-tick dedup + bucket-padded pack in ONE native
        # pass (single-chip adoption of the sharded mesh's fused prep
        # op; pure-Python fallback when the lib is absent).  Buffers are
        # packed fresh per tick here (reuse=False): single-chip pendings
        # hold their pbatch for the pipeline window, so pooled recycling
        # would alias a live device_put source.
        from ..ops.prep import TopicPrep

        self._prep = TopicPrep(self.space, min_batch=self.min_batch)

        # churn shed-load visibility: ops the pacing layer dropped
        # because apply capacity lagged demand (note_churn_shed)
        self.churn_shed = 0
        self._churn_shed_rec = 0  # high-water mark already flight-recorded  # analysis: owner=any

        # exact-match guarantee: verify device hash hits against stored
        # filter words (default on; see match())
        self.verify_matches = True
        self.collision_count = 0  # analysis: owner=any
        self.on_collision = None  # fn(topic, fid) — metrics hook

        # checkpoint WAL hook (checkpoint/manager.py): called with
        # (adds, removes) as each mutation commits to host truth, so a
        # snapshot + the logged tail always reconstructs this state
        self.on_churn = None

        self.epoch = 0  # bumps on every device-visible mutation  # analysis: owner=loop
        self._dev: Optional[DeviceTables] = None  # analysis: owner=loop
        self._dev_stale = True
        self._hcap_mult = 1  # sparse-return size factor (doubles on overflow)  # analysis: owner=any

        # dispatch-pipeline window (engine.pipeline_depth): the single-
        # chip fused step is already non-donating, so concurrent in-
        # flight ticks share the device tables by construction — the
        # engine only tracks occupancy (submitted-but-uncollected ticks)
        # for the flight recorder and the batcher's pacing
        self.pipeline_depth = 4
        self._inflight_n = 0  # analysis: owner=any

        # ---- hybrid host/device arbitration state (see module docstring)
        # Default OFF at the class level so unit tests exercise the device
        # path deterministically; the node runtime enables it from config
        # (broker.hybrid, default true) and bench.py measures both.
        self.hybrid = False
        self.rate_host: Optional[float] = None  # EWMA lookups/s, host path  # analysis: owner=any
        self.rate_dev: Optional[float] = None  # EWMA lookups/s, device path  # analysis: owner=any
        self.probe_interval = 10.0  # re-measure the idle path this often (s)
        self.dev_timeout_floor = 0.25  # min device-collect timeout (s)
        self.host_serve_count = 0  # analysis: owner=any
        self.dev_serve_count = 0  # analysis: owner=any
        self.dev_timeout_count = 0  # analysis: owner=any
        # device-path circuit breaker: after `breaker_threshold`
        # CONSECUTIVE device timeouts the engine stops arbitrating and
        # serves host-only (reason R_BREAKER) — per-tick fallback alone
        # would keep re-trying a dead link and paying the timeout floor
        # every few ticks.  Probes keep running while open; the first
        # completed probe (or device serve) closes it.  `on_breaker` is
        # the node-runtime alarm hook (engine_device_degraded).
        self.breaker_threshold = 3
        self.breaker_open = False  # analysis: owner=any
        self.breaker_trips = 0  # analysis: owner=any
        self.consec_dev_timeouts = 0  # analysis: owner=any
        self.on_breaker: Optional[object] = None  # fn(open: bool)
        self._probe = None  # in-flight device probe: (out, t0, n_topics)
        # adaptive probe batch: starts small (a probe's terms upload rides
        # the possibly-degraded link on the serving thread), escalates to
        # full serving batches when probes come back fast — so on healthy
        # hardware rate_dev is measured at the REAL batch size and the
        # arbiter is unbiased, while a dead link only ever pays small
        # probes
        self._probe_cap = 512
        # churn-delta slots a single probe dispatch may ship (the rest
        # stays pending; see _maybe_probe_device's sync policy)
        self.probe_delta_cap = 8192
        self._last_dev_meas = 0.0  # analysis: owner=any
        self._last_host_meas = 0.0  # analysis: owner=any

        # ---- flight recorder + latency histograms (observe/flight.py):
        # one ring-buffer row per tick (path, reason, rates, wire bytes,
        # verify mismatches, churn lag) and log2-bucket histograms for
        # tick latency / probe round-trip / churn apply.  Set flight=None
        # to disable the ring (engine.flight_ring=0); histograms stay —
        # they are one bucket increment per tick.
        self.flight: Optional[FlightRecorder] = FlightRecorder()
        self.hist_tick = LatencyHistogram()
        self.hist_probe = LatencyHistogram()
        self.hist_churn = LatencyHistogram()
        self.path_flips = 0  # analysis: owner=any
        self.probe_count = 0
        self._last_served = -1  # PATH_* of the previous tick (flip detect)  # analysis: owner=any
        self._churn_lag = 0.0  # duration of the most recent apply_churn  # analysis: owner=any
        # The match hot path is pure XLA by design.  A Pallas kernel for
        # the hash contraction was built and measured on a real TPU
        # (round-1 commit c2423d1): ~46 ms vs XLA's ~0.03-0.2 ms per
        # 4096-topic batch — XLA's fusion of the masked-sum contraction
        # is already near roofline.  A *fused* hash+probe kernel cannot
        # win either at the 10M-filter target: the probe tables
        # (hundreds of MB) exceed VMEM, so the probe stays HBM random
        # access, which XLA's native gather already is.

    # ------------------------------------------------------------ mutation

    def fid_of(self, filt: str) -> Optional[int]:
        if self._plane is not None:
            return self._plane.lookup(filt)
        return self._fids.get(filt)

    def fid_map(self) -> Dict[str, int]:
        """filter -> fid copy (tests/introspection; O(n))."""
        if self._plane is not None:
            return self._plane.fid_map()
        return dict(self._fids)

    def free_fid_count(self) -> int:
        if self._plane is not None:
            return self._plane.free_count()
        return len(self._free_fids)

    def refcount_of(self, filt: str) -> int:
        if self._plane is not None:
            return self._plane.refcount(filt)
        fid = self._fids.get(filt)
        return 0 if fid is None else self._refs[fid]

    # ---- fused-prep topic-memo telemetry (ops/prep.py; synced to the
    # engine.memo_* metrics counters by Broker.sync_engine_metrics)

    @property
    def memo_hits(self) -> int:
        return self._prep.hits

    @property
    def memo_misses(self) -> int:
        return self._prep.misses

    def note_churn_shed(self, n: int) -> None:
        """Count churn ops shed upstream (demand exceeded apply
        capacity): the pacing layer calls this instead of dropping
        silently, so shed load is visible in the flight recorder, the
        `engine.churn_shed` counter, and bench JSON."""
        if n <= 0:
            return
        self.churn_shed += n
        tp("engine.churn.shed", shed=n, total=self.churn_shed)

    # ---- churn-plane fast paths (native/churn.cc; see __init__) -------

    def _plane_deep(self, res, adds, removes) -> None:
        """Route the plane's deep entries (plen > device level cap) to
        the host-trie fallback — the plane owns their fid/refcount, the
        trie + _words/_fbytes own their match truth."""
        if res.new_deep.any():
            for k in np.nonzero(res.new_deep)[0].tolist():
                filt = adds[int(res.new_aidx[k])]
                fid = int(res.new_fid[k])
                ws = topiclib.words(filt)
                self._words[fid] = ws
                self._fbytes[fid] = filt.encode("utf-8")
                self._deep.insert(filt, fid)
                self._deep_fids.add(fid)
        if res.dead_deep.any():
            for k in np.nonzero(res.dead_deep)[0].tolist():
                filt = removes[int(res.dead_ridx[k])]
                fid = int(res.dead_fid[k])
                self._deep_fids.discard(fid)
                self._deep.delete(filt, fid)
                self._words.pop(fid, None)
                self._fbytes.pop(fid, None)

    def _plane_churn(self, adds: List[str], removes: List[str]):
        """One plane tick with in-place table mutation: the native call
        does bookkeeping + keys + slot clear/place in parallel shards;
        apply_planned keeps shapes/entries/delta consistent.  Returns
        the ChurnApply result; callers own epoch/on_churn."""
        res = self._plane.apply(
            adds, removes, tables=self.tables, reg=self._reg, place=True
        )
        self._plane_deep(res, adds, removes)
        if len(res.new_fid) or len(res.dead_fid):
            nk = ~res.new_deep
            dk = ~res.dead_deep
            self.tables.apply_planned(
                res.new_fid[nk], res.new_ha[nk], res.new_hb[nk],
                res.new_plen[nk], res.new_mask[nk], res.new_hash[nk],
                res.new_slot[nk],
                res.dead_fid[dk], res.dead_plen[dk], res.dead_mask[dk],
                res.dead_hash[dk], res.dead_slot[dk],
            )
        return res

    def add_filter(self, filt: str) -> int:
        if self._plane is not None:
            res = self._plane_churn([filt], [])
            self.epoch += 1
            if self.on_churn is not None:
                self.on_churn([filt], [])
            return int(res.fids[0])
        fid = self._fids.get(filt)
        if fid is not None:
            self._refs[fid] += 1
            if self.on_churn is not None:
                # refcount bumps must reach the WAL too: every replayed
                # remove decrements, so every increment must be logged
                self.on_churn([filt], [])
            return fid
        fid = self._free_fids.pop() if self._free_fids else self._alloc_fid()
        ws = topiclib.words(filt)
        self._fids[filt] = fid
        self._refs[fid] = 1
        if self._is_deep(ws):
            self._words[fid] = ws
            self._fbytes[fid] = filt.encode("utf-8")
            self._deep.insert(filt, fid)
            self._deep_fids.add(fid)
        else:
            self.tables.insert(ws, fid)
            if self._reg is not None:
                # registry owns the string (inline verify); the Python
                # dicts stay empty for table-resident filters
                self._reg.set_bulk([fid], [filt.encode("utf-8")])
            else:
                self._words[fid] = ws
                self._fbytes[fid] = filt.encode("utf-8")
        self.epoch += 1
        if self.on_churn is not None:
            self.on_churn([filt], [])
        return fid

    def add_filters(self, filts: Sequence[str]) -> List[int]:
        """Bulk add (route-table bootstrap): one native key pass + one
        device rebuild instead of len(filts) incremental inserts.

        With the native registry present, per-filter Python bookkeeping
        is the insert-rate ceiling, so the fast path keeps it to the
        refcount dicts only: no words() split, no utf-8 encode, no
        _words/_fbytes entries for table-resident filters (the registry
        owns their strings; deep filters keep the Python-side state
        their trie fallback needs)."""
        from ..ops import native

        if self._plane is not None:
            if not isinstance(filts, list):
                filts = list(filts)
            if len(filts) >= 512:
                # bootstrap scale: plane bookkeeping (no placement) +
                # ONE native table rebuild beats incremental placement
                res = self._plane.apply(filts, [], reg=self._reg,
                                        place=False)
                self._plane_deep(res, filts, [])
                keep = ~res.new_deep
                nk = res.new_fid[keep]
                if len(nk):
                    self.tables.bulk_insert_keys(
                        nk, res.new_ha[keep], res.new_hb[keep],
                        res.new_plen[keep], res.new_mask[keep],
                        res.new_hash[keep],
                    )
                out = res.fids.tolist()
            else:
                out = self._plane_churn(filts, []).fids.tolist()
            self.epoch += 1
            if self.on_churn is not None:
                self.on_churn(list(filts), [])
            return out
        if self._reg is None or len(filts) < 512:
            return self._add_filters_slow(filts)
        if not isinstance(filts, list):
            filts = list(filts)
        fids, new_strs, new_fids = self._bulk_alloc(filts)
        if new_strs:
            keys = native.filter_keys_packed(
                new_strs, self.space.max_levels, self.space
            )
            ha, hb, plen, plus_mask, has_hash, buf, offs = keys
            deep_mask = plen > self.space.max_levels
            if deep_mask.any():
                for k in np.nonzero(deep_mask)[0].tolist():
                    filt, fid = new_strs[k], new_fids[k]
                    ws = topiclib.words(filt)
                    self._words[fid] = ws
                    self._fbytes[fid] = filt.encode("utf-8")
                    self._deep.insert(filt, fid)
                    self._deep_fids.add(fid)
                keep = np.nonzero(~deep_mask)[0]
                kl = keep.tolist()
                shallow_strs = [new_strs[k] for k in kl]
                shallow_fids = [new_fids[k] for k in kl]
                ha, hb, plen, plus_mask, has_hash = (
                    a[keep] for a in (ha, hb, plen, plus_mask, has_hash)
                )
                if shallow_fids:
                    self.tables.bulk_insert_keys(
                        shallow_fids, ha, hb, plen, plus_mask, has_hash
                    )
                    self._reg.set_bulk(
                        shallow_fids,
                        [s.encode("utf-8") for s in shallow_strs],
                    )
            else:
                self.tables.bulk_insert_keys(
                    new_fids, ha, hb, plen, plus_mask, has_hash
                )
                self._reg.set_bulk_packed(new_fids, buf, offs)
        self.epoch += 1
        if self.on_churn is not None:
            self.on_churn(list(filts), [])
        return fids

    def _bulk_alloc(
        self, filts: List[str]
    ) -> Tuple[List[int], List[str], List[int]]:
        """Bulk dedup/refcount/fid allocation via dict primitives — the
        per-filter Python loop was the insert-rate ceiling at small
        exact populations (VERDICT r4 weak #6).  Returns (fids in input
        order, new filter strings, their fids); shared by add_filters
        and apply_churn's add side so the semantics cannot diverge."""
        _fids = self._fids
        refs = self._refs
        uniq = dict.fromkeys(filts)
        counts = None
        if len(uniq) != len(filts):
            from collections import Counter

            counts = Counter(filts)
        if _fids:
            new_strs = [f for f in uniq if f not in _fids]
            exist_strs = (
                [f for f in uniq if f in _fids]
                if len(new_strs) != len(uniq)
                else []
            )
        else:
            new_strs = list(uniq)
            exist_strs = []
        n_new = len(new_strs)
        free = self._free_fids
        if free and n_new:
            # n_new > 0 guards the slices: free[-0:] would alias the
            # WHOLE free list (and del free[-0:] would wipe it)
            take = min(len(free), n_new)
            new_fids = free[-take:][::-1]
            del free[-take:]
            nxt = self._next_fid
            new_fids += list(range(nxt, nxt + n_new - take))
            self._next_fid = nxt + n_new - take
        else:
            nxt = self._next_fid
            new_fids = list(range(nxt, nxt + n_new))
            self._next_fid = nxt + n_new
        _fids.update(zip(new_strs, new_fids))
        refs.update(dict.fromkeys(new_fids, 1))
        for f in exist_strs:
            refs[_fids[f]] += counts[f] if counts is not None else 1
        if counts is not None:
            for f in new_strs:
                k = counts[f]
                if k > 1:
                    refs[_fids[f]] += k - 1
        if counts is None and not exist_strs:
            fids = new_fids  # uniq preserves filts order: 1:1 already
        else:
            fids = [_fids[f] for f in filts]
        return fids, new_strs, new_fids

    def _add_filters_slow(self, filts: Sequence[str]) -> List[int]:
        """Bulk add without the native registry (pure-Python verify state
        maintained per filter), or for small batches."""
        fids: List[int] = []
        new_strs: List[str] = []
        new_fids: List[int] = []
        for filt in filts:
            fid = self._fids.get(filt)
            if fid is not None:
                self._refs[fid] += 1
                fids.append(fid)
                continue
            fid = self._free_fids.pop() if self._free_fids else self._alloc_fid()
            ws = topiclib.words(filt)
            self._fids[filt] = fid
            self._refs[fid] = 1
            self._words[fid] = ws
            self._fbytes[fid] = filt.encode("utf-8")
            fids.append(fid)
            if self._is_deep(ws):
                self._deep.insert(filt, fid)
                self._deep_fids.add(fid)
            else:
                new_strs.append(filt)
                new_fids.append(fid)
        if new_strs:
            self.tables.bulk_insert(new_strs, new_fids)
            if self._reg is not None:
                self._reg.set_bulk(
                    new_fids, [self._fbytes[f] for f in new_fids]
                )
        self.epoch += 1
        if self.on_churn is not None:
            self.on_churn(list(filts), [])
        return fids

    def remove_filter(self, filt: str) -> Optional[int]:
        """Drop one reference; returns the fid if it was fully removed."""
        if self._plane is not None:
            if self._plane.lookup(filt) is None:
                return None  # unknown filter: no mutation, no hook
            res = self._plane_churn([], [filt])
            self.epoch += 1
            if self.on_churn is not None:
                self.on_churn([], [filt])
            return int(res.dead_fid[0]) if len(res.dead_fid) else None
        fid = self._fids.get(filt)
        if fid is None:
            return None
        self._refs[fid] -= 1
        if self._refs[fid] > 0:
            if self.on_churn is not None:
                self.on_churn([], [filt])  # refcount decrement: log it
            return None
        del self._refs[fid]
        del self._fids[filt]
        self._words.pop(fid, None)
        self._fbytes.pop(fid, None)
        if fid in self._deep_fids:
            self._deep_fids.discard(fid)
            self._deep.delete(filt, fid)
        else:
            self.tables.delete(fid)
            if self._reg is not None:
                self._reg.del_bulk([fid])
        self._free_fids.append(fid)
        self.epoch += 1
        if self.on_churn is not None:
            self.on_churn([], [filt])
        return fid

    def apply_churn(
        self, adds: Sequence[str], removes: Sequence[str]
    ) -> List[int]:
        """One churn tick: batched unsubscribes + subscribes.

        The per-op path costs ~30us of host hashing/placement per
        filter — fine for interactive subscribes, but a 5%/s churn
        against 10M routes is ~500k ops/s (BASELINE config 5).  Here the
        adds' key computation and placement run in one native pass
        (matchhash.cc etpu_filter_keys + etpu_bulk_place_slots) and the
        device mirror still receives a single delta scatter.  With the
        churn plane (native/churn.cc) the whole tick — bookkeeping,
        keys, slot clears/placements — runs sharded on the worker pool
        with the GIL released; the hook/WAL stream stays ONE serialized
        call per tick either way.  Returns the fids assigned to `adds`.
        """
        import time

        if self._plane is not None:
            t0 = time.monotonic()
            if not isinstance(adds, list):
                adds = list(adds)
            if not isinstance(removes, list):
                removes = list(removes)
            res = self._plane_churn(adds, removes)
            self.epoch += 1
            if self.on_churn is not None:
                self.on_churn(list(adds), list(removes))
            dt = time.monotonic() - t0
            self._churn_lag = dt
            self.hist_churn.observe(dt)
            tp("engine.churn", adds=len(adds), removes=len(removes),
               dt_ms=dt * 1e3, backlog_slots=len(self.tables.delta.slots))
            return res.fids.tolist()

        t0 = time.monotonic()
        dead_fids: List[int] = []
        _fids = self._fids
        refs = self._refs
        words = self._words
        fbytes = self._fbytes
        deep_fids = self._deep_fids
        free = self._free_fids
        has_reg = self._reg is not None
        # removes: optimistic pop + reinstate refcounted survivors — the
        # common churn filter has one subscriber, so the hot path is two
        # dict pops and two list appends per filter.  Duplicates in one
        # batch each count one decrement (capped at the refcount, like
        # the per-op path where extra removes find the filter gone).
        dead_append = dead_fids.append
        free_append = free.append
        fpop = _fids.pop
        rpop = refs.pop
        uniq_rem = dict.fromkeys(removes)
        rem_counts = None
        if len(uniq_rem) != len(removes):
            from collections import Counter

            rem_counts = Counter(removes)
        for filt in uniq_rem:
            fid = fpop(filt, None)
            if fid is None:
                continue
            rc = rpop(fid)
            dec = rem_counts[filt] if rem_counts is not None else 1
            if rc > dec:
                refs[fid] = rc - dec
                _fids[filt] = fid
                continue
            if fid in deep_fids:
                deep_fids.discard(fid)
                self._deep.delete(filt, fid)
            else:
                dead_append(fid)
            # always drop the Python-side verify state: small batches go
            # through _add_filters_slow which populates these even when
            # the registry is present — a stale entry would verify a
            # reused fid against the wrong filter
            words.pop(fid, None)
            fbytes.pop(fid, None)
            free_append(fid)
        if dead_fids:
            self.tables.delete_batch(dead_fids)
            if self._reg is not None:
                self._reg.del_bulk(dead_fids)
        new_words: List[List[str]] = []
        # adds: bulk dedup/alloc via dict primitives (same shape as
        # add_filters' fast path); the per-filter loop only survives for
        # refcount bumps and the no-registry fallback
        if has_reg:
            if not isinstance(adds, list):
                adds = list(adds)
            out, new_strs, new_fids = self._bulk_alloc(adds)
        else:
            out = []
            new_strs = []
            new_fids = []
            out_append = out.append
            strs_append = new_strs.append
            nfids_append = new_fids.append
            nxt = self._next_fid
            for filt in adds:
                fid = _fids.get(filt)
                if fid is not None:
                    refs[fid] += 1
                    out_append(fid)
                    continue
                if free:
                    fid = free.pop()
                else:
                    fid = nxt
                    nxt += 1
                _fids[filt] = fid
                refs[fid] = 1
                ws = topiclib.words(filt)
                if self._is_deep(ws):
                    words[fid] = ws
                    fbytes[fid] = filt.encode("utf-8")
                    self._deep.insert(filt, fid)
                    deep_fids.add(fid)
                else:
                    words[fid] = ws
                    fbytes[fid] = filt.encode("utf-8")
                    strs_append(filt)
                    nfids_append(fid)
                    new_words.append(ws)
                out_append(fid)
            self._next_fid = nxt
        if new_strs:
            if has_reg:
                from ..ops import native

                keys = native.filter_keys_packed(
                    new_strs, self.space.max_levels, self.space
                )
                ha, hb, plen, plus_mask, has_hash, buf, offs = keys
                deep_mask = plen > self.space.max_levels
                if deep_mask.any():
                    for k in np.nonzero(deep_mask)[0].tolist():
                        filt, fid = new_strs[k], new_fids[k]
                        ws = topiclib.words(filt)
                        self._words[fid] = ws
                        self._fbytes[fid] = filt.encode("utf-8")
                        self._deep.insert(filt, fid)
                        self._deep_fids.add(fid)
                    keep = np.nonzero(~deep_mask)[0]
                    kl = keep.tolist()
                    sh_strs = [new_strs[k] for k in kl]
                    sh_fids = [new_fids[k] for k in kl]
                    ha, hb, plen, plus_mask, has_hash = (
                        a[keep] for a in (ha, hb, plen, plus_mask, has_hash)
                    )
                    if sh_fids:
                        self.tables.churn_insert_keys(
                            sh_fids, ha, hb, plen, plus_mask, has_hash
                        )
                        self._reg.set_bulk(
                            sh_fids, [s.encode("utf-8") for s in sh_strs]
                        )
                else:
                    self.tables.churn_insert_keys(
                        new_fids, ha, hb, plen, plus_mask, has_hash
                    )
                    self._reg.set_bulk_packed(new_fids, buf, offs)
            else:
                self.tables.churn_insert(new_strs, new_fids, words=new_words)
        self.epoch += 1
        if self.on_churn is not None:
            self.on_churn(list(adds), list(removes))
        # churn-apply lag: host-truth apply duration, surfaced per tick
        # by the flight recorder until the next apply supersedes it
        dt = time.monotonic() - t0
        self._churn_lag = dt
        self.hist_churn.observe(dt)
        tp("engine.churn", adds=len(adds), removes=len(removes),
           dt_ms=dt * 1e3, backlog_slots=len(self.tables.delta.slots))
        return out

    def _alloc_fid(self) -> int:
        self._next_fid += 1
        return self._next_fid - 1

    def _is_deep(self, ws: Sequence[str]) -> bool:
        # effective depth = levels minus a trailing '#': cheap length
        # check on the hot subscribe path (no Shape construction)
        plen = len(ws) - (1 if ws and ws[-1] == "#" else 0)
        return plen > self.space.max_levels

    @property
    def n_filters(self) -> int:
        if self._plane is not None:
            return self._plane.count()
        return len(self._fids)

    # --------------------------------------------------------- checkpoint

    def ref_snapshot(self) -> Dict[str, int]:
        """filter -> refcount copy (checkpoint reconcile, tests)."""
        if self._plane is not None:
            buf, offs, _fids, rcs, _dp, _fr, _nx = self._plane.export()
            data = buf.tobytes()
            ol = offs.tolist()
            return {
                data[ol[i]:ol[i + 1]].decode("utf-8"): int(rc)
                for i, rc in enumerate(rcs.tolist())
            }
        refs = self._refs
        return {f: refs[fid] for f, fid in self._fids.items()}

    def export_checkpoint(self) -> Tuple[Dict[str, np.ndarray], dict]:
        """Host truth as (named arrays, JSON meta) for the snapshot
        store: the table state (`MatchTables.export_state`) plus the
        packed filter registry (strings, fids, refcounts, deep flags,
        free list).  Everything is copied/serialized at capture time so
        the writer thread never races live mutations."""
        from ..checkpoint.store import pack_nul_list, packed_to_nul

        arrays: Dict[str, np.ndarray] = {}
        t_arr, t_meta = self.tables.export_state()
        for k, v in t_arr.items():
            arrays["tab/" + k] = v
        if self._plane is not None:
            # the plane is the registry of record: export is one native
            # walk + a vectorized NUL re-pack, no Python dict iteration
            buf, offs, pfids, prefs, pdeep, pfree, next_fid = (
                self._plane.export()
            )
            n = len(pfids)
            arrays.update({
                "reg/nul": packed_to_nul(buf, offs, n),
                "reg/fid": pfids.astype(np.int64),
                "reg/ref": prefs,
                "reg/deep": pdeep,
                "reg/free": pfree.astype(np.int64),
            })
        else:
            filts = list(self._fids)
            n = len(filts)
            fids = np.fromiter(
                (self._fids[f] for f in filts), dtype=np.int64, count=n
            )
            refs = np.fromiter(
                (self._refs[int(i)] for i in fids), dtype=np.int64,
                count=n,
            )
            deep = np.fromiter(
                (int(i) in self._deep_fids for i in fids), dtype=bool,
                count=n,
            )
            arrays.update({
                "reg/nul": pack_nul_list(filts), "reg/fid": fids,
                "reg/ref": refs, "reg/deep": deep,
                "reg/free": np.asarray(self._free_fids, dtype=np.int64),
            })
            next_fid = self._next_fid
        meta = {
            "kind": "engine",
            "tables": t_meta,
            "max_levels": self.space.max_levels,
            "next_fid": next_fid,
            "n_filters": n,
        }
        return arrays, meta

    def restore_checkpoint(
        self, arrays: Dict[str, np.ndarray], meta: dict
    ) -> int:
        """Adopt a snapshot wholesale: table arrays + registries, no
        re-hashing and no placement — restore cost is array adoption,
        dict zips and one registry bulk-set, and the device mirror is
        marked rebuilt so the next dispatch ships ONE bulk upload."""
        from ..checkpoint.store import nul_to_packed, unpack_nul_list
        from ..ops import native as _native

        if meta.get("kind") != "engine":
            raise ValueError(f"snapshot kind {meta.get('kind')!r} is not "
                             "a single-chip engine checkpoint")
        tables = MatchTables.from_state(
            self.space,
            {k[4:]: v for k, v in arrays.items() if k.startswith("tab/")},
            meta["tables"],
        )
        n_filts = int(meta["n_filters"])
        deep = arrays["reg/deep"]
        self.tables = tables
        self._words = {}
        self._fbytes = {}
        self._deep = CpuTrieIndex()
        self._deep_fids = set()
        self._reg = _native.make_registry()  # fresh: drop stale entries
        if self._plane is not None:
            # fresh plane + one parallel ingest (keys recomputed per
            # shard on the pool) — the dicts stay empty, the plane is
            # the registry of record
            self._plane = _native.make_churn_plane(
                self.space, self._plane.n_shards()
            )
            buf, offs = nul_to_packed(arrays["reg/nul"], n_filts)
            fid_arr = arrays["reg/fid"]
            self._plane.ingest(buf, offs, fid_arr, arrays["reg/ref"],
                               arrays["reg/free"], int(meta["next_fid"]))
            self._fids = {}
            self._refs = {}
            self._next_fid = int(meta["next_fid"])
            self._free_fids = []
            if deep.any():
                filts = unpack_nul_list(arrays["reg/nul"], n_filts)
                fids_l = fid_arr.tolist()
                for k in np.nonzero(deep)[0].tolist():
                    filt, fid = filts[k], int(fids_l[k])
                    ws = topiclib.words(filt)
                    self._words[fid] = ws
                    self._fbytes[fid] = filt.encode("utf-8")
                    self._deep.insert(filt, fid)
                    self._deep_fids.add(fid)
                shallow = np.nonzero(~deep)[0].tolist()
                self._reg.set_bulk(
                    [fids_l[k] for k in shallow],
                    [filts[k].encode("utf-8") for k in shallow],
                )
            elif n_filts:
                self._reg.set_bulk_packed(fid_arr, buf, offs)
            self._dev = None  # mirror must rebuild from the restored truth
            self._dev_stale = True
            self._probe = None
            self.epoch += 1
            return n_filts
        filts = unpack_nul_list(arrays["reg/nul"], n_filts)
        fids = arrays["reg/fid"].tolist()
        refs = arrays["reg/ref"].tolist()
        self._fids = dict(zip(filts, fids))
        self._refs = dict(zip(fids, refs))
        self._next_fid = int(meta["next_fid"])
        self._free_fids = arrays["reg/free"].tolist()
        if deep.any():
            for k in np.nonzero(deep)[0].tolist():
                filt, fid = filts[k], fids[k]
                ws = topiclib.words(filt)
                self._words[fid] = ws
                self._fbytes[fid] = filt.encode("utf-8")
                self._deep.insert(filt, fid)
                self._deep_fids.add(fid)
            shallow = np.nonzero(~deep)[0].tolist()
            sh_fids = [fids[k] for k in shallow]
            sh_strs = [filts[k] for k in shallow]
            if self._reg is not None:
                self._reg.set_bulk(
                    sh_fids, [s.encode("utf-8") for s in sh_strs]
                )
            else:
                for f, fid in zip(sh_strs, sh_fids):
                    self._words[fid] = topiclib.words(f)
                    self._fbytes[fid] = f.encode("utf-8")
        elif self._reg is not None:
            if len(filts):
                # vectorized NUL-strip: the blob becomes the registry
                # wire format without re-encoding any string
                buf, offs = nul_to_packed(arrays["reg/nul"], n_filts)
                self._reg.set_bulk_packed(fids, buf, offs)
        else:
            for f, fid in zip(filts, fids):
                self._words[fid] = topiclib.words(f)
                self._fbytes[fid] = f.encode("utf-8")
        self._dev = None  # mirror must rebuild from the restored truth
        self._dev_stale = True
        self._probe = None
        self.epoch += 1
        return len(filts)

    # --------------------------------------------------------------- sync

    @staticmethod
    def _pack_delta(delta) -> Optional[np.ndarray]:
        """Slot delta as ONE [4, K] u32 array (or None when empty).

        One transfer instead of four puts: each put is a round trip on a
        tunneled device (slots/vals bit-cast to u32; slot -1 = padding)."""
        if not delta.slots:
            return None
        k = _next_pow2(max(len(delta.slots), 16))
        n = len(delta.slots)
        packed = np.zeros((4, k), dtype=np.uint32)
        packed[0] = np.uint32(0xFFFFFFFF)
        packed[0, :n] = np.asarray(delta.slots, dtype=np.int32).view(np.uint32)
        packed[1, :n] = delta.key_a
        packed[2, :n] = delta.key_b
        packed[3, :n] = np.asarray(delta.val, dtype=np.int32).view(np.uint32)
        return packed

    def _sync_descs(self, delta) -> Optional[np.ndarray]:
        """Apply rebuild/descriptor updates; return the still-unapplied
        packed slot delta (to be fused into the next dispatch)."""
        if self._dev is None or delta.rebuilt:
            self._dev = DeviceTables.from_host(self.tables, self.device)
            return None
        if delta.desc_dirty:
            import jax

            # copies: the host mutates these arrays in place later (see
            # DeviceTables.from_host)
            put = lambda a: jax.device_put(a.copy(), self.device)
            self._dev = self._dev._replace(
                incl=put(self.tables.incl),
                k_a=put(self.tables.k_a),
                k_b=put(self.tables.k_b),
                min_len=put(self.tables.min_len),
                max_len=put(self.tables.max_len),
                wild_root=put(self.tables.wild_root),
                valid=put(self.tables.valid),
            )
        return self._pack_delta(delta)

    def sync_device(self) -> DeviceTables:
        """Bring the HBM mirror up to date with host truth."""
        packed = self._sync_descs(self.tables.drain_delta())
        if packed is not None:
            import jax
            from ..ops.match import apply_delta_packed

            self._dev = apply_delta_packed(
                self._dev, jax.device_put(packed, self.device)
            )
        return self._dev

    # -------------------------------------------------------------- match

    def match_submit(self, topics: Sequence[str]) -> "_PendingMatch":
        """Dispatch a match WITHOUT blocking (host or device path).

        Device path: pending subscription churn is fused into the same
        dispatch (`ops.match.fused_step_sparse`), so a churn tick costs
        the same single device round trip as a pure match tick; the
        return is the device-compacted sparse block, not the full [B, M]
        row.  Pair with :meth:`match_collect`; submitting batch N before
        collecting batch N-1 overlaps host hashing + upload with device
        compute.

        Host path (hybrid arbitration, module docstring): submit is just
        a table snapshot — all work (hash, native probe, verify) runs in
        collect, which the broker executes off the event loop.

        Batches with repeated topics (Zipf-skewed production traffic hits
        the same hot names many times per tick) are deduplicated before
        either path: the terms array is the device upload payload and the
        probe is the host cost, so matching each distinct name once and
        expanding at collect scales both paths by the duplication factor.
        """
        import time

        t_sub = time.monotonic()
        topics = list(topics)
        expand = None
        n_raw = n = len(topics)
        if n >= 128:
            umap: Dict[str, int] = {}
            setd = umap.setdefault
            expand = [setd(t, len(umap)) for t in topics]
            if len(umap) > n - (n >> 3):  # <12.5% duplicates: skip
                expand = None
            else:
                topics = list(umap)
        # deep hits AFTER dedup: the walk depends only on the name, so
        # duplicates share one trie walk (and one merged row)
        deep = self._deep_hits(topics)
        reason = 0
        if self.hybrid and self.tables.n_entries and self._host_ok():
            reason = self._pick_host()
        if reason:
            self._maybe_probe_device(topics)
            p = _PendingMatch(
                None, 0, None, None, topics,
                mode="host", snap=self._snapshot(), t0=t_sub,
                deep=deep, expand=expand, reason=reason, n_raw=n_raw,
            )
            return self._note_inflight(p)
        dev_reason = (
            R_RATE
            if self.hybrid and self._host_ok() and self.tables.n_entries
            else R_FORCED
        )
        p = self._device_submit(topics, deep=deep, t0=t_sub, reason=dev_reason)
        p.expand = expand
        p.n_raw = n_raw
        return self._note_inflight(p)

    def _note_inflight(self, p: "_PendingMatch") -> "_PendingMatch":
        """Window occupancy at submit (flight-recorder telemetry)."""
        self._inflight_n += 1
        p.pipe_occ = self._inflight_n
        p.pipe_depth = self.pipeline_depth
        return p

    @property
    def inflight_ticks(self) -> int:
        """Submitted-but-uncollected ticks right now (contention
        telemetry: dispatch-window occupancy gauge)."""
        return self._inflight_n

    @property
    def delta_backlog(self) -> int:
        """Churn-delta slots awaiting the next device sync (contention
        telemetry: churn backlog gauge)."""
        return len(self.tables.delta.slots)

    def _deep_hits(self, topics: Sequence[str]) -> Optional[List[Set[int]]]:
        """Deep-filter matches, computed AT SUBMIT on the caller's thread:
        collect may run on an executor thread while subscribes mutate the
        deep trie on the loop thread — iterating it there would race."""
        if not self._deep_fids:
            return None
        return [self._deep.match(t) & self._deep_fids for t in topics]

    def _device_submit(
        self, topics: Sequence[str], deep="auto", t0=None, reason=R_FORCED
    ) -> "_PendingMatch":
        import time

        if deep == "auto":
            deep = self._deep_hits(topics)
        out = pbatch = nb = None
        hcap = 0
        bytes_up = 0
        prep_res = None
        if self.tables.n_entries:
            import jax

            from ..ops.match import (
                fused_step_sparse,
                match_batch_sparse,
            )

            delta = self.tables.drain_delta()
            cold = delta.rebuilt or self._dev is None
            packed = self._sync_descs(delta)
            if cold:
                # the mirror was (re)built this tick: the whole table
                # set rode the wire, and the tick's latency reads
                # against that, not the steady-state floor
                reason = R_COLD_MIRROR
                bytes_up += sum(
                    int(getattr(a, "nbytes", 0)) for a in self._dev
                )
            # fused prep op (ops/prep.py): split+hash through the topic
            # memo + bucket-padded pack in one native pass; term levels
            # truncate to the batch's real (even-rounded) depth — the
            # packed array IS the upload payload
            prep_res = self._prep.pack(list(topics), reuse=False)
            B = prep_res.B
            hcap = B * self._hcap_mult
            # wire-byte accounting (BENCH_TABLE.md wire floor): the
            # packed terms array IS the upload payload — 2 hash lanes x
            # 4 B x L levels per topic row, plus length/dollar — and a
            # fused churn delta rides the same dispatch
            bytes_up += prep_res.buf.nbytes
            tp0 = time.perf_counter()
            pbatch = jax.device_put(prep_res.buf, self.device)
            prep_put_s = time.perf_counter() - tp0
            if packed is not None:
                bytes_up += packed.nbytes
                self._dev, out = fused_step_sparse(
                    self._dev, jax.device_put(packed, self.device), pbatch,
                    hcap=hcap,
                )
            else:
                out = match_batch_sparse(self._dev, pbatch, hcap=hcap)
            try:  # start the device->host copy NOW; collect() overlaps it
                out.copy_to_host_async()
            except AttributeError:  # pragma: no cover - older jax
                pass
        # snapshot THIS tick's table version: later pipelined submits may
        # advance self._dev, and the overflow refetch must not see them
        p = _PendingMatch(
            out, hcap, pbatch, self._dev, list(topics),
            mode="device", snap=self._snapshot(),
            t0=t0 if t0 is not None else time.monotonic(),
            deep=deep, reason=reason, bytes_up=bytes_up,
        )
        if prep_res is not None:
            p.prep_hash_s = prep_res.hash_s
            p.prep_pack_s = prep_res.pack_s
            p.prep_put_s = prep_put_s
            p.memo_hits_tick = prep_res.hits
        return p

    def match_collect(self, pending: "_PendingMatch") -> List[Set[int]]:
        """Block on a submitted match and return verified fid sets."""
        return [set(x) for x in self.match_collect_raw(pending)]

    def match_collect_raw(self, pending: "_PendingMatch") -> List[List[int]]:
        """Like match_collect but returns per-topic fid LISTS — the
        broker's dispatch only iterates, and the engine's hit streams are
        duplicate-free by construction (one hit per shape per topic; deep
        fids disjoint from table fids), so skipping 4096 set builds per
        tick is free throughput on the hot path.

        Wraps the serving body with the flight-recorder tick record:
        submit->collect latency, the path that ACTUALLY served (a timeout
        or overflow may differ from the submit decision), wire bytes, and
        this tick's verify-mismatch count."""
        import time

        colls0 = self.collision_count
        try:
            out = self._collect_serve(pending)
        finally:
            self._inflight_n = max(0, self._inflight_n - 1)
        t1 = time.monotonic()
        lat = max(t1 - (pending.t0 if pending.t0 is not None else t1), 0.0)
        self._record_tick(pending, lat, self.collision_count - colls0)
        return out

    def _collect_serve(self, pending: "_PendingMatch") -> List[List[int]]:
        import time

        if pending.mode == "host":
            t0 = time.monotonic()
            out = self._host_collect(pending)
            dt = max(time.monotonic() - t0, 1e-9)
            self._note_host_rate(len(pending.topics) / dt)
            self.host_serve_count += 1
            pending.served = PATH_HOST
            return self._finalize(pending, out)

        topics = pending.topics
        out: List[List[int]] = [[] for _ in topics]
        pending.served = PATH_DEVICE
        if pending.out is not None:
            n = len(topics)
            arr = self._timed_fetch(pending)
            if arr is None:  # device stalled past its budget: host serves
                self.dev_timeout_count += 1
                self._note_dev_timeout()
                pending.served = PATH_HOST
                pending.reason = R_LINK_STALL
                return self._finalize(pending, self._host_collect(pending))
            self.dev_serve_count += 1
            self._note_dev_ok()
            pending.bytes_down += arr.nbytes
            hcap = pending.hcap
            total = int(arr[-1])
            counts = arr[hcap:-1].view(np.uint16)[:n].astype(np.int64)
            if total > hcap or (counts >= 0xFFFF).any():
                # more hits than the sparse buffer holds: recover the full
                # set once and widen the next submits.  The host probe is
                # the cheap recovery (same tables, no [B, M] download);
                # the device refetch remains for hosts without the lib.
                self._hcap_mult *= 2
                pending.reason = R_OVERFLOW
                if self._host_ok() and pending.snap is not None:
                    pending.served = PATH_HOST
                    return self._finalize(
                        pending, self._host_collect(pending)
                    )
                from ..ops.match import match_batch_packed

                full = np.asarray(
                    match_batch_packed(pending.tables, pending.batch)
                )[:n]
                pending.bytes_down += full.nbytes
                ii, jj = np.nonzero(full >= 0)
                fids = full[ii, jj]
            else:
                offs = np.zeros(n + 1, dtype=np.int64)
                np.cumsum(counts, out=offs[1:])
                fids = arr[: offs[-1]]
                ii = np.repeat(np.arange(n), counts)
            if ii.size:
                if self.verify_matches:
                    self._verify_into(topics, ii, fids, out)
                else:
                    for i, f in zip(ii.tolist(), fids.tolist()):
                        out[i].append(int(f))
        return self._finalize(pending, out)

    def _record_tick(
        self, pending: "_PendingMatch", lat_s: float, verify_fail: int
    ) -> None:
        """One flight-recorder row + histogram bucket per collected tick
        (near-zero cost: a struct write and two int adds)."""
        path = pending.served
        reason = pending.reason
        flip = self._last_served >= 0 and self._last_served != path
        self._last_served = path
        if flip:
            self.path_flips += 1
            tp("engine.flip", path=PATHS[path],
               reason=REASONS.get(reason, "?"),
               rate_host=self.rate_host, rate_dev=self.rate_dev)
        self.hist_tick.observe(lat_s)
        fl = self.flight
        if fl is not None:
            shed = self.churn_shed - self._churn_shed_rec
            self._churn_shed_rec = self.churn_shed
            fl.record(
                n_topics=pending.n_raw or len(pending.topics),
                n_unique=len(pending.topics),
                path=path, reason=reason,
                rate_host=self.rate_host, rate_dev=self.rate_dev,
                bytes_up=pending.bytes_up, bytes_down=pending.bytes_down,
                verify_fail=verify_fail,
                churn_slots=len(self.tables.delta.slots),
                lat_s=lat_s, churn_lag_s=self._churn_lag,
                pipe_occ=pending.pipe_occ, pipe_depth=pending.pipe_depth,
                churn_shed=shed,
                prep_hash_s=pending.prep_hash_s,
                prep_pack_s=pending.prep_pack_s,
                prep_submit_s=pending.prep_put_s,
                memo_hits=pending.memo_hits_tick,
            )
        if _tps._active:  # gate: skip kwarg evaluation when tracing is off
            tp("engine.tick", path=PATHS[path], n=len(pending.topics),
               lat_ms=lat_s * 1e3, reason=REASONS.get(reason, "?"))

    def _finalize(
        self, pending: "_PendingMatch", out: List[List[int]]
    ) -> List[List[int]]:
        """Merge deep-trie hits into the per-name rows, then expand
        deduplicated rows back to per-publish order.  Deep hits are per
        NAME (pending.deep aligns with pending.topics, deduped or not),
        so merging before expansion is correct and duplicates share one
        merged row.  Rows may be tuples (the native extension path) and
        may be aliased across duplicate topics — callers only iterate."""
        deep = pending.deep
        if deep is not None:
            for i, hits in enumerate(deep):
                if not hits:
                    continue
                row = out[i]
                if isinstance(row, tuple):
                    out[i] = [*row, *hits]
                else:
                    row.extend(hits)
        exp = pending.expand
        if exp is not None:
            out = [out[j] for j in exp]
        return out

    # ------------------------------------------------- hybrid arbitration

    def _host_ok(self) -> bool:
        # the host path is the fused registry probe: both come from the
        # native lib, so the registry handle IS the availability signal
        return self._reg is not None

    def _snapshot(self) -> tuple:
        """Reference-capture the live table arrays: rebuilds REPLACE the
        numpy arrays, so holding these keeps this tick's version alive
        (in-place slot writes after the snapshot are benign dirty reads,
        the same semantics as concurrent ETS mutation in the reference)."""
        t = self.tables
        return (t.key_a, t.key_b, t.val, t.log2cap, t.incl, t.k_a, t.k_b,
                t.min_len, t.max_len, t.wild_root, t.valid)

    def _note_dev_timeout(self) -> None:
        """One more consecutive device timeout; trip the breaker at the
        threshold (host-only serving + engine_device_degraded alarm)."""
        self.consec_dev_timeouts += 1
        if (
            not self.breaker_open
            and self.consec_dev_timeouts >= self.breaker_threshold
        ):
            self.breaker_open = True
            self.breaker_trips += 1
            tp("engine.breaker", state="open",
               consec=self.consec_dev_timeouts, rate_dev=self.rate_dev)
            if self.on_breaker is not None:
                self.on_breaker(True)

    def _note_dev_ok(self) -> None:
        """A device round trip completed: reset the streak and close an
        open breaker (probes re-close it while host-only serving)."""
        self.consec_dev_timeouts = 0
        if self.breaker_open:
            self.breaker_open = False
            tp("engine.breaker", state="closed", rate_dev=self.rate_dev)
            if self.on_breaker is not None:
                self.on_breaker(False)

    def _pick_host(self) -> int:
        """0 = device serves; else the R_* reason the host path serves
        (the code lands in the flight record and the `engine.flip` tp)."""
        import time

        if self.breaker_open:
            return R_BREAKER  # host-only until a probe heals the link
        if self.rate_host is None or self.rate_dev is None:
            return R_UNMEASURED  # measure host first; the probe measures device
        if self.rate_host >= self.rate_dev:
            return R_RATE
        # device is winning: refresh the host estimate occasionally
        if time.monotonic() - self._last_host_meas > self.probe_interval:
            return R_HOST_REFRESH
        return 0

    def _note_host_rate(self, rps: float) -> None:
        import time

        self.rate_host = (
            rps if self.rate_host is None else 0.5 * self.rate_host + 0.5 * rps
        )
        self._last_host_meas = time.monotonic()

    def _note_dev_rate(self, rps: float) -> None:
        import time

        self.rate_dev = (
            rps if self.rate_dev is None else 0.5 * self.rate_dev + 0.5 * rps
        )
        self._last_dev_meas = time.monotonic()

    def _poll_probe(self) -> None:
        """Harvest a completed device probe (non-blocking)."""
        import time

        p = self._probe
        if p is None:
            return
        if _fault.enabled():
            a = _fault.peek("engine.probe")
            if a is not None and a.kind in ("drop", "error"):
                return  # probe looks stalled: the breaker stays open
        out, t0, n = p
        try:
            ready = out is None or out.is_ready()
        except AttributeError:  # pragma: no cover - older jax: settle now
            np.asarray(out)
            ready = True
        if ready:
            # completion time is an upper bound (ready since some earlier
            # tick); ticks are frequent while serving, so the bias is small
            dt = max(time.monotonic() - t0, 1e-9)
            self._note_dev_rate(n / dt)
            self.hist_probe.observe(dt)
            self._note_dev_ok()  # a live round trip closes the breaker
            tp("engine.probe", phase="complete", n=n, dt_ms=dt * 1e3,
               rate_dev=self.rate_dev)
            if dt < 0.05:
                self._probe_cap = min(self._probe_cap * 4, 8192)
            elif dt > 0.5:
                self._probe_cap = max(self._probe_cap // 4, 128)
            self._probe = None

    def _maybe_probe_device(self, topics: Sequence[str]) -> None:
        """Keep the device mirror warm + the device rate fresh while the
        host path serves: dispatch this batch to the device (applying any
        pending churn delta); completion is polled via is_ready() on later
        ticks — the serving path never waits on it, and no thread blocks
        inside the runtime (threads stuck in device waits abort at
        interpreter shutdown)."""
        import time

        self._poll_probe()
        if self._probe is not None:
            return
        now = time.monotonic()
        if (
            self.rate_dev is not None
            and now - self._last_dev_meas <= self.probe_interval
        ):
            return
        # cap the probe batch (adaptive, see __init__): a full 4096-topic
        # probe costs ~90 ms of submit-side blocking at 5 MB/s (measured
        # as the hybrid p99 spike); fast probes escalate the cap so
        # healthy hardware is measured at real batch sizes
        probe_topics = list(topics[: self._probe_cap])
        # bound what a probe dispatch ships over the (possibly degraded)
        # link on the SERVING thread.  Under heavy churn the backlog
        # since the last probe can reach MBs (measured: 109 ms p99 at
        # 10M filters + 5%/s churn), and a pending rebuild would mean a
        # full-table re-upload (minutes at tunnel bandwidth).  Policy:
        #   small delta        -> fuse into the probe (normal)
        #   medium backlog     -> compress, apply one chunk, keep rest
        #   huge/rebuilt + big table -> measure on the STALE mirror; a
        #      real device-mode dispatch (or a shrunken backlog) syncs.
        # compressed() bounds the backlog itself: fid-reuse churn
        # rewrites the same slots, so the kept rows never exceed the
        # live table's slot count.
        from ..ops.tables import Delta

        d = self.tables.delta
        cap = self.probe_delta_cap
        tail = None
        big_table = self.tables.n_entries > 1_000_000
        if (d.rebuilt or self._dev is None) and big_table:
            if self._dev is None:
                return  # no mirror to measure; boot warm/device mode builds it
            tail = d  # detach: probe matches the stale mirror
            self.tables.delta = Delta()
        elif len(d.slots) > cap and not d.rebuilt:
            d = d.compressed()
            if len(d.slots) > 4 * cap and big_table:
                self.tables.delta = Delta(desc_dirty=d.desc_dirty)
                tail = Delta(slots=d.slots, key_a=d.key_a,
                             key_b=d.key_b, val=d.val)
            else:
                head, tail = d.split(cap)
                self.tables.delta = head
        t0 = time.monotonic()
        try:
            pend = self._device_submit(probe_topics)
        except Exception:  # pragma: no cover - probe must not break serving
            import logging

            logging.getLogger("emqx_tpu.engine").exception("device probe")
            return
        finally:
            if tail is not None:
                # older writes (an undrained head on the exception path)
                # precede the detached tail
                self.tables.delta = self.tables.delta.merge(tail)
        self._probe = (pend.out, t0, len(pend.topics))
        self.probe_count += 1
        tp("engine.probe", phase="dispatch", n=len(pend.topics),
           stale_mirror=tail is not None, bytes_up=pend.bytes_up)

    def _timed_fetch(self, pending: "_PendingMatch") -> Optional[np.ndarray]:
        """Fetch the device result, bounded by a timeout when a host
        fallback exists; returns None on timeout (rate decayed so the
        arbiter flips to the host path).  The wait is an is_ready() poll
        with a sleep step sized well under the expected completion time,
        so a fast device pays ~no overhead and a stalled one never wedges
        a thread in an uninterruptible device wait."""
        import time

        if not (self.hybrid and self._host_ok() and pending.snap is not None):
            return np.asarray(pending.out)
        if _fault.enabled():
            # injected link stall: the fetch "times out" immediately —
            # same decay + host fallback as a real stall, so chaos soaks
            # can trip the breaker without a real dead device
            a = _fault.inject("engine.collect", err=False)
            if a is not None and a.kind in ("drop", "error"):
                self.rate_dev = max((self.rate_dev or 1.0) * 0.25, 1e-6)
                self._last_dev_meas = time.monotonic()
                tp("engine.stall", n=len(pending.topics), timeout_ms=0.0,
                   rate_dev=self.rate_dev, injected=True)
                return None
        out = pending.out
        if not hasattr(out, "is_ready"):  # pragma: no cover - older jax
            return np.asarray(out)
        expected = (
            len(pending.topics) / self.rate_dev if self.rate_dev else None
        )
        timeout = max(self.dev_timeout_floor, 4 * expected) if expected else 30.0
        t0 = pending.t0 or time.monotonic()
        # deadline anchors at COLLECT entry: under the pipelined batcher a
        # tick can sit queued behind earlier collects, and that wait must
        # not be charged against the device's timeout budget.  The rate
        # sample below still spans submit->completion (the device computed
        # while queued, so completion-since-submit IS its latency bound);
        # any pessimism self-corrects through the host-mode probes, which
        # measure the raw link without queueing.
        deadline = time.monotonic() + timeout
        step = min(max((expected or 0.01) / 8, 2e-4), 5e-3)
        while not out.is_ready():
            if time.monotonic() > deadline:
                # decay the device estimate so the arbiter flips host-side;
                # later probes re-measure the link when it recovers
                self.rate_dev = max((self.rate_dev or 1.0) * 0.25, 1e-6)
                self._last_dev_meas = time.monotonic()
                tp("engine.stall", n=len(pending.topics),
                   timeout_ms=timeout * 1e3, rate_dev=self.rate_dev)
                return None
            # device-collect poll: runs on the batcher's collect
            # executor thread by contract (publish_collect), never the
            # loop — the loop awaits the executor future instead
            time.sleep(step)  # analysis: allow-blocking(collect-executor poll; the batcher keeps this off the loop)
        self._note_dev_rate(
            len(pending.topics) / max(time.monotonic() - t0, 1e-9)
        )
        return np.asarray(out)

    def _host_collect(self, pending: "_PendingMatch") -> List[List[int]]:
        """Native host probe over the snapshot tables (hybrid data plane):
        split+hash+probe+verify in ONE fused native call against the
        registry (`native/registry.cc etpu_match_core`).  Returns RAW
        per-topic rows for pending.topics — dedup expansion and deep
        merge happen in _finalize at the collect seam."""
        from ..ops import native
        from ..ops.tables import PROBE

        topics = pending.topics
        out: Optional[List[List[int]]] = None
        snap = pending.snap
        n = len(topics)
        if snap is not None and n and self._reg is not None:
            (key_a, key_b, val, log2cap, incl, k_a, k_b,
             min_len, max_len, wild_root, valid) = snap
            vcap = int(valid.sum())
            if vcap:
                res2 = native.match_host_lists(
                    self._reg, topics, self.space,
                    key_a, key_b, val, log2cap, PROBE,
                    incl, k_a, k_b, min_len, max_len, wild_root, valid,
                    vcap,
                )
                if res2 is not None:
                    out, colls = res2
                    for ti, fid in colls:
                        self._collide(topics[ti], fid)
                    return out
                tbuf, toffs = native.pack_strs(topics)
                res = native.match_host_verified(
                    self._reg, tbuf, toffs, n, self.space,
                    key_a, key_b, val, log2cap, PROBE,
                    incl, k_a, k_b, min_len, max_len, wild_root, valid,
                    vcap,
                )
                if res is None:  # pragma: no cover - lib raced away
                    p = self._device_submit(topics, deep=None)
                    return self.match_collect_raw(p)
                fids, counts, colls = res
                for ti, fid in colls:
                    self._collide(topics[ti], fid)
                fid_list = fids.tolist()
                offs = np.zeros(n + 1, dtype=np.int64)
                np.cumsum(counts, out=offs[1:])
                ol = offs.tolist()
                out = [fid_list[ol[i]:ol[i + 1]] for i in range(n)]
        if out is None:
            out = [[] for _ in topics]
        return out

    def _verify_slow(
        self, topics: Sequence[str], ii: np.ndarray, fids: np.ndarray
    ) -> List[List[int]]:
        """Python-loop verification (no native lib / raced removals)."""
        tmp: List[Set[int]] = [set() for _ in topics]
        verify_pairs_into(
            topics, ii, fids, self._words, self._fbytes, tmp, self._collide
        )
        return [list(s) for s in tmp]

    def match(self, topics: Sequence[str]) -> List[Set[int]]:
        """Match a publish batch; returns the set of fids per topic.

        Device hits are verified against host truth by default: the
        device compares 2x32-bit lane hashes, so an astronomically-rare
        lane collision between a topic and an unrelated same-shape filter
        would otherwise cause a false delivery.  The reference's trie is
        exact (`emqx_trie.erl:272-334`); `verify_matches` keeps that
        guarantee, counting any discard in `collision_count` /
        `on_collision`."""
        return self.match_collect(self.match_submit(topics))

    def _collide(self, topic: str, fid: int) -> None:
        self.collision_count += 1
        if self.on_collision is not None:
            self.on_collision(topic, fid)

    def _verify_into(
        self,
        topics: Sequence[str],
        ii: np.ndarray,
        fids: np.ndarray,
        out: List[List[int]],
    ) -> None:
        from ..ops import native

        if self._reg is not None:
            tbuf, toffs = native.pack_strs(topics)
            ok = native.verify_pairs_reg(
                self._reg, tbuf, toffs,
                np.asarray(ii, dtype=np.int32), np.asarray(fids),
            )
            if ok is not None:
                ii_l = np.asarray(ii).tolist()
                fid_l = np.asarray(fids).tolist()
                if ok.all():
                    for i, f in zip(ii_l, fid_l):
                        out[i].append(int(f))
                else:
                    for i, f, good in zip(ii_l, fid_l, ok.tolist()):
                        if good:
                            out[i].append(int(f))
                        else:
                            self._collide(topics[int(i)], int(f))
                return
        for o, s in zip(out, self._verify_slow(topics, ii, fids)):
            o.extend(s)

    def match_one(self, name: str) -> Set[int]:
        return self.match([name])[0]

    # --------------------------------------------- foreign ticket intake
    # (shm match plane: pre-packed ticks from wire workers, no topic
    # strings — verify and deep serving stay worker-side, the hub
    # returns raw hash-match runs)

    def foreign_submit(self, reqs) -> "_ForeignPending":
        """Dispatch a group of PRE-PACKED foreign ticks as one device
        call.  Each req is ``(buf, n_live)`` where buf is a `[B, 2L+2]`
        u32 staging array a wire worker's own TopicPrep produced; all
        members share one (B, L) bucket and K follows the sharded
        coalescer's 4/2/1 ladder, so ticks from DIFFERENT processes
        amortize one dispatch (the flight `grp` column).  Pending churn
        fuses into the same call, exactly like the native submit path."""
        import time

        t0 = time.monotonic()
        K = len(reqs)
        B = int(reqs[0][0].shape[0])
        if any(r[0].shape != reqs[0][0].shape for r in reqs[1:]):
            raise ValueError(
                "foreign group members must share one (B, L) bucket: "
                + ", ".join(str(tuple(r[0].shape)) for r in reqs)
            )
        ns = [int(n) for _, n in reqs]
        out = pbatch = None
        hcap = 0
        bytes_up = 0
        if self.tables.n_entries:
            import jax

            from ..ops.match import (
                fused_step_sparse,
                match_batch_sparse,
            )

            delta = self.tables.drain_delta()
            packed = self._sync_descs(delta)
            big = reqs[0][0] if K == 1 else np.concatenate(
                [r[0] for r in reqs], axis=0
            )
            hcap = K * B * self._hcap_mult
            bytes_up += big.nbytes
            pbatch = jax.device_put(big, self.device)
            if packed is not None:
                bytes_up += packed.nbytes
                self._dev, out = fused_step_sparse(
                    self._dev, jax.device_put(packed, self.device),
                    pbatch, hcap=hcap,
                )
            else:
                out = match_batch_sparse(self._dev, pbatch, hcap=hcap)
            try:
                out.copy_to_host_async()
            except AttributeError:  # pragma: no cover - older jax
                pass
        p = _ForeignPending(out, hcap, pbatch, self._dev, K, B, ns, t0,
                            bytes_up)
        self._inflight_n += 1
        p.pipe_occ = self._inflight_n
        p.pipe_depth = self.pipeline_depth
        return p

    def foreign_collect(self, pending: "_ForeignPending"):
        """Block on a foreign group; returns ``[(counts, fids)]`` per
        member in submit order (counts int64[n_j], fids i32 in row
        order).  Overflow recovers through the dense refetch and widens
        the next submits, same policy as the native collect."""
        import time

        try:
            results = self._foreign_serve(pending)
        finally:
            self._inflight_n = max(0, self._inflight_n - 1)
        lat = max(time.monotonic() - pending.t0, 0.0)
        self.hist_tick.observe(lat)
        fl = self.flight
        if fl is not None:
            fl.record(
                n_topics=sum(pending.ns), n_unique=sum(pending.ns),
                path=PATH_DEVICE, reason=R_FORCED,
                rate_host=self.rate_host, rate_dev=self.rate_dev,
                bytes_up=pending.bytes_up,
                bytes_down=pending.bytes_down, verify_fail=0,
                churn_slots=len(self.tables.delta.slots),
                lat_s=lat, churn_lag_s=self._churn_lag,
                pipe_occ=pending.pipe_occ,
                pipe_depth=pending.pipe_depth,
                prep_group=pending.k,
            )
        return results

    def _foreign_serve(self, pending: "_ForeignPending"):
        K, B, ns = pending.k, pending.nb, pending.ns
        empty = np.empty(0, np.int32)
        if pending.out is None:  # no resident tables: nothing matches
            return [(np.zeros(n, np.int64), empty) for n in ns]
        arr = np.asarray(pending.out)
        pending.bytes_down += arr.nbytes
        self.dev_serve_count += 1
        self._note_dev_ok()
        hcap = pending.hcap
        total = int(arr[-1])
        counts = arr[hcap:-1].view(np.uint16)[: K * B].astype(np.int64)
        results = []
        if total > hcap or (counts >= 0xFFFF).any():
            # sparse buffer overflowed: dense refetch against THIS
            # tick's table version, widen subsequent submits
            self._hcap_mult *= 2
            from ..ops.match import match_batch_packed

            full = np.asarray(
                match_batch_packed(pending.tables, pending.batch)
            )
            pending.bytes_down += full.nbytes
            for j, n in enumerate(ns):
                rows = full[j * B: j * B + n]
                live = rows >= 0
                results.append((
                    live.sum(axis=1).astype(np.int64),
                    rows[live].astype(np.int32),  # row-major: in order
                ))
            return results
        offs = np.zeros(K * B + 1, dtype=np.int64)
        np.cumsum(counts, out=offs[1:])
        fids_all = arr[: offs[-1]]
        for j, n in enumerate(ns):
            lo, hi = int(offs[j * B]), int(offs[j * B + n])
            results.append((
                counts[j * B: j * B + n],
                np.asarray(fids_all[lo:hi], np.int32),
            ))
        return results


class _ForeignPending:
    """An in-flight foreign (shm-plane) group: K same-geometry ticks
    from wire workers fused into one device dispatch.  `tables`/`batch`
    pin this tick's device arrays for the overflow refetch, mirroring
    `_PendingMatch`."""

    __slots__ = ("out", "hcap", "batch", "tables", "k", "nb", "ns",
                 "t0", "bytes_up", "bytes_down", "pipe_occ",
                 "pipe_depth")

    def __init__(self, out, hcap, batch, tables, k, nb, ns, t0,
                 bytes_up):
        self.out = out
        self.hcap = hcap
        self.batch = batch
        self.tables = tables
        self.k = k  # group width (the flight `grp` column)
        self.nb = nb  # per-member padded batch rows B
        self.ns = ns  # live rows per member
        self.t0 = t0
        self.bytes_up = bytes_up
        self.bytes_down = 0
        self.pipe_occ = 0
        self.pipe_depth = 0


class _PendingMatch:
    """An in-flight match (see TopicMatchEngine.match_submit).

    mode "device": `out` is the dispatched sparse result; `snap` enables
    the host timeout fallback.  mode "host": only `topics` and `snap`
    are set — the fused native probe runs at collect time.  `topics` is
    the DEDUPLICATED name list when `expand` is set; `deep` aligns with
    `topics` (per name, deduped or not).

    Telemetry fields for the flight recorder: `reason` is the R_*
    arbitration code at submit (may be overwritten at collect by a
    timeout/overflow), `served` the PATH_* that actually produced the
    rows, `n_raw` the pre-dedup publish count, `bytes_up`/`bytes_down`
    the wire bytes this tick shipped."""

    __slots__ = (
        "out", "hcap", "batch", "tables", "topics", "mode", "snap", "t0",
        "deep", "expand", "reason", "served", "n_raw", "bytes_up",
        "bytes_down", "pipe_occ", "pipe_depth", "prep_hash_s",
        "prep_pack_s", "prep_put_s", "memo_hits_tick",
    )

    def __init__(self, out, hcap, batch, tables, topics,
                 mode="device", snap=None, t0=None, deep=None, expand=None,
                 reason=0, n_raw=0, bytes_up=0):
        self.out = out
        self.hcap = hcap
        self.batch = batch
        self.tables = tables  # table version this tick matched against
        self.topics = topics
        self.mode = mode
        self.snap = snap  # host-array snapshot (hybrid fallback/serve)
        self.t0 = t0
        self.deep = deep  # deep-filter hits, snapshotted at submit
        self.expand = expand  # original index -> deduped topics row
        self.reason = reason
        self.served = PATH_HOST if mode == "host" else PATH_DEVICE
        self.n_raw = n_raw
        self.bytes_up = bytes_up
        self.bytes_down = 0
        self.pipe_occ = 0  # in-flight ticks at submit (incl. this one)
        self.pipe_depth = 0  # engine.pipeline_depth at submit
        self.prep_hash_s = 0.0  # fused-prep sub-stages (flight columns)
        self.prep_pack_s = 0.0
        self.prep_put_s = 0.0
        self.memo_hits_tick = 0  # topic-memo hits within this tick
