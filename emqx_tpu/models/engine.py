"""TopicMatchEngine — the flagship: a TPU-resident topic-match automaton.

This is the TPU-native replacement for the reference's route/trie core
(`emqx_router:match_routes/1`, `emqx_trie:match/1` — SURVEY.md §1.7/§3.3).
Canonical truth lives on the host (`MatchTables` + python dicts, the analog of
mnesia/ETS); the device arrays are a cache rebuilt or patched from host truth
(SURVEY.md §5.4 failure model), versioned by an epoch counter.

API:
    fid = engine.add_filter("sensors/+/temp")      # refcounted
    engine.remove_filter("sensors/+/temp")
    sets = engine.match(["sensors/3/temp", ...])   # -> List[Set[fid]]

Filters deeper than the device level cap fall back to a host-side trie —
the same escape hatch as the reference's depth-bounding compaction
(`emqx_trie.erl:202-233`).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..broker import topic as topiclib
from ..ops import hashing
from ..ops.match import (
    DeviceTables,
    TopicBatch,
    apply_delta,
    match_batch_jit,
    next_pow2 as _next_pow2,
)
from ..ops.tables import MatchTables
from .reference import CpuTrieIndex


def verify_hits(twords, fids, words_map):
    """Split device hash hits into (verified, collisions).

    The device compares 2x32-bit lane hashes; an astronomically-rare lane
    collision between a topic and an unrelated same-shape filter would
    otherwise cause a false delivery.  The reference trie is exact
    (`emqx_trie.erl:272-334`); this check keeps that guarantee for every
    engine frontend (single-chip and sharded)."""
    good: List[int] = []
    bad: List[int] = []
    for f in fids:
        fid = int(f)
        fwords = words_map.get(fid)
        if fwords is not None and topiclib.match_words(twords, fwords):
            good.append(fid)
        else:
            bad.append(fid)
    return good, bad


class TopicMatchEngine:
    def __init__(
        self,
        space: Optional[hashing.HashSpace] = None,
        device=None,
        min_batch: int = 64,
    ):
        self.space = space or hashing.HashSpace()
        self.tables = MatchTables(self.space)
        self.device = device
        self.min_batch = min_batch

        self._fids: Dict[str, int] = {}  # filter str -> fid
        self._refs: Dict[int, int] = {}  # fid -> refcount
        self._words: Dict[int, List[str]] = {}
        self._next_fid = 0
        self._free_fids: List[int] = []

        # host fallback for filters deeper than the device level cap
        self._deep = CpuTrieIndex()
        self._deep_fids: Set[int] = set()

        # exact-match guarantee: verify device hash hits against stored
        # filter words (default on; see match())
        self.verify_matches = True
        self.collision_count = 0
        self.on_collision = None  # fn(topic, fid) — metrics hook

        self.epoch = 0  # bumps on every device-visible mutation
        self._dev: Optional[DeviceTables] = None
        self._dev_stale = True
        # The match hot path is pure XLA by design.  A Pallas kernel for
        # the hash contraction was built and measured on a real TPU
        # (round-1 commit c2423d1): ~46 ms vs XLA's ~0.03-0.2 ms per
        # 4096-topic batch — XLA's fusion of the masked-sum contraction
        # is already near roofline.  A *fused* hash+probe kernel cannot
        # win either at the 10M-filter target: the probe tables
        # (hundreds of MB) exceed VMEM, so the probe stays HBM random
        # access, which XLA's native gather already is.
        self._match_fn = match_batch_jit

    # ------------------------------------------------------------ mutation

    def fid_of(self, filt: str) -> Optional[int]:
        return self._fids.get(filt)

    def add_filter(self, filt: str) -> int:
        fid = self._fids.get(filt)
        if fid is not None:
            self._refs[fid] += 1
            return fid
        fid = self._free_fids.pop() if self._free_fids else self._alloc_fid()
        ws = topiclib.words(filt)
        self._fids[filt] = fid
        self._refs[fid] = 1
        self._words[fid] = ws
        if self._is_deep(ws):
            self._deep.insert(filt, fid)
            self._deep_fids.add(fid)
        else:
            self.tables.insert(ws, fid)
        self.epoch += 1
        return fid

    def add_filters(self, filts: Sequence[str]) -> List[int]:
        """Bulk add (route-table bootstrap): one native key pass + one
        device rebuild instead of len(filts) incremental inserts."""
        fids: List[int] = []
        new_strs: List[str] = []
        new_fids: List[int] = []
        for filt in filts:
            fid = self._fids.get(filt)
            if fid is not None:
                self._refs[fid] += 1
                fids.append(fid)
                continue
            fid = self._free_fids.pop() if self._free_fids else self._alloc_fid()
            ws = topiclib.words(filt)
            self._fids[filt] = fid
            self._refs[fid] = 1
            self._words[fid] = ws
            fids.append(fid)
            if self._is_deep(ws):
                self._deep.insert(filt, fid)
                self._deep_fids.add(fid)
            else:
                new_strs.append(filt)
                new_fids.append(fid)
        if new_strs:
            self.tables.bulk_insert(new_strs, new_fids)
        self.epoch += 1
        return fids

    def remove_filter(self, filt: str) -> Optional[int]:
        """Drop one reference; returns the fid if it was fully removed."""
        fid = self._fids.get(filt)
        if fid is None:
            return None
        self._refs[fid] -= 1
        if self._refs[fid] > 0:
            return None
        del self._refs[fid]
        del self._fids[filt]
        del self._words[fid]
        if fid in self._deep_fids:
            self._deep_fids.discard(fid)
            self._deep.delete(filt, fid)
        else:
            self.tables.delete(fid)
        self._free_fids.append(fid)
        self.epoch += 1
        return fid

    def apply_churn(
        self, adds: Sequence[str], removes: Sequence[str]
    ) -> List[int]:
        """One churn tick: batched unsubscribes + subscribes.

        The per-op path costs ~30us of host hashing/placement per
        filter — fine for interactive subscribes, but a 5%/s churn
        against 10M routes is ~500k ops/s (BASELINE config 5).  Here the
        adds' key computation and placement run in one native pass
        (matchhash.cc etpu_filter_keys + etpu_bulk_place_slots) and the
        device mirror still receives a single delta scatter.  Returns
        the fids assigned to `adds`.
        """
        dead_fids: List[int] = []
        for filt in removes:
            fid = self._fids.get(filt)
            if fid is None:
                continue
            self._refs[fid] -= 1
            if self._refs[fid] > 0:
                continue
            del self._refs[fid]
            del self._fids[filt]
            ws = self._words.pop(fid)
            if fid in self._deep_fids:
                self._deep_fids.discard(fid)
                self._deep.delete(filt, fid)
            else:
                dead_fids.append(fid)
            self._free_fids.append(fid)
        if dead_fids:
            self.tables.delete_batch(dead_fids)
        out: List[int] = []
        new_strs: List[str] = []
        new_fids: List[int] = []
        new_words: List[List[str]] = []
        for filt in adds:
            fid = self._fids.get(filt)
            if fid is not None:
                self._refs[fid] += 1
                out.append(fid)
                continue
            ws = topiclib.words(filt)
            fid = self._free_fids.pop() if self._free_fids else self._alloc_fid()
            self._fids[filt] = fid
            self._refs[fid] = 1
            self._words[fid] = ws
            if self._is_deep(ws):
                self._deep.insert(filt, fid)
                self._deep_fids.add(fid)
            else:
                new_strs.append(filt)
                new_fids.append(fid)
                new_words.append(ws)
            out.append(fid)
        if new_strs:
            self.tables.churn_insert(new_strs, new_fids, words=new_words)
        self.epoch += 1
        return out

    def _alloc_fid(self) -> int:
        self._next_fid += 1
        return self._next_fid - 1

    def _is_deep(self, ws: Sequence[str]) -> bool:
        # effective depth = levels minus a trailing '#': cheap length
        # check on the hot subscribe path (no Shape construction)
        plen = len(ws) - (1 if ws and ws[-1] == "#" else 0)
        return plen > self.space.max_levels

    @property
    def n_filters(self) -> int:
        return len(self._fids)

    # --------------------------------------------------------------- sync

    def sync_device(self) -> DeviceTables:
        """Bring the HBM mirror up to date with host truth."""
        delta = self.tables.drain_delta()
        if self._dev is None or delta.rebuilt:
            self._dev = DeviceTables.from_host(self.tables, self.device)
            return self._dev
        if delta.desc_dirty:
            import jax

            put = lambda a: jax.device_put(a, self.device)
            self._dev = self._dev._replace(
                incl=put(self.tables.incl),
                k_a=put(self.tables.k_a),
                k_b=put(self.tables.k_b),
                min_len=put(self.tables.min_len),
                max_len=put(self.tables.max_len),
                wild_root=put(self.tables.wild_root),
                valid=put(self.tables.valid),
            )
        if delta.slots:
            from ..ops.match import apply_delta_packed

            k = _next_pow2(max(len(delta.slots), 16))
            n = len(delta.slots)
            # one [4, K] u32 transfer instead of four puts: each put is a
            # round trip on a tunneled device (slots/vals bit-cast to u32)
            packed = np.zeros((4, k), dtype=np.uint32)
            packed[0] = np.uint32(0xFFFFFFFF)  # slot -1 padding
            packed[0, :n] = np.asarray(delta.slots, dtype=np.int32).view(
                np.uint32
            )
            packed[1, :n] = delta.key_a
            packed[2, :n] = delta.key_b
            packed[3, :n] = np.asarray(delta.val, dtype=np.int32).view(
                np.uint32
            )
            import jax

            self._dev = apply_delta_packed(
                self._dev, jax.device_put(packed, self.device)
            )
        return self._dev

    # -------------------------------------------------------------- match

    def match(self, topics: Sequence[str]) -> List[Set[int]]:
        """Match a publish batch; returns the set of fids per topic.

        Device hits are verified against host truth by default: the
        device compares 2x32-bit lane hashes, so an astronomically-rare
        lane collision between a topic and an unrelated same-shape filter
        would otherwise cause a false delivery.  The reference's trie is
        exact (`emqx_trie.erl:272-334`); `verify_matches` keeps that
        guarantee, counting any discard in `collision_count` /
        `on_collision`."""
        out: List[Set[int]] = [set() for _ in topics]

        if self.tables.n_entries:
            dev = self.sync_device()
            from ..ops.match import prepare_topics_raw

            nb, _n = prepare_topics_raw(self.space, topics, self.min_batch)
            import jax

            batch = TopicBatch(*(jax.device_put(a, self.device) for a in nb))
            matched = np.asarray(self._match_fn(dev, batch))[: len(topics)]
            for i in range(len(topics)):
                row = matched[i]
                hits = row[row >= 0]
                if not hits.size:
                    continue
                if self.verify_matches:
                    good, bad = verify_hits(
                        topiclib.words(topics[i]), hits, self._words
                    )
                    out[i].update(good)
                    self.collision_count += len(bad)
                    if self.on_collision is not None:
                        for fid in bad:
                            self.on_collision(topics[i], fid)
                else:
                    out[i].update(int(f) for f in hits)

        if self._deep_fids:
            for i, t in enumerate(topics):
                out[i] |= self._deep.match(t) & self._deep_fids
        return out

    def match_one(self, name: str) -> Set[int]:
        return self.match([name])[0]
