"""Device-resident retained-message index (subscribe-time wildcard fan-in).

The retainer's lookup direction is the PUBLISH path transposed: one
wildcard filter against many stored concrete topic names
(`emqx_retainer_mnesia.erl` walks a mnesia topic table per subscribe).
Round-3 verdict item 9: this is the same match problem the engine solves
on device, so spend the kernel surplus on it.

Design: stored names live in HBM as per-level hash-term rows (the same
`HashSpace` terms the publish path uses, `ops/hashing.py`).  A lookup
builds the FILTER's shape descriptor host-side (one inclusion row + the
shape constant) and runs ONE masked-sum dispatch over all rows:

    hit[n] = (sum_l terms_a[n,l] * incl[l]) + K_a == filter_key_a
           & (lane b likewise) & length-window & ~($-root wildcard rule)

— a [N, L] contraction, embarrassingly parallel, no trie walk.  Hits are
exact-verified host-side against the stored name strings (the same
two-lane-collision discipline as the publish engine), so delivery
correctness never depends on hash luck.  Churn is slot-wise scatter,
like the route tables; capacity doubles with full re-upload (rare).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional

import numpy as np

from ..broker import topic as topiclib
from ..ops import hashing


@functools.partial(__import__("jax").jit, static_argnames=())
def _retained_match(ta, tb, ln, dl, incl, ka, kb, ta_t, tb_t,
                    min_len, max_len, wild_root):
    import jax.numpy as jnp

    ha = (ta * incl[None, :]).sum(axis=-1, dtype=jnp.uint32) + ka
    hb = (tb * incl[None, :]).sum(axis=-1, dtype=jnp.uint32) + kb
    ok = (
        (ha == ta_t)
        & (hb == tb_t)
        & (ln >= min_len)
        & (ln <= max_len)
        & (ln >= 0)  # occupied slot
        & ~(dl & wild_root)
    )
    return ok


class RetainedDeviceIndex:
    """HBM index of retained topic NAMES; lookup(filter) -> names."""

    def __init__(self, space: Optional[hashing.HashSpace] = None,
                 device=None, cap: int = 1024):
        self.space = space or hashing.HashSpace()
        self.device = device
        L = self.space.max_levels
        self.cap = cap
        self.ta = np.zeros((cap, L), dtype=np.uint32)
        self.tb = np.zeros((cap, L), dtype=np.uint32)
        self.ln = np.full(cap, -1, dtype=np.int32)  # -1 = empty slot
        self.dl = np.zeros(cap, dtype=bool)
        self._topics: List[Optional[str]] = [None] * cap
        self._slot_of: Dict[str, int] = {}
        self._free: List[int] = list(range(cap - 1, -1, -1))
        self._dev = None  # (ta, tb, ln, dl) device arrays
        self._dirty: Optional[set] = set()  # changed slots; None = rebuild
        self.verify_matches = True
        self.collision_count = 0
        self.lookups = 0

    def __len__(self) -> int:
        return len(self._slot_of)

    # ----------------------------------------------------------- mutation

    def insert(self, topic: str) -> None:
        if topic in self._slot_of:
            return
        if not self._free:
            self._grow()
        slot = self._free.pop()
        ws = topiclib.words(topic)
        terms = self.space.topic_terms(ws)
        self.ta[slot] = terms[0]
        self.tb[slot] = terms[1]
        # depth beyond the level cap can't be hashed: deep names are
        # marked with length > any filter's max plen, so device lookups
        # never hit them; the retainer's trie remains their (tiny) path
        self.ln[slot] = len(ws)
        self.dl[slot] = bool(ws) and ws[0].startswith("$")
        self._topics[slot] = topic
        self._slot_of[topic] = slot
        if self._dirty is not None:
            self._dirty.add(slot)

    def delete(self, topic: str) -> None:
        slot = self._slot_of.pop(topic, None)
        if slot is None:
            return
        self.ln[slot] = -1
        self.ta[slot] = 0
        self.tb[slot] = 0
        self.dl[slot] = False
        self._topics[slot] = None
        self._free.append(slot)
        if self._dirty is not None:
            self._dirty.add(slot)

    def _grow(self) -> None:
        old = self.cap
        self.cap *= 2
        L = self.space.max_levels
        for name, fill in (("ta", 0), ("tb", 0), ("ln", -1), ("dl", False)):
            arr = getattr(self, name)
            shape = (self.cap, L) if arr.ndim == 2 else (self.cap,)
            new = np.full(shape, fill, dtype=arr.dtype)
            new[:old] = arr
            setattr(self, name, new)
        self._topics.extend([None] * (self.cap - old))
        self._free.extend(range(self.cap - 1, old - 1, -1))
        self._dirty = None  # shapes changed: full re-upload

    # --------------------------------------------------------- checkpoint

    def export_state(self):
        """(named arrays, meta) for the checkpoint store: term rows plus
        the packed name list (slot-aligned), copied at capture time."""
        from ..checkpoint.store import pack_str_list

        slots = sorted(self._slot_of.values())
        names = [self._topics[s] for s in slots]
        buf, offs = pack_str_list(names)
        arrays = {
            "ta": self.ta.copy(), "tb": self.tb.copy(),
            "ln": self.ln.copy(), "dl": self.dl.copy(),
            "slots": np.asarray(slots, dtype=np.int64),
            "buf": buf, "offs": offs,
        }
        return arrays, {"cap": self.cap, "max_levels": self.space.max_levels}

    def from_state(self, arrays, meta) -> int:
        """Adopt a snapshot wholesale (no re-hashing); the device copy
        is marked for a full re-upload on the next lookup."""
        from ..checkpoint.store import unpack_str_list

        if int(meta["max_levels"]) != self.space.max_levels:
            raise ValueError("retained snapshot max_levels mismatch")
        self.cap = int(meta["cap"])
        self.ta = arrays["ta"]
        self.tb = arrays["tb"]
        self.ln = arrays["ln"]
        self.dl = arrays["dl"]
        names = unpack_str_list(arrays["buf"], arrays["offs"])
        slots = arrays["slots"].tolist()
        self._topics = [None] * self.cap
        self._slot_of = {}
        for name, slot in zip(names, slots):
            self._topics[slot] = name
            self._slot_of[name] = slot
        occupied = set(slots)
        self._free = [
            i for i in range(self.cap - 1, -1, -1) if i not in occupied
        ]
        self._dev = None
        self._dirty = None  # full re-upload
        return len(names)

    # --------------------------------------------------------------- sync

    def _sync(self):
        import jax

        if self._dev is None or self._dirty is None:
            put = lambda a: jax.device_put(a.copy(), self.device)
            self._dev = (put(self.ta), put(self.tb),
                         put(self.ln), put(self.dl))
            self._dirty = set()
        elif self._dirty:
            import jax.numpy as jnp

            slots = np.fromiter(self._dirty, dtype=np.int32,
                                count=len(self._dirty))
            ta, tb, ln, dl = self._dev
            js = jax.device_put(slots, self.device)
            self._dev = (
                ta.at[js].set(jax.device_put(self.ta[slots], self.device)),
                tb.at[js].set(jax.device_put(self.tb[slots], self.device)),
                ln.at[js].set(jax.device_put(self.ln[slots], self.device)),
                dl.at[js].set(jax.device_put(self.dl[slots], self.device)),
            )
            self._dirty = set()
        return self._dev

    # ------------------------------------------------------------- lookup

    def lookup(self, filt: str) -> List[str]:
        """Stored names matching the filter — ONE device dispatch over
        all rows, exact-verified host-side."""
        if not self._slot_of:
            return []
        fw = topiclib.words(filt)
        shape = self.space.shape_of(fw)
        if shape.plen > self.space.max_levels:
            # deeper than the hash space: host fallback over the (small)
            # name list — same escape hatch as the engine's deep filters
            return [t for t in self._slot_of
                    if topiclib.match_words(topiclib.words(t), fw)]
        ha, hb, _ = self.space.filter_key(fw)
        ka, kb = self.space.shape_const(shape)
        L = self.space.max_levels
        incl = np.zeros(L, dtype=np.uint32)
        for l in range(min(shape.plen, L)):
            if not (shape.plus_mask >> l & 1):
                incl[l] = 1
        ta, tb, ln, dl = self._sync()
        import jax

        put = lambda a: jax.device_put(a, self.device)
        ok = np.asarray(_retained_match(
            ta, tb, ln, dl, put(incl),
            np.uint32(ka), np.uint32(kb),  # filter_key includes K
            np.uint32(ha), np.uint32(hb),
            np.int32(shape.min_len()),
            np.int32(min(shape.max_len(L), np.iinfo(np.int32).max)),
            np.bool_(shape.wild_root),
        ))
        self.lookups += 1
        out: List[str] = []
        for slot in np.nonzero(ok)[0].tolist():
            t = self._topics[slot]
            if t is None:  # raced delete between sync and fetch
                continue
            if self.verify_matches and not topiclib.match_words(
                topiclib.words(t), fw
            ):
                self.collision_count += 1
                continue
            out.append(t)
        return out
